"""AOT export/load for jitted train steps.

Two entry points, mirroring the two ways the tree builds train steps:

* :func:`export_train_step` / :func:`load_train_step` — the hapi
  ``Model`` path (``Model._build_jit_step``): forward+backward+fused
  optimizer in one donated XLA program.  The step has TWO signatures
  over its life — the first call takes per-name optimizer state and
  returns it in fused (flat-bucket) form; every later call threads the
  fused form — so the exporter serializes BOTH programs
  (``train_step_init`` / ``train_step``) and the loader dispatches per
  call on the recorded input signature, falling back to a fresh
  ``jax.jit`` (with a telemetry event) for anything else, e.g. a
  restored checkpoint with exotic slot state.

* :func:`export_jit_apply` — the raw ``Optimizer.build_jit_apply``
  fused-apply program, for callers that run their own step loop.

Donation: by default the export donates exactly when a deserialized
donated program is safe on this platform
(:func:`~paddle_tpu.aot.artifact.donation_deserialize_safe`); the
jax-0.4.37 XLA:CPU path exports undonated so its artifacts remain
loadable (identical numerics, double-buffered state).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax

from .artifact import (ArtifactStore, _sig_matches, args_signature,
                       donation_deserialize_safe, fresh_backend_compile)

__all__ = ["export_train_step", "load_train_step", "AotTrainStep",
           "export_jit_apply", "engine_topology_key", "export_engine_step",
           "load_engine_step", "AotEngineStep"]

_INIT = "train_step_init"
_STEADY = "train_step"


def _example_rng():
    """Same aval as ``core.rng.next_rng_key()`` (a folded typed key)
    without advancing the process generator — exporting must not shift
    the training run's RNG stream."""
    return jax.random.fold_in(jax.random.key(0), 0)


def _example_args(model, inputs, labels) -> Tuple:
    """Reconstruct ``Model.train_batch``'s exact jit-step call
    signature from one example batch (first-step form: per-name
    optimizer state)."""
    from ..hapi.model import _np
    inputs = _np(inputs)
    labels = _np(labels)
    params, buffers = model._split_state()
    trainable = {n: params[n]
                 for n, p in model.network.named_parameters()
                 if p.trainable}
    opt_state = model._optimizer.init_state(trainable)
    lr = model._optimizer.get_lr()
    scale = (model._scaler.get_loss_scaling()
             if model._scaler is not None and model._scaler.is_enable()
             else 1.0)
    return (params, buffers, opt_state, model._step_count + 1, lr,
            _example_rng(), scale, inputs, labels)


def train_config(model, args: Tuple) -> Dict[str, Any]:
    td, leaves = args_signature(args)
    return {
        "kind": "hapi_train_step",
        "network": type(model.network).__name__,
        "optimizer": type(model._optimizer).__name__,
        "loss": type(model._loss).__name__ if model._loss else None,
        "skip_nonfinite": bool(model._skip_nonfinite),
        "amp": bool(model._scaler is not None
                    and model._scaler.is_enable()),
        "args_treedef": td,
        "args_leaves": leaves,
    }


def export_train_step(model, inputs, labels, directory: str, *,
                      donate: Optional[bool] = None,
                      rotate: bool = False,
                      keep_last: Optional[int] = None,
                      registry=None) -> ArtifactStore:
    """Trace, lower, compile, and serialize the prepared ``model``'s
    jitted train step for one example batch shape — both the first-step
    (per-name optimizer state) and steady-state (fused state)
    programs.  ``rotate=True`` exports into a fresh generation under a
    rotation ROOT and publishes the atomic ``latest`` pointer
    (``keep_last`` prunes old generations); ``Model.prepare(aot_dir=
    root)`` then follows the pointer."""
    if model._optimizer is None:
        raise ValueError("export_train_step needs a prepared Model "
                         "(call prepare(optimizer=..., loss=...) first)")
    if donate is None:
        donate = donation_deserialize_safe()
    donate_argnums = (0, 1, 2) if donate else ()
    jit_step = model._build_jit_step(donate=donate)
    args_init = _example_args(model, inputs, labels)
    if rotate:
        from .artifact import new_generation
        store = new_generation(directory, registry=registry)
    else:
        store = ArtifactStore(directory, registry=registry)
    store.begin(config=train_config(model, args_init))

    with fresh_backend_compile():
        compiled = jit_step.lower(*args_init).compile()
        store.put(_INIT, compiled, args_init,
                  donate_argnums=donate_argnums)

        # steady state: the fused opt-state layout is whatever the
        # first step RETURNS — take its avals abstractly and compile
        # that program
        fused_sds = jax.eval_shape(jit_step, *args_init)[2]
        args_steady = args_init[:2] + (fused_sds,) + args_init[3:]
        compiled = jit_step.lower(*args_steady).compile()
        store.put(_STEADY, compiled, args_steady,
                  donate_argnums=donate_argnums)
    if rotate:
        store.publish(keep_last=keep_last)
    return store


class AotTrainStep:
    """Drop-in for ``Model._jit_step``: dispatches each call to the
    deserialized executable whose recorded input signature matches,
    fresh-compiling (once, with a telemetry event) for anything the
    artifacts don't cover."""

    def __init__(self, model, store: ArtifactStore):
        self._model = model
        self._store = store
        self._entries = []
        for name in (_INIT, _STEADY):
            self._entries.append((store.entry(name)["in_sig"],
                                  store.get(name)))
        self._fresh = None

    def __call__(self, *args):
        for sig, fn in self._entries:
            if _sig_matches(sig, args):
                return fn(*args)
        if self._fresh is None:
            self._store._event("signature_fallback",
                               name="train_step")
            self._fresh = self._model._build_jit_step()
        return self._fresh(*args)


def load_train_step(model, directory: str, *, registry=None
                    ) -> AotTrainStep:
    """Verify + deserialize the train-step artifacts for ``model``
    (``directory`` may be a rotation root — the ``latest`` pointer is
    followed).  Raises an AotError subclass (skew/corrupt/donation-
    refused) — the Model falls back to a fresh ``jax.jit``."""
    from .artifact import resolve_artifact_dir
    store = ArtifactStore(resolve_artifact_dir(directory),
                          registry=registry)
    store.check_env()
    return AotTrainStep(model, store)


# ----------------------------------------------------------------------
# per-topology DistributedEngine steps (ISSUE 17 elastic training)
# ----------------------------------------------------------------------
_ENGINE_PREFIX = "engine_step@"


def engine_topology_key(topo) -> str:
    """Stable artifact-entry key for a mesh, e.g.
    ``pp1-dp4-sharding1-sep1-mp1@d0.1.2.3`` — one AOT store holds one
    entry per mesh the elastic trainer has ever run at, so a resume at
    ANY previously-seen mesh is a pure deserialize.  The key includes
    the device ids, not just the axis degrees: a serialized executable
    bakes in its device assignment, and a dp3 mesh over survivors
    {0,1,3} cannot serve a dp3 mesh over {0,1,2}."""
    from ..parallel.topology import AXIS_ORDER
    degrees = "-".join(f"{a}{topo.axis_size(a)}" for a in AXIS_ORDER)
    devs = ".".join(str(d.id) for d in topo.mesh.devices.flat)
    return f"{degrees}@d{devs}"


def engine_config(engine) -> Dict[str, Any]:
    """Store-level config for an engine-step artifact store.  Deliberately
    topology-free: topologies live in the per-entry names, so a reshape
    EXTENDS the store instead of invalidating it."""
    return {
        "kind": "engine_train_step",
        "network": type(engine.network).__name__,
        "optimizer": type(engine.optimizer).__name__,
        "loss": (type(engine.loss_fn).__name__
                 if engine.loss_fn is not None else None),
        "sharding_stage": engine.sharding_stage,
        "amp": engine.amp_dtype,
        "skip_nonfinite": bool(engine.skip_nonfinite),
    }


def _engine_example_args(engine, inputs, labels) -> Tuple:
    """The exact ``DistributedEngine._step_fn`` call signature for one
    example batch (state must already be sharded)."""
    params, buffers, opt_state = engine._state
    inputs_p, labels_p = engine.place_batch(inputs, labels)
    lr = engine.optimizer.get_lr()
    return (params, buffers, opt_state, engine._step_count + 1, lr,
            _example_rng(), inputs_p, labels_p)


def export_engine_step(engine, inputs, labels, directory: str, *,
                       donate: Optional[bool] = None,
                       registry=None):
    """Compile + serialize ``engine``'s SPMD train step under its
    topology's entry name.  An existing store is EXTENDED (other
    topologies' entries are kept), so the elastic trainer accumulates
    one entry per mesh it reshapes through.  Returns ``(store,
    compiled)`` — the freshly compiled executable is handed back so the
    caller can install it directly and the export costs no second
    compile."""
    if donate is None:
        donate = donation_deserialize_safe()
    if engine._state is None:
        engine.shard_state()
    jitted = engine.build_train_step(donate=donate)
    args = _engine_example_args(engine, inputs, labels)
    store = ArtifactStore(directory, registry=registry)
    if store.exists():
        store.extend()
    else:
        store.begin(config=engine_config(engine))
    name = _ENGINE_PREFIX + engine_topology_key(engine.topo)
    with fresh_backend_compile():
        compiled = jitted.lower(*args).compile()
    store.put(name, compiled, args,
              donate_argnums=(0, 1, 2) if donate else ())
    return store, compiled


class AotEngineStep:
    """Drop-in for ``DistributedEngine._step_fn``: runs the deserialized
    executable while the call signature matches the recorded one,
    fresh-jitting (once, with a telemetry event) on divergence — e.g. a
    batch-shape change the artifacts don't cover."""

    def __init__(self, engine, store: ArtifactStore, sig, fn):
        self._engine = engine
        self._store = store
        self._sig = sig
        self._fn = fn
        self._fresh = None

    def __call__(self, *args):
        if self._fresh is None and _sig_matches(self._sig, args):
            return self._fn(*args)
        if self._fresh is None:
            self._store._event("signature_fallback", name="engine_step")
            # build_train_step re-points engine._step_fn at the fresh
            # jit, so later train_batch calls skip this dispatch
            self._fresh = self._engine.build_train_step()
        return self._fresh(*args)


def load_engine_step(engine, directory: str, *, registry=None
                     ) -> AotEngineStep:
    """Verify + deserialize the engine-step entry matching ``engine``'s
    CURRENT topology.  Raises an AotError subclass when the store, this
    environment, or this topology's entry is unusable — callers fall
    back to a fresh jit (one bounded compile)."""
    from .artifact import resolve_artifact_dir
    store = ArtifactStore(resolve_artifact_dir(directory),
                          registry=registry)
    store.check_env()
    store.check_config(engine_config(engine))
    name = _ENGINE_PREFIX + engine_topology_key(engine.topo)
    entry = store.entry(name)
    return AotEngineStep(engine, store, entry["in_sig"], store.get(name))


def export_jit_apply(opt, params, grads, state, directory: str, *,
                     lr=1e-3, step: int = 1,
                     donate: Optional[bool] = None,
                     registry=None) -> ArtifactStore:
    """Serialize ``Optimizer.build_jit_apply``'s fused-apply program at
    the given (params, grads, state) signature — the raw-step-loop
    analog of :func:`export_train_step`."""
    if donate is None:
        donate = donation_deserialize_safe()
    fused = opt.build_jit_apply(donate=donate)
    args = (params, grads, state, lr, step)
    store = ArtifactStore(directory, registry=registry)
    td, leaves = args_signature(args)
    store.begin(config={"kind": "fused_jit_apply",
                        "optimizer": type(opt).__name__,
                        "args_treedef": td, "args_leaves": leaves})
    with fresh_backend_compile():
        compiled = fused.lower(*args).compile()
    store.put("jit_apply", compiled, args,
              donate_argnums=(0, 1, 2) if donate else ())
    return store
