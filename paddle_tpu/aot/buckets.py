"""Declared shape buckets for the serving engine (ISSUE 6).

The continuous-batching engine's decode step is already shape-static
([max_batch] everything), but prefill length varies per request — the
legacy path jits one program per distinct prompt length, which is
exactly the per-host compile storm AOT exists to kill.  A
:class:`ShapeBucketRegistry` declares a fixed set of prefill CHUNK
lengths; any prompt (or prefix-cache suffix) is decomposed into a
sequence of declared chunks, the last one zero-padded to its bucket,
so variable load always lands on one of ``len(chunk_sizes)``
precompiled executables.

Decomposition is greedy largest-first; a remainder smaller than the
smallest bucket pads the smallest bucket.  A chunk whose ``valid``
count equals its bucket size is a HIT; a padded chunk is a MISS (the
pad fraction is wasted compute) — both are counted so bench rows and
telemetry can report bucket efficiency, and misses tell you which
bucket to add next.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ShapeBucketRegistry", "DEFAULT_CHUNK_BUCKETS"]

#: chunk lengths that cover short prompts exactly and long prompts with
#: <= smallest-bucket padding waste per request
DEFAULT_CHUNK_BUCKETS = (16, 64, 256)


class ShapeBucketRegistry:
    """Declared (chunk_sizes, max_batch) serve buckets + hit/miss
    accounting.  ``max_batch`` rides along so an artifact manifest can
    refuse an engine whose decode batch differs from the exported one."""

    def __init__(self, chunk_sizes, max_batch: Optional[int] = None):
        sizes = sorted({int(c) for c in chunk_sizes})
        if not sizes or sizes[0] < 1:
            raise ValueError(f"chunk_sizes must be positive: {chunk_sizes}")
        self.chunk_sizes: Tuple[int, ...] = tuple(sizes)
        self.max_batch = None if max_batch is None else int(max_batch)
        self.hits = 0
        self.misses = 0
        self.padded_tokens = 0

    def plan_chunks(self, n: int) -> List[Tuple[int, int]]:
        """Decompose a prefill of ``n`` tokens into [(bucket, valid)]
        with sum(valid) == n and every bucket declared.  Updates the
        hit/miss counters."""
        if n < 1:
            raise ValueError("cannot plan an empty prefill")
        out: List[Tuple[int, int]] = []
        rem = n
        while rem > 0:
            size = self.chunk_sizes[0]
            for c in reversed(self.chunk_sizes):
                if c <= rem:
                    size = c
                    break
            valid = min(size, rem)
            out.append((size, valid))
            rem -= valid
            if valid == size:
                self.hits += 1
            else:
                self.misses += 1
                self.padded_tokens += size - valid
        return out

    def stats(self) -> Dict[str, int]:
        return {"bucket_hits": self.hits, "bucket_misses": self.misses,
                "bucket_padded_tokens": self.padded_tokens}

    # -- manifest round-trip -------------------------------------------
    def to_manifest(self) -> Dict[str, Any]:
        return {"chunk_sizes": list(self.chunk_sizes),
                "max_batch": self.max_batch}

    @classmethod
    def from_manifest(cls, m: Dict[str, Any]) -> "ShapeBucketRegistry":
        return cls(m["chunk_sizes"], max_batch=m.get("max_batch"))
