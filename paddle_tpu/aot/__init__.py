"""AOT compile-artifact subsystem (ISSUE 6).

Serialize traced+lowered+compiled XLA executables once, warm-start
every other process from the artifact directory:

* :mod:`~paddle_tpu.aot.artifact` — the versioned, CRC'd store with an
  environment/config manifest and the jax-0.4.37 donated-deserialize
  gate;
* :mod:`~paddle_tpu.aot.buckets` — declared serve shape buckets, so
  variable prefill load lands on precompiled programs;
* :mod:`~paddle_tpu.aot.serve` — export/load for the continuous-
  batching engine (``ContinuousBatchingEngine(aot_dir=...)``);
* :mod:`~paddle_tpu.aot.train` — export/load for the hapi jitted train
  step (``Model.prepare(aot_dir=...)``) and the raw fused
  ``build_jit_apply`` program.

The recompile-budget ratchet over this subsystem lives in
``tools/compile_budget.py`` + ``COMPILE_BUDGET.md``; see
``docs/aot.md`` for the artifact layout and policies.
"""

from .artifact import (LATEST_POINTER, AotArtifactCorruptError,
                       AotDonationError, AotError,
                       AotManifestMismatchError, ArtifactStore,
                       args_signature, config_hash,
                       donation_deserialize_safe, environment_fingerprint,
                       export_compiled, new_generation,
                       resolve_artifact_dir)
from .buckets import DEFAULT_CHUNK_BUCKETS, ShapeBucketRegistry
from .serve import engine_config, export_engine, load_engine_artifacts
from .train import (AotTrainStep, export_jit_apply, export_train_step,
                    load_train_step)

__all__ = [
    "AotError", "AotArtifactCorruptError", "AotManifestMismatchError",
    "AotDonationError", "ArtifactStore", "args_signature", "config_hash",
    "donation_deserialize_safe", "environment_fingerprint",
    "export_compiled", "new_generation", "resolve_artifact_dir",
    "LATEST_POINTER",
    "DEFAULT_CHUNK_BUCKETS", "ShapeBucketRegistry",
    "engine_config", "export_engine", "load_engine_artifacts",
    "AotTrainStep", "export_jit_apply", "export_train_step",
    "load_train_step",
]
