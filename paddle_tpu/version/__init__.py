"""paddle.version parity (reference generated python/paddle/version).

The capability target is the reference snapshot's API line; the version
numbers mirror that claim with a TPU-build local tag."""

full_version = "3.0.0+tpu"
major = "3"
minor = "0"
patch = "0"
rc = "0"
commit = "paddle-tpu"
istaged = False
with_pip_cuda_libraries = "OFF"

cuda_version = "False"
cudnn_version = "False"
xpu_version = "False"
xpu_xccl_version = "False"
xpu_xhpc_version = "False"
tensorrt_version = "False"
cinn_version = "False"

__all__ = ["cuda", "cudnn", "nccl", "show", "xpu", "xpu_xccl", "xpu_xhpc"]


def show() -> None:
    print(f"full_version: {full_version}")
    print(f"commit: {commit}")
    print("tpu: True (jax/XLA backend)")


def cuda():
    return False


def cudnn():
    return False


def nccl():
    return 0


def xpu():
    return False


def xpu_xccl():
    return False


def xpu_xhpc():
    return False
