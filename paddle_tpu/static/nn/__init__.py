"""paddle.static.nn — build-time layer functions for static programs
(reference python/paddle/static/nn/__init__.py).

TPU-native: each function creates its Parameters eagerly (so they land in
the recorded Program as live refs — see static/__init__.py) and routes the
math through the ordinary functional ops, which record nodes when handed
symbolic Variables.  The LoD `sequence_*` family needs variable-length
LoD semantics the recording design intentionally dropped (SURVEY §7 — pad
+ mask is the TPU idiom); those raise with that guidance.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ...core.tensor import Parameter

__all__ = [
    "fc", "embedding", "conv2d", "conv3d", "conv2d_transpose",
    "conv3d_transpose", "batch_norm", "layer_norm", "group_norm",
    "instance_norm", "data_norm", "spectral_norm", "prelu",
    "bilinear_tensor_product", "deform_conv2d", "row_conv", "nce",
    "sparse_embedding", "cond", "case", "switch_case", "while_loop",
    "static_pylayer", "py_func", "sequence_conv", "sequence_pool",
    "sequence_softmax", "sequence_pad", "sequence_unpad",
    "sequence_expand", "sequence_expand_as", "sequence_first_step",
    "sequence_last_step", "sequence_reshape", "sequence_scatter",
    "sequence_slice", "sequence_enumerate",
]


def _mk_param(shape, dtype="float32", is_bias=False, name=None):
    from ... import create_parameter
    return create_parameter(list(shape), dtype, is_bias=is_bias, name=name)


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Reference static.nn.fc: flatten trailing dims, linear, optional
    activation."""
    from ...nn import functional as F
    from ...ops import api

    in_dim = int(np.prod([d for d in x.shape[num_flatten_dims:]]))
    w = _mk_param((in_dim, size))
    b = None if bias_attr is False else _mk_param((size,), is_bias=True)
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        h = api.reshape(h, list(x.shape[:num_flatten_dims]) + [in_dim])
    out = F.linear(h, w, b)
    if activation:
        out = getattr(api, activation)(out)
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    from ...nn import functional as F
    w = _mk_param(size, dtype)
    return F.embedding(input, w, padding_idx=padding_idx)


sparse_embedding = embedding


def conv2d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCHW"):
    from ...nn import functional as F
    from ...ops import api
    fs = (filter_size,) * 2 if isinstance(filter_size, int) else \
        tuple(filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _mk_param((num_filters, int(cin) // groups) + fs)
    b = None if bias_attr is False else _mk_param((num_filters,),
                                                  is_bias=True)
    out = F.conv2d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    if act:
        out = getattr(api, act)(out)
    return out


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           use_cudnn=True, act=None, name=None, data_format="NCDHW"):
    from ...nn import functional as F
    from ...ops import api
    fs = (filter_size,) * 3 if isinstance(filter_size, int) else \
        tuple(filter_size)
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    w = _mk_param((num_filters, int(cin) // groups) + fs)
    b = None if bias_attr is False else _mk_param((num_filters,),
                                                  is_bias=True)
    out = F.conv3d(input, w, bias=b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    if act:
        out = getattr(api, act)(out)
    return out


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCHW"):
    from ...nn import functional as F
    from ...ops import api
    fs = (filter_size,) * 2 if isinstance(filter_size, int) else \
        tuple(filter_size)
    cin = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _mk_param((int(cin), num_filters // groups) + fs)
    b = None if bias_attr is False else _mk_param((num_filters,),
                                                  is_bias=True)
    out = F.conv2d_transpose(input, w, bias=b, stride=stride,
                             padding=padding, groups=groups,
                             output_size=output_size,
                             data_format=data_format)
    if act:
        out = getattr(api, act)(out)
    return out


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format="NCDHW"):
    from ...nn import functional as F
    fs = (filter_size,) * 3 if isinstance(filter_size, int) else \
        tuple(filter_size)
    cin = input.shape[1] if data_format == "NCDHW" else input.shape[-1]
    w = _mk_param((int(cin), num_filters // groups) + fs)
    b = None if bias_attr is False else _mk_param((num_filters,),
                                                  is_bias=True)
    return F.conv3d_transpose(input, w, bias=b, stride=stride,
                              padding=padding, data_format=data_format)


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               do_model_average_for_mean_and_var=True, use_global_stats=False):
    from ...nn import functional as F
    from ...ops import api
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    w = _mk_param((c,))
    b = _mk_param((c,), is_bias=True)
    mean = _mk_param((c,))
    var = _mk_param((c,))
    mean.trainable = False
    var.trainable = False
    out = F.batch_norm(input, mean, var, weight=w, bias=b,
                       training=not is_test, momentum=momentum,
                       epsilon=epsilon, data_format=data_layout)
    if act:
        out = getattr(api, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    from ...nn import functional as F
    from ...ops import api
    from ... import create_parameter
    from ...nn import initializer as I
    norm_shape = tuple(int(d) for d in input.shape[begin_norm_axis:])
    # reference defaults: scale init Constant(1.0), bias Constant(0.0)
    w = create_parameter(list(norm_shape), "float32",
                         default_initializer=I.Constant(1.0)) \
        if scale else None
    b = create_parameter(list(norm_shape), "float32", is_bias=True) \
        if shift else None
    out = F.layer_norm(input, norm_shape, weight=w, bias=b, epsilon=epsilon)
    if act:
        out = getattr(api, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-5, param_attr=None,
               bias_attr=None, act=None, data_layout="NCHW", name=None):
    from ...nn import functional as F
    from ...ops import api
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    w = _mk_param((c,))
    b = _mk_param((c,), is_bias=True)
    out = F.group_norm(input, groups, weight=w, bias=b, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(api, act)(out)
    return out


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    from ...nn import functional as F
    c = input.shape[1]
    w = _mk_param((c,)) if param_attr is not False else None
    b = _mk_param((c,), is_bias=True) if bias_attr is not False else None
    return F.instance_norm(input, weight=w, bias=b, eps=epsilon)


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              enable_scale_and_shift=False, name=None, **kw):
    """Reference data_norm: normalization by accumulated batch statistics
    (PS-era); maps to instance-free batch normalization over dim 0."""
    from ...ops import api
    mean = api.mean(input, 0, True)
    var = api.mean((input - mean) ** 2, 0, True)
    out = (input - mean) / api.sqrt(var + epsilon)
    if act:
        out = getattr(api, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ...nn import functional as F
    return F.spectral_norm(weight, dim=dim, power_iters=power_iters,
                           eps=eps) if hasattr(F, "spectral_norm") else \
        _spectral_norm_impl(weight, dim, power_iters, eps)


def _spectral_norm_impl(weight, dim, power_iters, eps):
    from ...core.dispatch import run_op
    import jax.numpy as jnp

    def impl(w):
        mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((mat.shape[0],), mat.dtype) / np.sqrt(mat.shape[0])
        for _ in range(max(power_iters, 1)):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ mat @ v
        return w / sigma

    return run_op("spectral_norm", impl, (weight,), {})


def prelu(x, mode="all", param_attr=None, data_format="NCHW", name=None):
    from ...ops import api
    if mode == "all":
        alpha = _mk_param((1,))
    elif mode == "channel":
        c = x.shape[1] if data_format == "NCHW" else x.shape[-1]
        alpha = _mk_param((c,))
    else:
        alpha = _mk_param([int(np.prod(x.shape[1:]))])
    return api.prelu(x, alpha)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    from ...nn import functional as F
    w = _mk_param((size, int(x.shape[-1]), int(y.shape[-1])))
    b = None if bias_attr is False else _mk_param((size,), is_bias=True)
    return F.bilinear(x, y, w, b)


def deform_conv2d(input, offset, mask, num_filters, filter_size,
                  stride=1, padding=0, dilation=1, groups=1,
                  deformable_groups=1, im2col_step=1, param_attr=None,
                  bias_attr=None, name=None):
    """Static deformable conv: creates the weight/bias params and runs
    the vision.ops kernel (reference static.nn.deform_conv2d)."""
    from ...vision.ops import deform_conv2d as _dcn
    fs = (filter_size,) * 2 if isinstance(filter_size, int) else \
        tuple(filter_size)
    cin = int(input.shape[1])
    w = _mk_param((num_filters, cin // groups) + fs)
    b = None if bias_attr is False else _mk_param((num_filters,),
                                                  is_bias=True)
    return _dcn(input, offset, w, bias=b, stride=stride, padding=padding,
                dilation=dilation, deformable_groups=deformable_groups,
                groups=groups, mask=mask)


def row_conv(input, future_context_size, param_attr=None, act=None):
    """Reference row_conv (lookahead conv for streaming ASR)."""
    from ...core.dispatch import run_op
    import jax.numpy as jnp
    d = int(input.shape[-1])
    w = _mk_param((future_context_size + 1, d))

    def impl(x, wv):
        t = x.shape[-2]
        outs = 0.0
        for k in range(future_context_size + 1):
            shifted = jnp.roll(x, -k, axis=-2)
            mask = (jnp.arange(t) + k < t).astype(x.dtype)
            outs = outs + shifted * mask[..., :, None] * wv[k]
        return outs

    return run_op("row_conv", impl, (input, w), {})


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None, name=None,
        sampler="uniform", custom_dist=None, seed=0, is_sparse=False):
    raise NotImplementedError(
        "nce: PS-era negative sampling head; use "
        "nn.functional.margin_cross_entropy or sampled softmax via "
        "class_center_sample (SURVEY §7 parameter-server non-goal)")


# control flow: under the recording design these run eagerly at build
# time on Variables via lax constructs inside ops; expose the dygraph
# equivalents (which ARE jit-compatible) for parity
def cond(pred, true_fn=None, false_fn=None, name=None,
         return_names=None):
    """Control-flow cond (reference static.nn.cond — NOT paddle.cond the
    matrix condition number, whose name this used to collide with):
    concrete predicates branch in Python; traced predicates lower to
    ``lax.cond``; a record-mode Variable predicate records BOTH branches
    and multiplexes with ``where`` (both branches' side effects run —
    the dense analog of the reference's sub-block select)."""
    from ... import static as _static
    if isinstance(pred, _static.Variable):
        import jax
        t_out = true_fn()
        f_out = false_fn() if false_fn is not None else None
        if f_out is None:
            return t_out
        from ...ops import api as _api

        def _sel(t, f):
            nd = len(getattr(t, "shape", ()))
            p = _api.reshape(_api.cast(pred, "bool"), [1] * nd) if nd \
                else _api.cast(pred, "bool")
            return _api.where(_api.broadcast_to(p, list(t.shape)), t, f)

        if isinstance(t_out, (tuple, list)):
            return type(t_out)(_sel(t, f) for t, f in zip(t_out, f_out))
        return _sel(t_out, f_out)
    from ...jit.dy2static import convert_to_bool
    b = convert_to_bool(pred)
    if isinstance(b, bool):
        return true_fn() if b else (false_fn() if false_fn else None)
    import jax
    return jax.lax.cond(b, lambda _: true_fn(),
                        lambda _: false_fn() if false_fn else None, None)


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        if bool(getattr(pred, "_value", pred)):
            return fn()
    return default() if default else None


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = int(getattr(branch_index, "_value", branch_index))
    table = dict(branch_fns) if not isinstance(branch_fns, dict) \
        else branch_fns
    fn = table.get(idx, default)
    return fn() if fn else None


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Reference static while_loop → eager loop over Tensors (each body
    iteration is jit-cached op dispatch; data-dependent trip counts
    cannot live inside one XLA program by design)."""
    vars_ = list(loop_vars)
    while bool(getattr(cond(*vars_), "_value", cond(*vars_))):
        out = body(*vars_)
        vars_ = list(out) if isinstance(out, (list, tuple)) else [out]
    return vars_


def static_pylayer(forward_fn, inputs, backward_fn=None, name=None):
    from ...autograd.py_layer import PyLayer

    class _P(PyLayer):
        @staticmethod
        def forward(ctx, *xs):
            return forward_fn(*xs)

        @staticmethod
        def backward(ctx, *gs):
            if backward_fn is None:
                raise RuntimeError("static_pylayer without backward_fn "
                                   "cannot be differentiated")
            return backward_fn(*gs)

    return _P.apply(*inputs)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    from ..extras import py_func as _pf
    return _pf(func, x, out, backward_func, skip_vars_in_backward_input)


def _sequence_unsupported(name):
    def fn(*a, **k):
        raise NotImplementedError(
            f"static.nn.{name}: LoD sequence ops are out of scope on TPU "
            "(variable-length rows break static shapes); use padded "
            "tensors + masks, e.g. nn.functional.sequence_mask + the "
            "varlen flash-attention path (SURVEY §7)")
    fn.__name__ = name
    return fn


sequence_conv = _sequence_unsupported("sequence_conv")
sequence_pool = _sequence_unsupported("sequence_pool")
sequence_softmax = _sequence_unsupported("sequence_softmax")
sequence_pad = _sequence_unsupported("sequence_pad")
sequence_unpad = _sequence_unsupported("sequence_unpad")
sequence_expand = _sequence_unsupported("sequence_expand")
sequence_expand_as = _sequence_unsupported("sequence_expand_as")
sequence_first_step = _sequence_unsupported("sequence_first_step")
sequence_last_step = _sequence_unsupported("sequence_last_step")
sequence_reshape = _sequence_unsupported("sequence_reshape")
sequence_scatter = _sequence_unsupported("sequence_scatter")
sequence_slice = _sequence_unsupported("sequence_slice")
sequence_enumerate = _sequence_unsupported("sequence_enumerate")
