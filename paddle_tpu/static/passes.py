"""Static-graph pass essentials (VERDICT r3 item 8).

Reference: python/paddle/distributed/passes/ — the 21-pass zoo over
Program IR.  Two are load-bearing for training and land here, reshaped
for the recorded-Program design:

* ``apply_amp_pass`` — the auto_parallel_amp/fp16 analog.  The reference
  inserts cast ops around white/black-list ops in the ProgramDesc; here
  each recorded node's ``call`` is wrapped with the same white/black
  policy (core/amp_state lists), so the casts trace into the one XLA
  program at replay.  Gradients flow through the casts (jax.grad of the
  replay), landing in fp32 on the fp32 master params — AMP-with-master-
  weights exactly like the reference pass pair (amp + master_grad).

* ``apply_gradient_merge_pass`` — the auto_parallel_gradient_merge
  analog.  The reference rewrites the program to accumulate grads into
  persistable buffers and gates the optimizer block on a step counter;
  here the Executor's train step IS the optimizer application site, so
  the pass marks the program and the Executor accumulates grads across
  ``k_steps`` runs, applying the (averaged) update on every k-th —
  identical update math, no IR surgery.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from ..core.amp_state import BLACK_LIST, WHITE_LIST

__all__ = ["apply_amp_pass", "apply_gradient_merge_pass"]


def _cast_wrapper(call, tgt):
    def wrapped(dyn):
        cast = [v.astype(tgt) if hasattr(v, "dtype")
                and jnp.issubdtype(jnp.asarray(v).dtype, jnp.floating)
                and jnp.asarray(v).dtype != tgt else v
                for v in dyn]
        return call(cast)
    return wrapped


def apply_amp_pass(program, level: str = "O1", dtype=jnp.bfloat16,
                   custom_white_list=None, custom_black_list=None):
    """Rewrite ``program`` IN PLACE so white-list ops (matmuls/convs)
    compute in ``dtype`` and black-list ops (softmax/norms/reductions)
    in fp32; returns the program.  ``level="O2"`` runs everything except
    the black list in ``dtype``."""
    if level not in ("O1", "O2"):
        raise ValueError(f"amp pass level must be O1/O2, got {level!r}")
    white = set(custom_white_list) if custom_white_list is not None \
        else set(WHITE_LIST)
    black = set(custom_black_list) if custom_black_list is not None \
        else set(BLACK_LIST)
    for node in program.nodes:
        base = node.name.split("_\n")[0]
        if base in black:
            tgt = jnp.float32
        elif base in white or level == "O2":
            tgt = dtype
        else:
            continue                      # gray ops follow their inputs
        node.call = _cast_wrapper(node.call, tgt)
        if tgt == dtype:
            for ov in node.out_vars:
                if jnp.issubdtype(jnp.dtype(ov.dtype), jnp.floating):
                    ov.dtype = jnp.dtype(dtype)
    program._amp_level = level
    return program


def apply_gradient_merge_pass(program, k_steps: int,
                              avg: bool = True):
    """Mark ``program`` for k-step gradient accumulation: the Executor's
    train loop adds grads across ``k_steps`` consecutive ``run()`` calls
    and applies the optimizer once per window (averaged when ``avg``) —
    reference auto_parallel_gradient_merge semantics."""
    if k_steps < 1:
        raise ValueError(f"k_steps must be >= 1, got {k_steps}")
    program._grad_merge_k = int(k_steps)
    program._grad_merge_avg = bool(avg)
    return program
