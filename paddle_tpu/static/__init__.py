"""paddle.static parity: Program / program_guard / data / Executor
(reference python/paddle/static/__init__.py, framework.Program,
executor.Executor — SURVEY §1 layer 3).

TPU-first design: a Program is a RECORDED op graph, not an IR.  Under
``enable_static`` + ``program_guard``, every framework op that touches a
symbolic :class:`Variable` appends a node (shape/dtype inferred with
``jax.eval_shape``) instead of executing.  ``Executor.run`` replays the
recording as one pure function of the feeds and ``jax.jit``s it — the
Program/Executor pair collapses onto XLA exactly like ``jit.to_static``,
but through the reference's build-then-run API shape.

Supported surface: inference programs (data → ops → fetch) AND the
static training loop — ``optimizer.minimize(loss)`` under
``program_guard`` registers the optimizer on the Program, and
``Executor.run`` then executes one fused jitted step: loss +
``jax.value_and_grad`` over the Parameter slots + the optimizer's pure
``apply_gradients``, writing updated weights back to the live
Parameter boxes (reference: base/backward.py append_backward +
optimizer ops + PirInterpreter, collapsed into one XLA program).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch as _dispatch
from ..core.tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "Variable",
           "InputSpec", "CPUPlace", "CUDAPlace", "TPUPlace",
           "append_backward"]


class Variable:
    """Symbolic tensor inside a Program (reference framework.Variable):
    knows shape/dtype, produced by a recorded node or a ``data`` feed."""

    def __init__(self, program: "Program", name: str, shape, dtype):
        self.program = program
        self.name = name
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.stop_gradient = True

    @property
    def ndim(self):
        return len(self.shape)

    def aval(self):
        concrete = tuple(1 if d in (None, -1) else int(d)
                         for d in self.shape)
        return jax.ShapeDtypeStruct(concrete, self.dtype)

    def __repr__(self):
        return f"Variable(name={self.name!r}, shape={self.shape}, " \
               f"dtype={self.dtype.name})"

    # arithmetic sugar routes through the recorded ops
    def _binop(self, op, other, swap=False):
        from ..ops import api
        return getattr(api, op)(other, self) if swap \
            else getattr(api, op)(self, other)

    def __add__(self, o):
        return self._binop("add", o)

    def __radd__(self, o):
        return self._binop("add", o, swap=True)

    def __sub__(self, o):
        return self._binop("subtract", o)

    def __mul__(self, o):
        return self._binop("multiply", o)

    def __rmul__(self, o):
        return self._binop("multiply", o, swap=True)

    def __matmul__(self, o):
        return self._binop("matmul", o)

    def __truediv__(self, o):
        return self._binop("divide", o)

    def __rtruediv__(self, o):
        return self._binop("divide", o, swap=True)

    def __rsub__(self, o):
        return self._binop("subtract", o, swap=True)

    def __neg__(self):
        from ..ops import api
        return api.neg(self)

    def __pow__(self, o):
        return self._binop("pow", o)

    def __lt__(self, o):
        return self._binop("less_than", o)

    def __le__(self, o):
        return self._binop("less_equal", o)

    def __gt__(self, o):
        return self._binop("greater_than", o)

    def __ge__(self, o):
        return self._binop("greater_equal", o)


class _Node:
    __slots__ = ("name", "call", "in_vars", "const_args", "out_vars",
                 "statics")

    def __init__(self, name, call, in_vars, const_args, out_vars,
                 statics=()):
        self.name = name            # op name (completion-pass rule lookup)
        self.call = call            # fn(dyn_values_list) -> outputs
        self.in_vars = in_vars      # Variable inputs, positional in call
        self.const_args = const_args
        self.out_vars = out_vars
        self.statics = list(statics)  # non-tensor args (axis/perm/...)


class Program:
    """An ordered recording of op nodes (reference framework.Program;
    blocks/ops collapse to one linear node list — control flow inside a
    recorded op is a lax construct, not a sub-block)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.nodes: List[_Node] = []
        self.feeds: Dict[str, Variable] = {}
        self.params: Dict[str, Any] = {}    # name -> live Parameter box
        self._loss: Optional[Variable] = None
        self._optimizer = None
        self._name_i = 0

    def _fresh(self, prefix="tmp"):
        self._name_i += 1
        return f"{prefix}_{self.id}_{self._name_i}"

    def global_block(self):
        return self

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.nodes = list(self.nodes)
        p.feeds = dict(self.feeds)
        p.params = dict(self.params)
        return p

    # ---- recording hook used by core.dispatch ----
    def record(self, name, call, markers, consts, out_avals, out_treedef,
               statics=()):
        """Append a node.  ``markers``: per-dynamic-slot Variable,
        Parameter (live box), or None (None slots read from ``consts``
        in order at replay).  ``statics``: the op's non-tensor arguments
        (axis, perm, ...) for the completion pass."""
        from ..core.tensor import Parameter
        for m in markers:
            if isinstance(m, Parameter):
                self.params.setdefault(m.name, m)
        outs = [Variable(self, self._fresh(name), a.shape, a.dtype)
                for a in out_avals]
        self.nodes.append(_Node(name, call, markers, consts, outs, statics))
        return jax.tree.unflatten(out_treedef, outs)

    # ---- replay ----
    def build_fn(self, fetch_vars: Sequence[Variable]):
        """Replay as ``run(feed_values, param_values=None)``.  Parameters
        read from ``param_values`` (name -> array) when given — the static
        training path differentiates wrt that dict — else from the live
        Parameter boxes (inference replay sees updated weights)."""
        from ..core.tensor import Parameter
        feed_names = list(self.feeds)

        # prune to the fetch subgraph (reference Program pruning /
        # normalize_program): walk producers backward from the fetches so
        # dead branches (e.g. the loss side of a train program when only
        # the prediction is fetched) neither execute nor demand feeds
        needed = {id(v) for v in fetch_vars}
        live_nodes = []
        for node in reversed(self.nodes):
            if any(id(ov) in needed for ov in node.out_vars):
                live_nodes.append(node)
                for v in node.in_vars:
                    if isinstance(v, Variable) and not isinstance(
                            v, Parameter):
                        needed.add(id(v))
        live_nodes.reverse()

        def run(feed_values: Dict[str, Any], param_values=None):
            env: Dict[int, Any] = {}
            for n in feed_names:
                # bind only supplied feeds: a fetch subgraph (e.g. the
                # inference slice of a train program) may not consume
                # every recorded feed; truly-needed misses surface below
                # as used-before-definition
                if n in feed_values:
                    env[id(self.feeds[n])] = jnp.asarray(feed_values[n])
            for node in live_nodes:
                dyn = []
                it_const = iter(node.const_args)
                for v in node.in_vars:
                    if isinstance(v, Parameter):
                        if param_values is not None:
                            dyn.append(param_values[v.name])
                        else:
                            dyn.append(jnp.asarray(v._value))
                    elif isinstance(v, Variable):
                        if id(v) not in env:
                            raise KeyError(
                                f"variable {v.name!r} used before "
                                "definition (missing feed?)")
                        dyn.append(env[id(v)])
                    else:
                        dyn.append(next(it_const))
                outs = node.call(dyn)
                flat = jax.tree.leaves(outs)
                for var, val in zip(node.out_vars, flat):
                    env[id(var)] = val
            outs = []
            for v in fetch_vars:
                if id(v) not in env:
                    raise KeyError(
                        f"fetch variable {v.name!r} was not produced by "
                        "this program (wrong Program or missing feed?)")
                outs.append(env[id(v)])
            return outs

        return run


_default_main: Program = Program()
_default_startup: Program = Program()
_guard_stack: List[Program] = []


def default_main_program() -> Program:
    return _guard_stack[-1] if _guard_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """``with program_guard(main, startup):`` — ops recorded into main
    (reference static.program_guard)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _guard_stack.append(self.main)
        return self

    def __exit__(self, *exc):
        _guard_stack.pop()
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """Feed placeholder (reference static.data)."""
    prog = default_main_program()
    v = Variable(prog, name, shape, dtype)
    prog.feeds[name] = v
    return v


class InputSpec:
    """paddle.static.InputSpec (shared with jit.to_static signatures)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


class CPUPlace:
    pass


class CUDAPlace:
    def __init__(self, _id=0):
        self.id = _id


class TPUPlace:
    def __init__(self, _id=0):
        self.id = _id


def append_backward(loss: Variable, parameter_list=None, no_grad_set=None):
    """Mark ``loss`` for in-graph training (reference base/backward.py
    append_backward).  TPU-native: no grad ops are appended — the replay
    function is differentiated with ``jax.value_and_grad`` wrt the
    program's Parameter slots when the Executor runs the train step.
    Returns [(param, grad_name)] for API parity."""
    prog = loss.program
    prog._loss = loss
    params = list(parameter_list) if parameter_list else \
        list(prog.params.values())
    return [(p, p.name + "@GRAD") for p in params]


class Executor:
    """Program runner (reference executor.Executor → here: replay the
    recording as a pure function and jit it, cached per fetch set).

    Training programs (``optimizer.minimize(loss)`` called under
    ``program_guard``) run a jitted (loss, grads, apply) step per
    ``run()`` call: gradients via ``jax.value_and_grad`` over the
    Parameter slots, updates via the optimizer's pure
    ``apply_gradients``, new weights written back to the live boxes —
    the PirInterpreter + optimizer-op path collapsed into one XLA
    program."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}
        self._train_state: Dict[int, Dict[str, Any]] = {}

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Sequence[Variable] = (), return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        if hasattr(program, "fetch_names") and hasattr(program, "_exported"):
            outs = program.run(feed)      # ExportedProgram (loaded model)
            return [np.asarray(o) for o in outs] if return_numpy else                 [Tensor(o) for o in outs]
        if not program.nodes and not fetch_list:
            return []          # startup program: params are eager here
        feed_vals = {k: np.asarray(v._value if isinstance(v, Tensor) else v)
                     for k, v in feed.items()}
        if program._optimizer is not None and program._loss is not None:
            outs = self._run_train(program, feed_vals, list(fetch_list))
        else:
            key = (id(program), len(program.nodes),
                   tuple(id(v) for v in fetch_list))
            fn = self._cache.get(key)
            if fn is None:
                raw = program.build_fn(list(fetch_list))
                fn = jax.jit(raw)
                self._cache[key] = fn
            # params ride as traced ARGUMENTS — reading p._value inside
            # the traced fn would constant-fold the weights into the
            # cached executable and serve stale values after training
            param_vals = {n: jnp.asarray(p._value)
                          for n, p in program.params.items()}
            outs = fn(feed_vals, param_vals)
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _run_train(self, program: Program, feed_vals, fetch_vars):
        opt = program._optimizer
        loss_var = program._loss
        merge_k = int(getattr(program, "_grad_merge_k", 1))
        merge_avg = bool(getattr(program, "_grad_merge_avg", True))
        key = (id(program), len(program.nodes), id(loss_var),
               tuple(id(v) for v in fetch_vars), merge_k, merge_avg)
        cached = self._cache.get(key)
        if cached is None:
            replay = program.build_fn([loss_var] + fetch_vars)

            def grads_of(param_vals, feeds):
                def loss_fn(pv):
                    outs = replay(feeds, pv)
                    return outs[0], outs
                return jax.grad(loss_fn, has_aux=True)(param_vals)

            def step(param_vals, slots, t, lr, feeds):
                grads, outs = grads_of(param_vals, feeds)
                new_p, new_s = opt.apply_gradients(param_vals, grads,
                                                   slots, lr, t)
                return outs, new_p, new_s

            def accum(param_vals, acc, feeds):
                # gradient-merge pass: add this micro-step's grads into
                # the persistent accumulators (reference
                # auto_parallel_gradient_merge's @GradientMerge buffers)
                grads, outs = grads_of(param_vals, feeds)
                new_acc = jax.tree.map(jnp.add, acc, grads)
                return outs, new_acc

            def apply_merged(param_vals, acc, slots, t, lr):
                if getattr(program, "_grad_merge_avg", True):
                    acc = jax.tree.map(lambda g: g / merge_k, acc)
                return opt.apply_gradients(param_vals, acc, slots, lr, t)

            cached = {
                "step": jax.jit(step, donate_argnums=(0, 1)),
                "accum": jax.jit(accum, donate_argnums=(1,)),
                "apply": jax.jit(apply_merged, donate_argnums=(0, 2)),
            }
            self._cache[key] = cached
        st = self._train_state.get(id(program))
        if st is None:
            slots = {name: opt._init_slot_state(jnp.asarray(p._value))
                     for name, p in program.params.items()}
            st = {"slots": slots, "t": 0, "micro": 0, "acc": None}
            self._train_state[id(program)] = st
        param_vals = {name: jnp.asarray(p._value)
                      for name, p in program.params.items()}
        if merge_k <= 1:
            st["t"] += 1
            outs, new_p, new_s = cached["step"](
                param_vals, st["slots"], st["t"], float(opt.get_lr()),
                feed_vals)
            st["slots"] = new_s
            for name, p in program.params.items():
                p._value = new_p[name]
            if hasattr(opt, "_step_count"):
                opt._step_count += 1
            return outs[1:]
        # ---- gradient-merge window ----
        if st["acc"] is None:
            st["acc"] = {n: jnp.zeros_like(v)
                         for n, v in param_vals.items()}
        outs, st["acc"] = cached["accum"](param_vals, st["acc"],
                                          feed_vals)
        st["micro"] += 1
        if st["micro"] >= merge_k:
            st["t"] += 1
            new_p, new_s = cached["apply"](param_vals, st["acc"],
                                           st["slots"], st["t"],
                                           float(opt.get_lr()))
            st["slots"] = new_s
            st["acc"] = None
            st["micro"] = 0
            for name, p in program.params.items():
                p._value = new_p[name]
            if hasattr(opt, "_step_count"):
                opt._step_count += 1
        return outs[1:]         # user fetches (loss itself if requested)


def is_static_variable(x) -> bool:
    return isinstance(x, Variable)


def _bind_recording(on: bool) -> None:
    """Install/remove the dispatch recording hook.  Bound only while
    enable_static is active so pure-dygraph dispatch pays zero cost for
    the Variable scan."""
    _dispatch._static_variable_cls = Variable if on else None


from .extras import (  # noqa: F401,E402
    BuildStrategy, CompiledProgram, ExponentialMovingAverage,
    IpuCompiledProgram, IpuStrategy, Print, WeightNormParamAttr, accuracy,
    auc, cpu_places, create_global_var, create_parameter,
    ctr_metric_bundle, cuda_places, deserialize_persistables,
    deserialize_program, device_guard, global_scope, gradients,
    ipu_shard_guard, load, load_from_file, load_inference_model,
    load_program_state, name_scope, normalize_program, py_func, save,
    save_inference_model, save_to_file, scope_guard, serialize_persistables,
    serialize_program, set_ipu_shard, set_program_state, xpu_places,
)
from . import nn  # noqa: F401,E402
from . import passes  # noqa: F401,E402
from .passes import apply_amp_pass, apply_gradient_merge_pass  # noqa: F401,E402
from . import pass_manager  # noqa: F401,E402
from .pass_manager import PassManager, register_pass  # noqa: F401,E402
