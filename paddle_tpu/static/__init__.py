"""paddle.static parity: Program / program_guard / data / Executor
(reference python/paddle/static/__init__.py, framework.Program,
executor.Executor — SURVEY §1 layer 3).

TPU-first design: a Program is a RECORDED op graph, not an IR.  Under
``enable_static`` + ``program_guard``, every framework op that touches a
symbolic :class:`Variable` appends a node (shape/dtype inferred with
``jax.eval_shape``) instead of executing.  ``Executor.run`` replays the
recording as one pure function of the feeds and ``jax.jit``s it — the
Program/Executor pair collapses onto XLA exactly like ``jit.to_static``,
but through the reference's build-then-run API shape.

Supported surface: inference-style programs (data → ops → fetch).  The
legacy in-graph training loop (append_backward/minimize) is out of scope —
training is the compiled dygraph path (SURVEY §7 design decision).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dispatch as _dispatch
from ..core.tensor import Tensor

__all__ = ["Program", "program_guard", "default_main_program",
           "default_startup_program", "data", "Executor", "Variable",
           "InputSpec", "CPUPlace", "CUDAPlace", "TPUPlace"]


class Variable:
    """Symbolic tensor inside a Program (reference framework.Variable):
    knows shape/dtype, produced by a recorded node or a ``data`` feed."""

    def __init__(self, program: "Program", name: str, shape, dtype):
        self.program = program
        self.name = name
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.stop_gradient = True

    @property
    def ndim(self):
        return len(self.shape)

    def aval(self):
        concrete = tuple(1 if d in (None, -1) else int(d)
                         for d in self.shape)
        return jax.ShapeDtypeStruct(concrete, self.dtype)

    def __repr__(self):
        return f"Variable(name={self.name!r}, shape={self.shape}, " \
               f"dtype={self.dtype.name})"

    # arithmetic sugar routes through the recorded ops
    def _binop(self, op, other, swap=False):
        from ..ops import api
        return getattr(api, op)(other, self) if swap \
            else getattr(api, op)(self, other)

    def __add__(self, o):
        return self._binop("add", o)

    def __radd__(self, o):
        return self._binop("add", o, swap=True)

    def __sub__(self, o):
        return self._binop("subtract", o)

    def __mul__(self, o):
        return self._binop("multiply", o)

    def __rmul__(self, o):
        return self._binop("multiply", o, swap=True)

    def __matmul__(self, o):
        return self._binop("matmul", o)

    def __truediv__(self, o):
        return self._binop("divide", o)

    def __rtruediv__(self, o):
        return self._binop("divide", o, swap=True)

    def __rsub__(self, o):
        return self._binop("subtract", o, swap=True)

    def __neg__(self):
        from ..ops import api
        return api.neg(self)

    def __pow__(self, o):
        return self._binop("pow", o)

    def __lt__(self, o):
        return self._binop("less_than", o)

    def __le__(self, o):
        return self._binop("less_equal", o)

    def __gt__(self, o):
        return self._binop("greater_than", o)

    def __ge__(self, o):
        return self._binop("greater_equal", o)


class _Node:
    __slots__ = ("call", "in_vars", "const_args", "out_vars")

    def __init__(self, call, in_vars, const_args, out_vars):
        self.call = call            # fn(dyn_values_list) -> outputs
        self.in_vars = in_vars      # Variable inputs, positional in call
        self.const_args = const_args
        self.out_vars = out_vars


class Program:
    """An ordered recording of op nodes (reference framework.Program;
    blocks/ops collapse to one linear node list — control flow inside a
    recorded op is a lax construct, not a sub-block)."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.nodes: List[_Node] = []
        self.feeds: Dict[str, Variable] = {}
        self._name_i = 0

    def _fresh(self, prefix="tmp"):
        self._name_i += 1
        return f"{prefix}_{self.id}_{self._name_i}"

    def global_block(self):
        return self

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.nodes = list(self.nodes)
        p.feeds = dict(self.feeds)
        return p

    # ---- recording hook used by core.dispatch ----
    def record(self, name, call, markers, consts, out_avals, out_treedef):
        """Append a node.  ``markers``: per-dynamic-slot Variable or None
        (None slots read from ``consts`` in order at replay)."""
        outs = [Variable(self, self._fresh(name), a.shape, a.dtype)
                for a in out_avals]
        self.nodes.append(_Node(call, markers, consts, outs))
        return jax.tree.unflatten(out_treedef, outs)

    # ---- replay ----
    def build_fn(self, fetch_vars: Sequence[Variable]):
        feed_names = list(self.feeds)

        def run(feed_values: Dict[str, Any]):
            env: Dict[int, Any] = {}
            for n in feed_names:
                env[id(self.feeds[n])] = jnp.asarray(feed_values[n])
            for node in self.nodes:
                dyn = []
                it_const = iter(node.const_args)
                for v in node.in_vars:
                    if isinstance(v, Variable):
                        if id(v) not in env:
                            raise KeyError(
                                f"variable {v.name!r} used before "
                                "definition (missing feed?)")
                        dyn.append(env[id(v)])
                    else:
                        dyn.append(next(it_const))
                outs = node.call(dyn)
                flat = jax.tree.leaves(outs)
                for var, val in zip(node.out_vars, flat):
                    env[id(var)] = val
            outs = []
            for v in fetch_vars:
                if id(v) not in env:
                    raise KeyError(
                        f"fetch variable {v.name!r} was not produced by "
                        "this program (wrong Program or missing feed?)")
                outs.append(env[id(v)])
            return outs

        return run


_default_main: Program = Program()
_default_startup: Program = Program()
_guard_stack: List[Program] = []


def default_main_program() -> Program:
    return _guard_stack[-1] if _guard_stack else _default_main


def default_startup_program() -> Program:
    return _default_startup


class program_guard:
    """``with program_guard(main, startup):`` — ops recorded into main
    (reference static.program_guard)."""

    def __init__(self, main_program: Program,
                 startup_program: Optional[Program] = None):
        self.main = main_program
        self.startup = startup_program

    def __enter__(self):
        _guard_stack.append(self.main)
        return self

    def __exit__(self, *exc):
        _guard_stack.pop()
        return False


def data(name: str, shape, dtype="float32", lod_level=0) -> Variable:
    """Feed placeholder (reference static.data)."""
    prog = default_main_program()
    v = Variable(prog, name, shape, dtype)
    prog.feeds[name] = v
    return v


class InputSpec:
    """paddle.static.InputSpec (shared with jit.to_static signatures)."""

    def __init__(self, shape, dtype="float32", name=None):
        self.shape = tuple(shape)
        self.dtype = dtype
        self.name = name


class CPUPlace:
    pass


class CUDAPlace:
    def __init__(self, _id=0):
        self.id = _id


class TPUPlace:
    def __init__(self, _id=0):
        self.id = _id


class Executor:
    """Program runner (reference executor.Executor → here: replay the
    recording as a pure function and jit it, cached per fetch set)."""

    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[Any, Any] = {}

    def run(self, program: Optional[Program] = None, feed=None,
            fetch_list: Sequence[Variable] = (), return_numpy=True):
        program = program or default_main_program()
        feed = feed or {}
        if not program.nodes and not fetch_list:
            return []          # startup program: params are eager here
        key = (id(program), len(program.nodes),
               tuple(id(v) for v in fetch_list))
        fn = self._cache.get(key)
        if fn is None:
            raw = program.build_fn(list(fetch_list))
            fn = jax.jit(raw)
            self._cache[key] = fn
        outs = fn({k: np.asarray(v._value if isinstance(v, Tensor) else v)
                   for k, v in feed.items()})
        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]


def is_static_variable(x) -> bool:
    return isinstance(x, Variable)


def _bind_recording(on: bool) -> None:
    """Install/remove the dispatch recording hook.  Bound only while
    enable_static is active so pure-dygraph dispatch pays zero cost for
    the Variable scan."""
    _dispatch._static_variable_cls = Variable if on else None
