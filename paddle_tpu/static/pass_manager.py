"""Program pass manager + pattern-rewrite engine.

Reference: the PIR pass ecosystem — pass registry/manager
(paddle/pir/include/pass/pass_manager.h, python/paddle/distributed/passes/
pass_base.py PassManager) and the declarative rewrite rules (DRR,
paddle/fluid/pir/drr/) that fuse op patterns in the IR.

TPU-native shape: a pass is a callable ``(Program) -> Program`` over the
RECORDED node list; the rewrite engine matches straight-line producer→
consumer chains by op name and replaces them with one fused node.  The
fused node keeps BOTH chains' output Variables (replay-time pruning drops
dead ones), so downstream references and fetches stay valid without any
use-def surgery.  XLA refuses nothing here — these rewrites exist for
the cases where the op boundary itself carries semantics (AMP casting,
gradient-merge windows, explicit fused kernels), exactly the passes the
reference keeps OUTSIDE its compiler too.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

__all__ = ["register_pass", "get_pass", "PassManager", "fuse_chain_pass",
           "dead_code_elimination", "REGISTRY"]

REGISTRY: Dict[str, Callable] = {}


def register_pass(name: str):
    """Register ``fn(program, **opts) -> program`` under ``name``
    (reference pass_base.py register_pass)."""
    def deco(fn):
        REGISTRY[name] = fn
        fn.pass_name = name
        return fn
    return deco


def get_pass(name: str) -> Callable:
    if name not in REGISTRY:
        raise KeyError(f"unknown pass {name!r}; registered: "
                       f"{sorted(REGISTRY)}")
    return REGISTRY[name]


class PassManager:
    """Ordered pass pipeline (reference PassManager): passes are names
    from the registry or raw callables; ``apply`` threads the program
    through all of them."""

    def __init__(self, passes: Sequence = (), opts: Optional[dict] = None):
        self._passes: List[Callable] = []
        self._opts = opts or {}
        for p in passes:
            self.add(p)

    def add(self, p) -> "PassManager":
        self._passes.append(get_pass(p) if isinstance(p, str) else p)
        return self

    @property
    def names(self) -> List[str]:
        return [getattr(p, "pass_name", getattr(p, "__name__", "?"))
                for p in self._passes]

    def apply(self, program):
        consumed = set()
        for p in self._passes:
            name = getattr(p, "pass_name",
                           getattr(p, "__name__", ""))
            kwargs = self._opts.get(name, {})
            if kwargs:
                consumed.add(name)
            program = p(program, **kwargs) or program
        unknown = set(self._opts) - consumed - \
            {n for n in self._opts if not self._opts[n]}
        if unknown:
            raise KeyError(f"PassManager opts for passes not in the "
                           f"pipeline: {sorted(unknown)}")
        return program


# ---------------------------------------------------------------------------
# the rewrite engine (DRR analog)
# ---------------------------------------------------------------------------

def fuse_chain_pass(program, pattern: Sequence[str],
                    fused_name: Optional[str] = None):
    """Fuse straight-line chains ``pattern[0] -> pattern[1] -> ...``
    where each link's FIRST dynamic input is the previous node's first
    output.  The fused node emits every chain output (replay pruning
    drops the dead intermediates), composing the original calls — the
    declarative-rewrite analog over recorded nodes."""
    from . import _Node

    nodes = program.nodes
    fused_name = fused_name or "_".join(pattern)
    i = 0
    out_nodes: List = []
    while i < len(nodes):
        chain = _match_chain(nodes, i, pattern)
        if chain is None:
            out_nodes.append(nodes[i])
            i += 1
            continue
        out_nodes.append(_build_fused(chain, fused_name))
        i = chain[-1][0] + 1
    program.nodes = out_nodes
    return program


def _match_chain(nodes, start: int, pattern: Sequence[str]):
    """Match pattern anchored at nodes[start]; links must be CONSECUTIVE
    recorded nodes (the recording is in execution order, so real chains
    are adjacent) and each link's first Variable input must be the
    previous link's first output."""
    if nodes[start].name != pattern[0]:
        return None
    chain = [(start, nodes[start])]
    for step, want in enumerate(pattern[1:], 1):
        idx = start + step
        if idx >= len(nodes):
            return None
        node = nodes[idx]
        if node.name != want:
            return None
        prev_out = chain[-1][1].out_vars[0]
        first_var = next((v for v in node.in_vars if v is not None), None)
        if first_var is not prev_out:
            return None
        chain.append((idx, node))
    return chain


def _build_fused(chain, fused_name: str):
    from . import _Node

    nodes = [n for _, n in chain]
    # the fused node's inputs: first node's inputs + every later node's
    # inputs EXCEPT the chained intermediate
    in_vars: List = list(nodes[0].in_vars)
    const_args: List = list(nodes[0].const_args)
    extra_slots: List[tuple] = []      # (node_idx, positions in its call)
    for k, node in enumerate(nodes[1:], 1):
        chained = chain[k - 1][1].out_vars[0]
        positions = []
        for pos, v in enumerate(node.in_vars):
            if v is chained:
                positions.append(None)          # EVERY occurrence wires
                # to the previous link's output (add(m, m) is legal)
            else:
                positions.append(len(in_vars))
                in_vars.append(v)
        const_args.extend(node.const_args)
        extra_slots.append((k, positions))
    out_vars = [ov for n in nodes for ov in n.out_vars]
    calls = [n.call for n in nodes]
    n_in0 = len(nodes[0].in_vars)
    import jax

    def fused_call(dyn):
        outs0 = calls[0](dyn[:n_in0])
        flat = jax.tree.leaves(outs0)
        all_outs = list(flat)
        prev_first = flat[0]
        for (k, positions) in extra_slots:
            vals = [prev_first if p is None else dyn[p]
                    for p in positions]
            outs = calls[k](vals)
            flat = jax.tree.leaves(outs)
            all_outs.extend(flat)
            prev_first = flat[0]
        return tuple(all_outs)

    return _Node(fused_name, fused_call, in_vars, const_args, out_vars,
                 statics=[s for n in nodes for s in n.statics])


@register_pass("dead_code_elimination")
def dead_code_elimination(program, keep=()):
    """Drop nodes whose outputs reach neither the loss nor ``keep``
    (replay prunes at build_fn time anyway; this makes the PROGRAM
    itself small — reference DCE pass).  Without any anchor (no loss,
    no keep) the pass is a no-op: it can't know the fetch set."""
    needed = {id(v) for v in keep}
    if program._loss is not None:
        needed.add(id(program._loss))
    if not needed:
        return program
    live: List = []
    for node in reversed(program.nodes):
        if any(id(ov) in needed for ov in node.out_vars):
            live.append(node)
            for v in node.in_vars:
                if v is not None:
                    needed.add(id(v))
    live.reverse()
    program.nodes = live
    return program


@register_pass("fuse_matmul_add")
def fuse_matmul_add(program):
    """matmul + add -> one fused linear node (the fused_gemm_epilogue
    pass analog; XLA fuses the math anyway — the pass keeps the op
    BOUNDARY fused so per-op hooks/AMP see one linear)."""
    return fuse_chain_pass(program, ("matmul", "add"), "linear")


@register_pass("amp")
def amp_pass(program, level: str = "O1", **kw):
    from .passes import apply_amp_pass
    return apply_amp_pass(program, level=level, **kw)


@register_pass("gradient_merge")
def gradient_merge_pass(program, k_steps: int = 1, avg: bool = True):
    from .passes import apply_gradient_merge_pass
    return apply_gradient_merge_pass(program, k_steps, avg)
