"""paddle.static surface tail (reference python/paddle/static/__init__.py
__all__): program save/load + serialization, scopes/guards, metric ops,
parameter creation, EMA, strategies.

TPU-native mappings: a "serialized program" is the feed→fetch replay
lowered to STABLEHLO (the portable artifact — the recorded closures are
process-local, so bytes-level fidelity lives at the XLA layer, same
family as jit.save); scopes collapse into the live Parameter boxes;
device guards are jax default-device scopes.
"""

from __future__ import annotations

import contextlib
import pickle
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter, Tensor
from . import (Executor, Program, Variable, append_backward,
               default_main_program)

__all__ = [
    "save", "load", "save_inference_model", "load_inference_model",
    "serialize_program", "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "normalize_program", "save_to_file",
    "load_from_file", "load_program_state", "set_program_state",
    "global_scope", "scope_guard", "device_guard", "name_scope",
    "ipu_shard_guard", "set_ipu_shard", "cpu_places", "cuda_places",
    "xpu_places", "create_parameter", "create_global_var", "gradients",
    "accuracy", "auc", "ctr_metric_bundle", "py_func", "Print",
    "BuildStrategy", "CompiledProgram", "IpuCompiledProgram",
    "IpuStrategy", "ExponentialMovingAverage", "WeightNormParamAttr",
]


# ---------------------------------------------------------------------------
# program persistence (reference static/io.py)
# ---------------------------------------------------------------------------

def _program_state(program: Program) -> Dict[str, np.ndarray]:
    return {n: np.asarray(p._value) for n, p in program.params.items()}


def _export_program(program: Program, feed_vars, fetch_vars):
    """Lower the feed→fetch replay to serialized STABLEHLO (the recorded
    closures are process-local; STABLEHLO is the portable form — same
    artifact family as jit.save)."""
    feed_names = [v.name for v in feed_vars]
    fetch_list = list(fetch_vars)
    raw = program.build_fn(fetch_list)

    def pure(param_vals, *feed_vals):
        feeds = dict(zip(feed_names, feed_vals))
        return tuple(raw(feeds, param_vals))

    param_avals = {n: jax.ShapeDtypeStruct(
        jnp.asarray(p._value).shape, jnp.asarray(p._value).dtype)
        for n, p in program.params.items()}
    scope = jax.export.SymbolicScope()
    feed_avals = []
    for v in feed_vars:
        if any(d in (None, -1) for d in v.shape):
            parts = [f"_d{i}" if d in (None, -1) else str(d)
                     for i, d in enumerate(v.shape)]
            shape = jax.export.symbolic_shape(",".join(parts), scope=scope)
        else:
            shape = tuple(v.shape)
        feed_avals.append(jax.ShapeDtypeStruct(shape, v.dtype))
    exported = jax.export.export(jax.jit(pure))(param_avals, *feed_avals)
    return exported


class ExportedProgram:
    """Deserialized inference program (reference: the Program returned by
    load_inference_model).  Executor.run detects and calls it."""

    def __init__(self, exported, state, feed_names, fetch_names):
        self._exported = exported
        self._state = {k: jnp.asarray(v) for k, v in state.items()}
        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)

    def run(self, feed: Dict[str, Any]):
        vals = [jnp.asarray(feed[n]) for n in self.feed_names]
        return list(self._exported.call(self._state, *vals))


def serialize_program(program: Optional[Program] = None, feed_vars=None,
                      fetch_vars=None, **kw) -> bytes:
    """Reference static/io.py serialize_program (ProgramDesc bytes →
    serialized STABLEHLO of the feed→fetch replay here)."""
    program = program or default_main_program()
    feed_vars = feed_vars or list(program.feeds.values())
    fetch_vars = fetch_vars or [program.nodes[-1].out_vars[0]]
    exported = _export_program(program, feed_vars, fetch_vars)
    return pickle.dumps({"stablehlo": exported.serialize(),
                         "feed_names": [v.name for v in feed_vars],
                         "fetch_names": [v.name for v in fetch_vars]})


def deserialize_program(data: bytes) -> "ExportedProgram":
    blob = pickle.loads(data)
    exported = jax.export.deserialize(blob["stablehlo"])
    return ExportedProgram(exported, blob.get("state", {}),
                           blob["feed_names"], blob["fetch_names"])


def serialize_persistables(feed_vars=None, fetch_vars=None,
                           executor=None, program=None, **kw) -> bytes:
    program = program or default_main_program()
    return pickle.dumps(_program_state(program))


def deserialize_persistables(program: Program, data: bytes,
                             executor=None) -> None:
    state = pickle.loads(data)
    set_program_state(program, state)


def save_to_file(path: str, content: bytes) -> None:
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program: Program, feed_vars, fetch_vars, **kw
                      ) -> Program:
    """Reference normalize_program prunes to the feed→fetch subgraph; our
    replay prunes lazily at build_fn time, so this records the io vars."""
    p = program.clone(for_test=True)
    p._io_vars = (list(feed_vars), list(fetch_vars))
    return p


def save(program: Program, model_path: str, protocol: int = 4, **kw):
    """paddle.static.save: parameter state (reference saves persistables;
    program structure goes via save_inference_model)."""
    save_to_file(model_path + ".pdparams",
                 pickle.dumps(_program_state(program), protocol=protocol))


def load(program: Program, model_path: str, executor=None, var_list=None):
    state = pickle.loads(load_from_file(model_path + ".pdparams"))
    set_program_state(program, state)


def save_inference_model(path_prefix: str, feed_vars, fetch_vars,
                         executor=None, program: Optional[Program] = None,
                         **kw) -> None:
    """Reference static/io.py:469 — persists the feed→fetch subgraph as
    STABLEHLO + the parameter values; loadable by
    :func:`load_inference_model` in a fresh process."""
    import os
    program = program or default_main_program()
    exported = _export_program(program, feed_vars, fetch_vars)
    blob = {"stablehlo": exported.serialize(),
            "feed_names": [v.name for v in feed_vars],
            "fetch_names": [v.name for v in fetch_vars],
            "state": _program_state(program)}
    d = os.path.dirname(path_prefix)
    if d:
        os.makedirs(d, exist_ok=True)
    save_to_file(path_prefix + ".pdmodel", pickle.dumps(blob))


def load_inference_model(path_prefix: str, executor=None, **kw):
    """Reference static/io.py:787 — returns
    [program, feed_names, fetch_names]; the program is an
    :class:`ExportedProgram` the Executor can run."""
    blob = pickle.loads(load_from_file(path_prefix + ".pdmodel"))
    exported = jax.export.deserialize(blob["stablehlo"])
    prog = ExportedProgram(exported, blob["state"], blob["feed_names"],
                           blob["fetch_names"])
    return [prog, blob["feed_names"], blob["fetch_names"]]


def load_program_state(model_path: str, var_list=None
                       ) -> Dict[str, np.ndarray]:
    return pickle.loads(load_from_file(model_path + ".pdparams"))


def set_program_state(program: Program, state: Dict[str, Any]) -> None:
    for n, p in program.params.items():
        if n in state:
            p._value = jnp.asarray(state[n])


# ---------------------------------------------------------------------------
# scopes / guards / places
# ---------------------------------------------------------------------------

class _Scope:
    """Live-parameter view (the reference's Scope holds persistables; our
    Parameters ARE the storage, so the scope reads through them)."""

    def find_var(self, name: str):
        for prog in (default_main_program(),):
            if name in prog.params:
                return prog.params[name]
        return None

    def var_names(self):
        return list(default_main_program().params)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


@contextlib.contextmanager
def scope_guard(scope):
    yield scope


@contextlib.contextmanager
def device_guard(device: Optional[str] = None):
    """Reference device_guard('cpu'/'gpu:0') — maps to a jax default
    device scope."""
    if device is None:
        yield
        return
    ty = device.split(":")[0]
    idx = int(device.split(":")[1]) if ":" in device else 0
    plat = {"gpu": None, "cuda": None, "npu": None}.get(ty, ty)
    try:
        devs = [d for d in jax.devices()] if plat is None else \
            [d for d in jax.devices() if d.platform == plat]
        target = devs[idx] if devs else None
    except Exception:
        target = None
    if target is None:
        yield
        return
    with jax.default_device(target):
        yield


@contextlib.contextmanager
def name_scope(prefix: Optional[str] = None):
    """Reference name_scope — names ops for debugging; maps onto
    jax.named_scope so the prefix shows in XLA profiles."""
    with jax.named_scope(prefix or "scope"):
        yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield                      # IPU is out of scope; guard is a no-op


def set_ipu_shard(call_func, index=-1, stage=-1):
    return call_func


def cpu_places(device_count: Optional[int] = None) -> List:
    from . import CPUPlace
    n = device_count or max(
        len([d for d in jax.devices() if d.platform == "cpu"]), 1)
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None) -> List:
    from . import CUDAPlace
    ids = device_ids if device_ids is not None else range(
        max(len(jax.devices()), 1))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None) -> List:
    return cuda_places(device_ids)


# ---------------------------------------------------------------------------
# parameter / variable creation
# ---------------------------------------------------------------------------

def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference static.nn.create_parameter — eager Parameter registered
    with the current Program when recording."""
    from .. import create_parameter as _cp
    p = _cp(shape, dtype, name=name, attr=attr,
            default_initializer=default_initializer, is_bias=is_bias)
    return p


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    v = jnp.full(tuple(shape), value, dtype)
    p = Parameter(v, name=name, trainable=False)
    p.persistable = persistable
    return p


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference base/backward.py gradients: grads of targets wrt inputs
    in the static program.  Marks the program for training and returns
    grad placeholders; the Executor's fused step computes them."""
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    pairs = append_backward(targets[0])
    wanted = {getattr(i, "name", None) for i in (
        inputs if isinstance(inputs, (list, tuple)) else [inputs])}
    return [g for p, g in pairs if p.name in wanted or not wanted]


# ---------------------------------------------------------------------------
# metric ops
# ---------------------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Reference static accuracy op."""
    from ..ops import api as _api
    from ..core.dispatch import run_op

    def impl(x, lab):
        topk = jax.lax.top_k(x, k)[1]
        lab_ = lab.reshape(-1, 1)
        hit = jnp.any(topk == lab_, axis=1)
        return jnp.mean(hit.astype(jnp.float32))

    return run_op("accuracy", impl, (input, label), {})


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """Reference static auc op (batch AUC, trapezoidal)."""
    from ..core.dispatch import run_op

    def impl(x, lab):
        score = x[:, 1] if x.ndim == 2 and x.shape[1] == 2 else \
            x.reshape(-1)
        lab_ = lab.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(-score)
        lab_sorted = lab_[order]
        tp = jnp.cumsum(lab_sorted)
        fp = jnp.cumsum(1.0 - lab_sorted)
        p = jnp.maximum(tp[-1], 1e-6)
        n = jnp.maximum(fp[-1], 1e-6)
        tpr = jnp.concatenate([jnp.zeros(1), tp / p])
        fpr = jnp.concatenate([jnp.zeros(1), fp / n])
        return jnp.trapezoid(tpr, fpr)

    return run_op("auc", impl, (input, label), {})


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    """Reference ctr_metric_bundle: (auc, batch_auc, ...) bundle — the
    TPU build surfaces the core AUC pair."""
    a = auc(input, label)
    return a, a


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference static py_func op → jax.pure_callback."""
    from ..core.dispatch import run_op

    def impl(*vals):
        outs = func(*vals)
        return outs

    xs = x if isinstance(x, (list, tuple)) else [x]
    return run_op("py_func", impl, tuple(xs), {})


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Reference Print op → jax.debug.print inside the replay."""
    from ..core.dispatch import run_op

    def impl(v):
        jax.debug.print((message or "") + "{}", v)
        return v

    return run_op("print", impl, (input,), {})


# ---------------------------------------------------------------------------
# strategies / compiled program / EMA
# ---------------------------------------------------------------------------

class BuildStrategy:
    """Reference BuildStrategy — pass toggles; XLA owns fusion here, so
    the knobs are accepted and recorded for introspection."""

    def __init__(self):
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.enable_auto_fusion = False
        self.memory_optimize = None
        self.reduce_strategy = None
        self.build_cinn_pass = False


class CompiledProgram:
    """Reference CompiledProgram(program).with_data_parallel(...) — the
    Executor already jit-compiles replays, so this wraps the Program and
    keeps the API shape."""

    def __init__(self, program: Program, build_strategy: Optional[
            BuildStrategy] = None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        return self


class IpuStrategy:          # IPU backend is an explicit non-goal
    def __init__(self):
        self.is_training = True

    def set_graph_config(self, **kw):
        return None


class IpuCompiledProgram:
    def __init__(self, program=None, ipu_strategy=None, scope=None):
        raise NotImplementedError(
            "IPU backend is out of scope for the TPU build "
            "(SURVEY §7 non-goals)")


class ExponentialMovingAverage:
    """EMA of parameters (reference static ExponentialMovingAverage):
    update() after each step; apply()/restore() swap EMA weights in and
    out for evaluation."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = float(decay)
        self._ema: Dict[str, jax.Array] = {}
        self._backup: Dict[str, jax.Array] = {}
        self._step = 0

    def _params(self):
        return default_main_program().params

    def update(self):
        self._step += 1
        # reference uses min(decay, (1+t)/(10+t)) warmup
        d = min(self._decay, (1.0 + self._step) / (10.0 + self._step))
        for n, p in self._params().items():
            v = jnp.asarray(p._value, jnp.float32)
            prev = self._ema.get(n)
            self._ema[n] = v if prev is None else d * prev + (1 - d) * v

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {n: p._value for n, p in self._params().items()}
        for n, p in self._params().items():
            if n in self._ema:
                p._value = self._ema[n].astype(
                    jnp.asarray(self._backup[n]).dtype)
        try:
            yield self
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for n, p in self._params().items():
            if n in self._backup:
                p._value = self._backup[n]
        self._backup = {}


class WeightNormParamAttr:
    """Reference WeightNormParamAttr (weight-normalized parameterization
    attr).  Carried on the param; the normalization itself is the
    nn.utils.weight_norm transform."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.trainable = trainable
