"""Optimizer base (reference: python/paddle/optimizer/optimizer.py:127).

Design: every optimizer defines two pure per-parameter functions
(`_init_slot_state`, `_update`); the base class derives BOTH execution modes
from them:

* **eager** — ``opt.step()`` after ``loss.backward()`` applies updates via a
  cached jitted tree function (mirrors the reference's per-param fused
  adam_/sgd_ op calls, optimizer.py _add_accumulator machinery);
* **functional** — ``opt.apply_gradients(params, grads, state, lr)`` is pure
  and jit/shard_map-compatible: the trainer, pipeline and sharded variants
  all reuse it.  Optimizer state is a pytree, so sharding-stage-1/2/3
  becomes a sharding annotation on this pytree (SURVEY §7.5).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.tensor import Parameter, Tensor
from .lr import LRScheduler

__all__ = ["Optimizer"]


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        self._lr = learning_rate
        self._parameters: Optional[List[Parameter]] = (
            list(parameters) if parameters is not None else None)
        if self._parameters is not None and self._parameters and isinstance(
                self._parameters[0], dict):
            # param-group form: [{'params': [...], 'learning_rate': ...}]
            flat = []
            for group in self._parameters:
                flat.extend(group["params"])
            self._parameters = flat
        self._weight_decay = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._state: Dict[str, Any] = {}          # name -> slot dict
        self._step_count = 0
        self._jit_apply: Optional[Callable] = None
        self._param_index: Dict[str, Parameter] = {}
        if self._parameters is not None:
            for p in self._parameters:
                self._param_index[p.name] = p

    # -- learning rate --------------------------------------------------
    def get_lr(self) -> float:
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value: float) -> None:
        if isinstance(self._lr, LRScheduler):
            raise RuntimeError("cannot set_lr when using an LRScheduler")
        self._lr = value

    @property
    def _learning_rate(self):
        return self._lr

    # -- subclass interface ---------------------------------------------
    #: Subclasses whose ``_update`` is purely ELEMENT-WISE (each output
    #: element depends only on the same input element + scalars) set this
    #: True to enable the fused multi-tensor path (fused.py): the update
    #: applied to a concatenation of same-bucket params is then
    #: bit-identical to the per-param loop.
    _fused_elementwise = False

    def _init_slot_state(self, value: jax.Array) -> Dict[str, jax.Array]:
        """Per-param slot init (e.g. Adam moments)."""
        return {}

    def _update(self, p: jax.Array, g: jax.Array, s: Dict[str, jax.Array],
                lr: jax.Array, t: jax.Array
                ) -> Tuple[jax.Array, Dict[str, jax.Array]]:
        raise NotImplementedError

    def _wd_coeff(self) -> float:
        wd = self._weight_decay
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):  # regularizer.L2Decay
            return float(wd._coeff)
        return float(wd)

    # -- functional API --------------------------------------------------
    def init_state(self, params: Dict[str, jax.Array]) -> Dict[str, Any]:
        state = {}
        for name, v in params.items():
            s = self._init_slot_state(v)
            if self._multi_precision and v.dtype in (jnp.bfloat16, jnp.float16):
                s["master_weight"] = v.astype(jnp.float32)
            state[name] = s
        return state

    def apply_gradients(self, params: Dict[str, jax.Array],
                        grads: Dict[str, jax.Array], state: Dict[str, Any],
                        lr, step) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Pure update: returns (new_params, new_state).  Used directly
        inside jitted train steps."""
        if self._grad_clip is not None:
            grads = self._grad_clip.apply_values(grads)
        wd = self._wd_coeff()
        new_params, new_state = {}, {}
        lr = jnp.asarray(lr, jnp.float32)
        t = jnp.asarray(step, jnp.int32)
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state.get(name, {})
                continue
            s = dict(state.get(name, {}))
            master = s.get("master_weight")
            work_p = master if master is not None else p
            g32 = g.astype(work_p.dtype)
            if wd and self._decay_applies(name):
                g32 = g32 + wd * work_p
            np_, ns = self._update(work_p, g32, s, lr, t)
            if master is not None:
                ns["master_weight"] = np_
                np_ = np_.astype(p.dtype)
            new_params[name] = np_
            new_state[name] = ns
        return new_params, new_state

    def _decay_applies(self, name: str) -> bool:
        return True

    # -- fused multi-tensor path ------------------------------------------
    def _fused_decay_coeff(self) -> float:
        """Weight-decay coefficient the fused planner buckets by (AdamW's
        decoupled coeff lives outside ``_wd_coeff``)."""
        return self._wd_coeff()

    def _fused_pre_update(self, flat_work: jax.Array, lr: jax.Array,
                          decay: bool) -> jax.Array:
        """Hook applied to each bucket's flattened working params before
        ``_update`` (AdamW's decoupled decay overrides this)."""
        return flat_work

    def _fused_supported(self) -> bool:
        if not self._fused_elementwise:
            return False
        # an apply_gradients override changes per-step semantics the fused
        # path would silently skip — unless that same class declares (in
        # its own __dict__, so subclasses re-overriding lose the marker)
        # that its override is fully captured by the fused hooks.
        owner = next(c for c in type(self).__mro__
                     if "apply_gradients" in c.__dict__)
        return owner is Optimizer or owner.__dict__.get(
            "_fused_handles_apply", False)

    def apply_gradients_fused(self, params: Dict[str, jax.Array],
                              grads: Dict[str, jax.Array],
                              state: Dict[str, Any], lr, step
                              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        """Pure update like :meth:`apply_gradients`, but through the
        multi-tensor fused path (one kernel per bucket instead of one per
        parameter) whenever this optimizer supports it; exotic slot
        states fall back to the per-param loop.  This is the default
        entry point for jitted train steps.

        The returned state is in FUSED form (flat per-bucket slot
        buffers) and is accepted back on the next call — thread it
        through the train loop unchanged and call
        :meth:`unflatten_state` when per-name slots are needed
        (checkpointing)."""
        if self._fused_supported():
            from .fused import apply_fused, is_fused_state
            out = apply_fused(self, params, grads, state, lr, step)
            if out is not None:
                return out
            if is_fused_state(state):
                raise ValueError(
                    "optimizer received fused state but cannot fuse this "
                    "parameter set; unflatten_state it first")
        return self.apply_gradients(params, grads, state, lr, step)

    def unflatten_state(self, state: Dict[str, Any]) -> Dict[str, Any]:
        """Per-name slot dicts from a (possibly fused) state pytree."""
        from .fused import is_fused_state, unflatten_state
        if not is_fused_state(state):
            return state
        plan = getattr(self, "_fused_active_plan", None)
        if plan is None:
            raise ValueError("no active fused plan on this optimizer; "
                             "fused state cannot be unflattened")
        return unflatten_state(plan, state)

    def build_jit_apply(self, donate: bool = True) -> Callable:
        """Jitted fused apply with params/grads/moments DONATED: optimizer
        state is updated in place (no double-buffering) — the old buffers
        are deleted after the call.  Cached per optimizer."""
        key = ("_jit_apply_donated" if donate else "_jit_apply_undonated")
        fn = getattr(self, key, None)
        if fn is None:
            fn = jax.jit(self.apply_gradients_fused,
                         donate_argnums=(0, 1, 2) if donate else ())
            setattr(self, key, fn)
        return fn

    # -- eager API --------------------------------------------------------
    def step(self) -> None:
        if self._parameters is None:
            raise RuntimeError("Optimizer created without parameters; use the "
                               "functional API instead")
        params, grads = {}, {}
        for p in self._parameters:
            if p.grad is not None and p.trainable:
                params[p.name] = p._value
                grads[p.name] = p.grad._value
        if not params:
            return
        for name, v in params.items():
            if name not in self._state:
                s = self._init_slot_state(v)
                if self._multi_precision and v.dtype in (jnp.bfloat16,
                                                         jnp.float16):
                    s["master_weight"] = v.astype(jnp.float32)
                self._state[name] = s
        state = {n: self._state[n] for n in params}
        if self._jit_apply is None:
            # donate params + moments: the eager step updates optimizer
            # state in place instead of double-buffering it.  Grads stay
            # undonated — ``p.grad`` remains readable after ``step()``
            # (and accumulable by a later ``backward()``).  Per-param
            # (not fused) on purpose: ``self._state`` keeps its per-name
            # contract for state_dict(), and one whole-step XLA program
            # has no per-op dispatch to save anyway.
            self._jit_apply = jax.jit(self.apply_gradients,
                                      donate_argnums=(0, 2))
        try:
            new_params, new_state = self._jit_apply(params, grads, state,
                                                    self.get_lr(),
                                                    self._step_count + 1)
        except TypeError:
            # safe despite the donation above: TypeError from jit means
            # apply_gradients could not be TRACED (e.g. a Python-object
            # lr schedule) — tracing precedes execution, so no buffer
            # was actually donated when we reach this fallback
            new_params, new_state = self.apply_gradients(
                params, grads, state,  # tracelint: disable=TL004
                self.get_lr(), self._step_count + 1)
        for name, v in new_params.items():
            self._param_index[name]._value = v
        self._state.update(new_state)
        self._step_count += 1

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import is_static_variable
        if is_static_variable(loss):
            # static-graph training (reference Optimizer.minimize →
            # append_backward + optimizer ops): register on the Program;
            # Executor.run executes the fused grad+update step
            prog = loss.program
            prog._loss = loss
            prog._optimizer = self
            params = list(prog.params.values())
            return None, [(p, p.name + "@GRAD") for p in params]
        loss.backward()
        self.step()
        return None, [(p, p.grad) for p in (self._parameters or [])]

    def clear_grad(self, set_to_zero: bool = False) -> None:
        for p in self._parameters or []:
            p.clear_grad()

    clear_gradients = clear_grad

    # -- state dict -------------------------------------------------------
    def state_dict(self) -> Dict[str, Any]:
        flat: Dict[str, Any] = {"@step": self._step_count}
        for pname, slots in self._state.items():
            for sname, v in slots.items():
                flat[f"{pname}/{sname}"] = Tensor(v)
        if isinstance(self._lr, LRScheduler):
            flat["@lr"] = self._lr.state_dict()
        return flat

    def set_state_dict(self, state: Dict[str, Any]) -> None:
        self._step_count = int(state.get("@step", 0))
        if "@lr" in state and isinstance(self._lr, LRScheduler):
            self._lr.set_state_dict(state["@lr"])
        for key, v in state.items():
            if key.startswith("@"):
                continue
            pname, _, sname = key.rpartition("/")
            self._state.setdefault(pname, {})[sname] = (
                v._value if isinstance(v, Tensor) else jnp.asarray(v))

    def _scheduler_step(self) -> None:
        if isinstance(self._lr, LRScheduler):
            self._lr.step()
