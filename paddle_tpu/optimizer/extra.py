"""ASGD / Rprop / LBFGS (reference: python/paddle/optimizer/{asgd,rprop,
lbfgs}.py — VERDICT r2 item 5 optimizer tail).

ASGD and Rprop are pure per-param updates and ride the base class's
jit-compiled ``apply_gradients``.  LBFGS is closure-driven (inherently
sequential line search) and overrides ``step`` the way the reference's
LBFGS does — the closure's forward/backward still runs under the normal
jit'd eager path.
"""

from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from .optimizer import Optimizer

__all__ = ["ASGD", "Rprop", "LBFGS"]


class ASGD(Optimizer):
    """Stochastic Average Gradient (reference: optimizer/asgd.py:41):
    keeps the last-seen grad per batch slot; the step direction is the
    running average ``d / min(m+1, n)``."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        if batch_num < 1:
            raise ValueError("batch_num must be >= 1")
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._batch_num = int(batch_num)

    def _init_slot_state(self, v):
        return {"d": jnp.zeros(v.shape, jnp.float32),
                "ys": jnp.zeros((self._batch_num,) + v.shape, jnp.float32)}

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        n = self._batch_num
        m = t - 1                       # 0-based step counter
        i = jnp.mod(m, n)
        y_i = s["ys"][i]
        d = s["d"] - y_i + g32
        ys = s["ys"].at[i].set(g32)
        denom = jnp.minimum(m + 1, n).astype(jnp.float32)
        new_p = p.astype(jnp.float32) - lr * d / denom
        return new_p.astype(p.dtype), {"d": d, "ys": ys}


class Rprop(Optimizer):
    """Resilient backpropagation (reference: optimizer/rprop.py:118):
    per-weight step sizes adapted by grad-sign agreement; magnitudes
    ignored.  Sign flip -> shrink step and skip the update (Rprop-)."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        if learning_rate is None:
            raise ValueError("learning_rate is not set")
        if not (0.0 < learning_rate_range[0] <= learning_rate
                <= learning_rate_range[1]):
            raise ValueError(
                "need 0 < lr_range[0] <= lr <= lr_range[1]")
        if not (0.0 < etas[0] < 1.0 < etas[1]):
            raise ValueError("need 0 < eta_minus < 1 < eta_plus")
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._lr_min, self._lr_max = (float(learning_rate_range[0]),
                                      float(learning_rate_range[1]))
        self._eta_minus, self._eta_plus = float(etas[0]), float(etas[1])

    def _init_slot_state(self, v):
        return {"prev_grad": jnp.zeros(v.shape, jnp.float32),
                "lrs": jnp.full(v.shape, float(self.get_lr()), jnp.float32)}

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        agree = jnp.sign(g32) * jnp.sign(s["prev_grad"])
        factor = jnp.where(agree > 0, self._eta_plus,
                           jnp.where(agree < 0, self._eta_minus, 1.0))
        lrs = jnp.clip(s["lrs"] * factor, self._lr_min, self._lr_max)
        g_eff = jnp.where(agree < 0, 0.0, g32)
        new_p = p.astype(jnp.float32) - jnp.sign(g_eff) * lrs
        return new_p.astype(p.dtype), {"prev_grad": g_eff, "lrs": lrs}


def _strong_wolfe(obj, x0, d, f0, g0, lr0, c1=1e-4, c2=0.9, max_ls=25):
    """Strong-Wolfe cubic-interpolation line search (same contract as the
    reference's lbfgs.py _strong_wolfe; independent NumPy implementation)."""
    gtd0 = float(np.dot(g0, d))
    t, t_prev = lr0, 0.0
    f_prev, g_prev, gtd_prev = f0, g0, gtd0
    bracket = None
    for _ in range(max_ls):
        f_t, g_t = obj(x0 + t * d)
        gtd_t = float(np.dot(g_t, d))
        if f_t > f0 + c1 * t * gtd0 or (bracket is None and f_t >= f_prev
                                        and t_prev > 0):
            bracket = (t_prev, f_prev, g_prev, gtd_prev, t, f_t, g_t, gtd_t)
            break
        if abs(gtd_t) <= -c2 * gtd0:
            return t, f_t, g_t
        if gtd_t >= 0:
            bracket = (t, f_t, g_t, gtd_t, t_prev, f_prev, g_prev, gtd_prev)
            break
        t_prev, f_prev, g_prev, gtd_prev = t, f_t, g_t, gtd_t
        t = t * 2.0
    else:
        return t, f_t, g_t
    lo_t, lo_f, lo_g, lo_gtd, hi_t, hi_f, hi_g, hi_gtd = bracket
    for _ in range(max_ls):
        if abs(hi_t - lo_t) < 1e-9:
            break
        t = 0.5 * (lo_t + hi_t)          # bisection (robust, derivative-free)
        f_t, g_t = obj(x0 + t * d)
        gtd_t = float(np.dot(g_t, d))
        if f_t > f0 + c1 * t * gtd0 or f_t >= lo_f:
            hi_t, hi_f, hi_g, hi_gtd = t, f_t, g_t, gtd_t
        else:
            if abs(gtd_t) <= -c2 * gtd0:
                return t, f_t, g_t
            if gtd_t * (hi_t - lo_t) >= 0:
                hi_t, hi_f, hi_g, hi_gtd = lo_t, lo_f, lo_g, lo_gtd
            lo_t, lo_f, lo_g, lo_gtd = t, f_t, g_t, gtd_t
    return lo_t, lo_f, lo_g


class LBFGS(Optimizer):
    """Limited-memory BFGS with optional strong-Wolfe line search
    (reference: optimizer/lbfgs.py:347).  ``step(closure)`` re-evaluates
    the closure during the line search, like the reference."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         False)
        self._max_iter = int(max_iter)
        self._max_eval = int(max_eval) if max_eval is not None else \
            self._max_iter * 5 // 4
        self._tol_grad = float(tolerance_grad)
        self._tol_change = float(tolerance_change)
        self._history_size = int(history_size)
        if line_search_fn not in (None, "strong_wolfe"):
            raise ValueError("line_search_fn must be None or 'strong_wolfe'")
        self._line_search_fn = line_search_fn
        self._hist_s: list = []
        self._hist_y: list = []
        self._prev_flat_grad = None
        self._n_evals = 0

    # -- flat param plumbing ---------------------------------------------
    def _trainable(self):
        return [p for p in (self._parameters or []) if p.trainable]

    def _flat_params(self):
        return np.concatenate([
            np.asarray(p._value, np.float64).reshape(-1)
            for p in self._trainable()])

    def _set_flat_params(self, flat):
        i = 0
        for p in self._trainable():
            n = int(np.prod(p.shape)) if p.shape else 1
            v = flat[i:i + n].reshape(p.shape)
            p._value = jnp.asarray(v, jnp.asarray(p._value).dtype)
            i += n

    def _flat_grad(self):
        outs = []
        for p in self._trainable():
            if p.grad is None:
                outs.append(np.zeros(int(np.prod(p.shape)) or 1))
            else:
                outs.append(np.asarray(p.grad._value,
                                       np.float64).reshape(-1))
        return np.concatenate(outs)

    def step(self, closure=None):
        if closure is None:
            raise RuntimeError("LBFGS.step requires a closure that "
                               "re-evaluates the model and returns the loss")

        def evaluate(flat):
            self._set_flat_params(flat)
            loss = closure()
            self._n_evals += 1
            return float(np.asarray(loss._value)), self._flat_grad()

        x = self._flat_params()
        self._n_evals = 0
        f, g = evaluate(x)
        if np.max(np.abs(g)) <= self._tol_grad:
            return loss_tensor(f)
        lr = float(self.get_lr())

        for _ in range(self._max_iter):
            # two-loop recursion over stored (s, y)
            q = g.copy()
            alphas = []
            for s_i, y_i in zip(reversed(self._hist_s),
                                reversed(self._hist_y)):
                rho = 1.0 / max(float(np.dot(y_i, s_i)), 1e-10)
                a = rho * np.dot(s_i, q)
                alphas.append((a, rho, s_i, y_i))
                q -= a * y_i
            if self._hist_y:
                y_l, s_l = self._hist_y[-1], self._hist_s[-1]
                gamma = float(np.dot(s_l, y_l)) / max(
                    float(np.dot(y_l, y_l)), 1e-10)
                q *= gamma
            for a, rho, s_i, y_i in reversed(alphas):
                b = rho * np.dot(y_i, q)
                q += (a - b) * s_i
            d = -q
            gtd = float(np.dot(g, d))
            if gtd > -1e-15:             # not a descent direction: reset
                d = -g
                self._hist_s.clear()
                self._hist_y.clear()
            t0 = min(1.0, 1.0 / max(np.sum(np.abs(g)), 1e-10)) * lr \
                if not self._hist_s else lr

            if self._line_search_fn == "strong_wolfe":
                t, f_new, g_new = _strong_wolfe(
                    lambda z: evaluate(z), x, d, f, g, t0)
            else:
                t = t0
                f_new, g_new = evaluate(x + t * d)

            x_new = x + t * d
            s_vec = x_new - x
            y_vec = g_new - g
            if float(np.dot(s_vec, y_vec)) > 1e-10:
                self._hist_s.append(s_vec)
                self._hist_y.append(y_vec)
                if len(self._hist_s) > self._history_size:
                    self._hist_s.pop(0)
                    self._hist_y.pop(0)
            x_prev, f_prev = x, f
            x, f, g = x_new, f_new, g_new
            if self._n_evals >= self._max_eval:
                break
            if np.max(np.abs(g)) <= self._tol_grad:
                break
            if np.max(np.abs(x - x_prev)) <= self._tol_change:
                break
            if abs(f - f_prev) <= self._tol_change:
                break

        self._set_flat_params(x)
        self._step_count += 1
        return loss_tensor(f)


def loss_tensor(f):
    from ..core.tensor import Tensor
    return Tensor(jnp.asarray(f, jnp.float32), stop_gradient=True)
