"""Concrete optimizers (reference: python/paddle/optimizer/{sgd,momentum,
adam,adamw,adamax,adagrad,adadelta,rmsprop,lamb}.py — each maps to a fused
phi kernel there; here each is a pure per-param update XLA fuses into one
kernel per parameter, or one whole-step kernel under jit)."""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["SGD", "Momentum", "Adam", "AdamW", "Adamax", "Adagrad",
           "Adadelta", "RMSProp", "Lamb", "NAdam", "RAdam"]

Arr = jax.Array
State = Dict[str, Arr]


class SGD(Optimizer):
    _fused_elementwise = True

    def _update(self, p, g, s, lr, t):
        return p - lr * g, s


class Momentum(Optimizer):
    _fused_elementwise = True

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slot_state(self, v):
        return {"velocity": jnp.zeros_like(v)}

    def _update(self, p, g, s, lr, t):
        v = self._momentum * s["velocity"] + g
        if self._nesterov:
            new_p = p - lr * (g + self._momentum * v)
        else:
            new_p = p - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    _fused_elementwise = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 use_multi_tensor=False, amsgrad=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._amsgrad = amsgrad

    def _init_slot_state(self, v):
        s = {"moment1": jnp.zeros(v.shape, jnp.float32),
             "moment2": jnp.zeros(v.shape, jnp.float32)}
        if self._amsgrad:
            s["moment2_max"] = jnp.zeros(v.shape, jnp.float32)
        return s

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** tf)
        vv = v
        ns = {"moment1": m, "moment2": v}
        if self._amsgrad:
            vv = jnp.maximum(s["moment2_max"], v)
            ns["moment2_max"] = vv
        vhat = vv / (1 - self._beta2 ** tf)
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), ns


class AdamW(Adam):
    """Decoupled weight decay (reference: python/paddle/optimizer/adamw.py)."""

    # the apply_gradients override below is fully captured by the fused
    # hooks (_fused_decay_coeff + _fused_pre_update), so the fused path
    # may bypass it
    _fused_handles_apply = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, amsgrad=False,
                 name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision,
                         amsgrad=amsgrad)
        self._coeff = float(weight_decay) if weight_decay is not None else 0.0
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_applies(self, name):
        if self._apply_decay_param_fun is not None:
            return self._apply_decay_param_fun(name)
        return True

    def _fused_decay_coeff(self):
        return self._coeff

    def _fused_pre_update(self, flat_work, lr, decay):
        # decoupled decay on the flattened working (master-or-param)
        # buffer: p *= (1 - lr*coeff), cast back like the per-param path
        if decay and self._coeff:
            return (flat_work.astype(jnp.float32)
                    * (1.0 - lr * self._coeff)).astype(flat_work.dtype)
        return flat_work

    def apply_gradients(self, params, grads, state, lr, step):
        # decoupled decay: p *= (1 - lr*coeff) before the adam update
        if self._coeff:
            lrv = jnp.asarray(lr, jnp.float32)
            decayed = {}
            for name, p in params.items():
                if name in grads and grads[name] is not None and \
                        self._decay_applies(name):
                    decayed[name] = (p.astype(jnp.float32)
                                     * (1.0 - lrv * self._coeff)).astype(p.dtype)
                    ms = state.get(name, {}).get("master_weight")
                    if ms is not None:
                        state[name]["master_weight"] = ms * (1.0 - lrv * self._coeff)
                else:
                    decayed[name] = p
            params = decayed
        return super().apply_gradients(params, grads, state, lr, step)


class Adamax(Optimizer):
    _fused_elementwise = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon

    def _init_slot_state(self, v):
        return {"moment": jnp.zeros(v.shape, jnp.float32),
                "inf_norm": jnp.zeros(v.shape, jnp.float32)}

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * s["moment"] + (1 - self._beta1) * g32
        u = jnp.maximum(self._beta2 * s["inf_norm"], jnp.abs(g32))
        tf = t.astype(jnp.float32)
        upd = lr / (1 - self._beta1 ** tf) * m / (u + self._eps)
        return (p.astype(jnp.float32) - upd).astype(p.dtype), \
            {"moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    _fused_elementwise = True

    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slot_state(self, v):
        return {"moment": jnp.full(v.shape, self._init_acc, jnp.float32)}

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        acc = s["moment"] + jnp.square(g32)
        new_p = p.astype(jnp.float32) - lr * g32 / (jnp.sqrt(acc) + self._eps)
        return new_p.astype(p.dtype), {"moment": acc}


class Adadelta(Optimizer):
    _fused_elementwise = True

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._eps = epsilon
        self._rho = rho

    def _init_slot_state(self, v):
        return {"avg_squared_grad": jnp.zeros(v.shape, jnp.float32),
                "avg_squared_update": jnp.zeros(v.shape, jnp.float32)}

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        asg = self._rho * s["avg_squared_grad"] + (1 - self._rho) * jnp.square(g32)
        upd = (jnp.sqrt(s["avg_squared_update"] + self._eps)
               / jnp.sqrt(asg + self._eps)) * g32
        asu = self._rho * s["avg_squared_update"] + (1 - self._rho) * jnp.square(upd)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"avg_squared_grad": asg, "avg_squared_update": asu}


class RMSProp(Optimizer):
    _fused_elementwise = True

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self._rho = rho
        self._eps = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_slot_state(self, v):
        s = {"mean_square": jnp.zeros(v.shape, jnp.float32),
             "momentum": jnp.zeros(v.shape, jnp.float32)}
        if self._centered:
            s["mean_grad"] = jnp.zeros(v.shape, jnp.float32)
        return s

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        ms = self._rho * s["mean_square"] + (1 - self._rho) * jnp.square(g32)
        ns = {"mean_square": ms}
        denom = ms
        if self._centered:
            mg = self._rho * s["mean_grad"] + (1 - self._rho) * g32
            denom = ms - jnp.square(mg)
            ns["mean_grad"] = mg
        mom = self._momentum * s["momentum"] + lr * g32 / jnp.sqrt(
            denom + self._eps)
        ns["momentum"] = mom
        return (p.astype(jnp.float32) - mom).astype(p.dtype), ns


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip)
        self._wd = lamb_weight_decay
        self._beta1 = beta1
        self._beta2 = beta2
        self._eps = epsilon
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_slot_state(self, v):
        return {"moment1": jnp.zeros(v.shape, jnp.float32),
                "moment2": jnp.zeros(v.shape, jnp.float32)}

    def apply_gradients(self, params, grads, state, lr, step):
        # per-name exclusion needs the param NAME (reference lamb.py
        # exclude_from_weight_decay_fn), so run the loop here
        if self._grad_clip is not None:
            grads = self._grad_clip.apply_values(grads)
        lr = jnp.asarray(lr, jnp.float32)
        t = jnp.asarray(step, jnp.int32)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state.get(name, {})
                continue
            wd = 0.0 if (self._exclude_fn is not None
                         and self._exclude_fn(name)) else self._wd
            s = dict(state.get(name, {}))
            new_params[name], new_state[name] = self._lamb_update(
                p, g, s, lr, t, wd)
        return new_params, new_state

    def _lamb_update(self, p, g, s, lr, t, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        mhat = m / (1 - self._beta1 ** tf)
        vhat = v / (1 - self._beta2 ** tf)
        r = mhat / (jnp.sqrt(vhat) + self._eps) + wd * p32
        w_norm = jnp.linalg.norm(p32)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return (p32 - lr * trust * r).astype(p.dtype), \
            {"moment1": m, "moment2": v}

    def _update(self, p, g, s, lr, t):          # functional-API fallback
        return self._lamb_update(p, g, s, lr, t, self._wd)


class NAdam(Adam):
    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        mhat = (self._beta1 * m + (1 - self._beta1) * g32) / (
            1 - self._beta1 ** tf)
        vhat = v / (1 - self._beta2 ** tf)
        upd = mhat / (jnp.sqrt(vhat) + self._eps)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"moment1": m, "moment2": v}


class RAdam(Adam):
    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        m = self._beta1 * s["moment1"] + (1 - self._beta1) * g32
        v = self._beta2 * s["moment2"] + (1 - self._beta2) * jnp.square(g32)
        tf = t.astype(jnp.float32)
        rho_inf = 2.0 / (1 - self._beta2) - 1
        beta2t = self._beta2 ** tf
        rho = rho_inf - 2 * tf * beta2t / (1 - beta2t)
        mhat = m / (1 - self._beta1 ** tf)

        def rect(_):
            r = jnp.sqrt(((rho - 4) * (rho - 2) * rho_inf)
                         / ((rho_inf - 4) * (rho_inf - 2) * rho))
            vhat = jnp.sqrt(v / (1 - beta2t))
            return r * mhat / (vhat + self._eps)

        upd = jax.lax.cond(rho > 5.0, rect, lambda _: mhat, None)
        return (p.astype(jnp.float32) - lr * upd).astype(p.dtype), \
            {"moment1": m, "moment2": v}
