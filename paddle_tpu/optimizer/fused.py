"""Multi-tensor (fused) optimizer apply.

Reference: python/paddle/optimizer/{adam,momentum}.py ``use_multi_tensor``
(``_multi_tensor_init`` buckets params by dtype/regularization into
``_param_dict['FP32_LODTensor']``-style groups, then issues ONE
``multi_tensor_adam``/``merged_momentum`` op per group instead of one op
per parameter).

TPU-native translation: a per-parameter Python update loop costs one XLA
op-subgraph per parameter — hundreds of tiny element-wise kernels plus the
Python dispatch to build them every trace.  Because every supported update
rule is ELEMENT-WISE (Adam/AdamW/Momentum/SGD moment+param math touches
each element independently), applying the rule to the CONCATENATION of a
bucket's parameters is bit-identical to applying it per parameter.  So:

* bucket parameters by (dtype, weight-decay-applies, master-weight-ness,
  slot-key set) — the static facts that change the update expression;
* flatten each bucket into one 1-D buffer per role (param, grad, each slot)
  with an index map (name → offset/size/shape) reused across steps;
* run the optimizer's ``_update`` ONCE per bucket;
* slice the results back out per parameter.

Global-norm gradient clipping becomes a single fused reduction over the
bucket buffers instead of one reduction per parameter.

The win comes from the flat buffers PERSISTING across steps: the returned
optimizer state is in **fused form** — ``{"@fused": {"b0": {slot: flat}},
"@passthrough": {...}}`` — so the next step consumes the flat moment
buffers directly (no per-step re-concatenation of optimizer state; on CPU
this is what turns a ~0.7x slowdown into a ~3x win over the per-param
loop).  ``Optimizer.unflatten_state`` recovers the per-name slot dicts for
checkpointing/interop.

The fused path refuses (returns ``None``) whenever any parameter carries
exotic state — slot keys or shapes that do not match the optimizer's
canonical ``_init_slot_state`` layout — and the caller falls back to the
per-parameter path, keeping correctness for restored/hand-edited state.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["FusedPlan", "build_fused_plan", "apply_fused",
           "is_fused_state", "unflatten_state", "flatten_state",
           "FUSED_STATE_KEY", "PASSTHROUGH_KEY"]

#: reserved keys marking the flat (fused) optimizer-state representation
FUSED_STATE_KEY = "@fused"
PASSTHROUGH_KEY = "@passthrough"


def is_fused_state(state) -> bool:
    return isinstance(state, dict) and FUSED_STATE_KEY in state


class _Bucket:
    __slots__ = ("names", "shapes", "sizes", "offsets", "dtype",
                 "grad_dtype", "decay", "has_master", "slot_keys", "total")

    def __init__(self, dtype: str, grad_dtype: str, decay: bool,
                 has_master: bool, slot_keys: Tuple[str, ...]):
        self.dtype = dtype
        self.grad_dtype = grad_dtype
        self.decay = decay
        self.has_master = has_master
        self.slot_keys = slot_keys
        self.names: List[str] = []
        self.shapes: List[Tuple[int, ...]] = []
        self.sizes: List[int] = []
        self.offsets: List[int] = []
        self.total = 0

    def add(self, name: str, shape: Tuple[int, ...]) -> None:
        size = 1
        for d in shape:
            size *= int(d)
        self.names.append(name)
        self.shapes.append(shape)
        self.sizes.append(size)
        self.offsets.append(self.total)
        self.total += size


class FusedPlan:
    """Static bucketing of one (params, grads, state) signature."""

    __slots__ = ("buckets", "passthrough")

    def __init__(self, buckets: List[_Bucket], passthrough: List[str]):
        self.buckets = buckets
        self.passthrough = passthrough


def _canonical_slots(opt, p) -> Optional[Dict[str, Tuple[int, ...]]]:
    """Slot keys/shapes ``_init_slot_state`` would create for ``p`` —
    evaluated abstractly (no allocation, trace-safe)."""
    try:
        out = jax.eval_shape(opt._init_slot_state,
                             jax.ShapeDtypeStruct(p.shape, p.dtype))
    except Exception:
        return None
    if not isinstance(out, dict):
        return None
    return {k: (tuple(v.shape), v.dtype) for k, v in out.items()}


def _plan_signature(params, grads, state, decay_flags) -> Tuple:
    sig = []
    for name in sorted(params):
        p = params[name]
        slots = state.get(name, {})
        sig.append((name, tuple(p.shape), str(p.dtype),
                    grads.get(name) is not None,
                    tuple(sorted((k, tuple(v.shape), str(v.dtype))
                                 for k, v in slots.items())),
                    decay_flags.get(name, False)))
    return tuple(sig)


def build_fused_plan(opt, params, grads, state) -> Optional[FusedPlan]:
    """Bucket the parameter set; ``None`` when any param is unfusable.

    All-or-nothing: a partially fused step would have to re-implement the
    (possibly subclass-overridden) per-param semantics for the leftovers
    AND split global-norm clipping across both halves — the per-param
    fallback is simpler and only pays on exotic state.
    """
    decay_active = bool(opt._fused_decay_coeff())
    decay_flags = {n: (decay_active and opt._decay_applies(n))
                   for n in params}
    sig = _plan_signature(params, grads, state, decay_flags)
    cache = getattr(opt, "_fused_plan_cache", None)
    if cache is None:
        cache = opt._fused_plan_cache = {}
    if sig in cache:
        return cache[sig]
    if len(cache) > 64:      # plans are tiny; this only guards pathology
        cache.clear()

    buckets: Dict[Tuple, _Bucket] = {}
    passthrough: List[str] = []
    plan: Optional[FusedPlan] = None
    # sorted iteration: jit reconstructs dict inputs in sorted-key order,
    # eager callers pass insertion order — sorting makes the plan (and so
    # the fused-state layout) identical in both contexts
    for name in sorted(params):
        p = params[name]
        if grads.get(name) is None:
            passthrough.append(name)
            continue
        slots = state.get(name, {})
        canonical = _canonical_slots(opt, p)
        if canonical is None:
            break
        has_master = "master_weight" in slots
        expected = set(canonical) | ({"master_weight"} if has_master
                                     else set())
        if set(slots) != expected:
            break           # exotic/restored state → per-param fallback
        pshape = tuple(p.shape)
        if any(shape != pshape or slots[k].dtype != dt
               for k, (shape, dt) in canonical.items()):
            break   # non-canonical slot shape/dtype (e.g. rowwise, or a
            #         checkpoint restored at a different precision)
        if has_master and (tuple(slots["master_weight"].shape) != pshape
                           or slots["master_weight"].dtype != jnp.float32):
            break
        key = (str(p.dtype), str(grads[name].dtype), decay_flags[name],
               has_master, tuple(sorted(canonical)))
        b = buckets.get(key)
        if b is None:
            b = buckets[key] = _Bucket(*key)
        b.add(name, pshape)
    else:
        plan = FusedPlan(list(buckets.values()), passthrough)
    cache[sig] = plan
    return plan


def _flatten(arrays) -> jax.Array:
    flats = [a.reshape(-1) for a in arrays]
    return flats[0] if len(flats) == 1 else jnp.concatenate(flats)


def _unflatten(flat: jax.Array, bucket: _Bucket):
    for name, off, size, shape in zip(bucket.names, bucket.offsets,
                                      bucket.sizes, bucket.shapes):
        yield name, flat[off:off + size].reshape(shape)


def _clip_fused(opt, plan: FusedPlan, bucket_grads: List[jax.Array],
                grads: Dict[str, jax.Array]) -> List[jax.Array]:
    """Gradient clipping over the flattened buckets.  Global-norm clip is
    ONE fused reduction chain; per-tensor clips reuse ``apply_values`` on
    the original dict and re-flatten."""
    from ..nn.clip import ClipGradByGlobalNorm

    clip = opt._grad_clip
    if clip is None:
        return bucket_grads
    if isinstance(clip, ClipGradByGlobalNorm):
        total = jnp.zeros((), jnp.float32)
        for fg in bucket_grads:
            total = total + jnp.sum(jnp.square(fg.astype(jnp.float32)))
        if clip.group_norm_fn is not None:
            total = clip.group_norm_fn(total)
        gn = jnp.sqrt(total)
        scale = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
        return [(fg.astype(jnp.float32) * scale).astype(fg.dtype)
                for fg in bucket_grads]
    active = {n: grads[n] for b in plan.buckets for n in b.names}
    clipped = clip.apply_values(active)
    return [_flatten([clipped[n] for n in b.names]) for b in plan.buckets]


def _state_matches(plan: FusedPlan, state: Dict[str, Any]) -> bool:
    fused = state.get(FUSED_STATE_KEY, {})
    if len(fused) != len(plan.buckets):
        return False
    for i, b in enumerate(plan.buckets):
        bstate = fused.get(f"b{i}")
        if bstate is None:
            return False
        expected = set(b.slot_keys) | ({"master_weight"} if b.has_master
                                       else set())
        if set(bstate) != expected:
            return False
        if any(v.shape != (b.total,) for v in bstate.values()):
            return False
    return True


def unflatten_state(plan: FusedPlan, state: Dict[str, Any]
                    ) -> Dict[str, Any]:
    """Fused state → the per-name slot dicts ``init_state`` would give."""
    out = {n: dict(s) for n, s in state.get(PASSTHROUGH_KEY, {}).items()}
    for i, b in enumerate(plan.buckets):
        bstate = state[FUSED_STATE_KEY][f"b{i}"]
        per = {name: {} for name in b.names}
        for k, flat_s in bstate.items():
            for name, val in _unflatten(flat_s, b):
                per[name][k] = val
        out.update(per)
    return out


def flatten_state(plan: FusedPlan, state: Dict[str, Any]
                  ) -> Dict[str, Any]:
    """Per-name slot dicts → fused form under ``plan`` (inverse of
    :func:`unflatten_state`).  Trace-compatible: used by the anomaly
    step-guard to express "state unchanged" in fused layout on the very
    first step, whose input state is still per-name while the computed
    output is already flat."""
    fused: Dict[str, Dict[str, Any]] = {}
    for i, b in enumerate(plan.buckets):
        keys = set(b.slot_keys) | ({"master_weight"} if b.has_master
                                   else set())
        fused[f"b{i}"] = {k: _flatten([state[n][k] for n in b.names])
                          for k in keys}
    return {FUSED_STATE_KEY: fused,
            PASSTHROUGH_KEY: {n: dict(state.get(n, {}))
                              for n in plan.passthrough}}


def apply_fused(opt, params: Dict[str, Any], grads: Dict[str, Any],
                state: Dict[str, Any], lr, step
                ) -> Optional[Tuple[Dict[str, Any], Dict[str, Any]]]:
    """Fused multi-tensor update; ``None`` → caller uses the per-param
    path.  Accepts per-name OR fused state; always RETURNS fused state
    (flat slot buffers persist across steps — the per-step cost is one
    concat of params + grads and one slice-out of params, while moments
    never leave flat form).  Numerics match the per-param path exactly
    except for the global-norm reduction order under grad clipping
    (documented in docs/performance.md)."""
    fused_in = is_fused_state(state)
    if fused_in:
        plan = getattr(opt, "_fused_active_plan", None)
        if plan is None or not _state_matches(plan, state):
            raise ValueError(
                "fused optimizer state does not match this optimizer's "
                "active plan; rebuild per-name state (unflatten_state) "
                "before changing the parameter set")
    else:
        plan = build_fused_plan(opt, params, grads, state)
        if plan is None or not plan.buckets:
            return None
    opt._fused_active_plan = plan
    lr = jnp.asarray(lr, jnp.float32)
    t = jnp.asarray(step, jnp.int32)
    wd = opt._wd_coeff()

    new_params: Dict[str, Any] = {}
    pass_state: Dict[str, Any] = {}
    for n in plan.passthrough:
        new_params[n] = params[n]
        if fused_in:
            pass_state[n] = state.get(PASSTHROUGH_KEY, {}).get(n, {})
        else:
            pass_state[n] = state.get(n, {})

    bucket_grads = [_flatten([grads[n] for n in b.names])
                    for b in plan.buckets]
    bucket_grads = _clip_fused(opt, plan, bucket_grads, grads)

    fused_out: Dict[str, Dict[str, Any]] = {}
    for i, (b, flat_g) in enumerate(zip(plan.buckets, bucket_grads)):
        flat_p = _flatten([params[n] for n in b.names])
        if fused_in:
            bstate = state[FUSED_STATE_KEY][f"b{i}"]
            flat_slots = {k: bstate[k] for k in b.slot_keys}
            work = bstate["master_weight"] if b.has_master else flat_p
        else:
            flat_slots = {k: _flatten([state[n][k] for n in b.names])
                          for k in b.slot_keys}
            work = (_flatten([state[n]["master_weight"]
                              for n in b.names])
                    if b.has_master else flat_p)
        g_w = flat_g.astype(work.dtype)
        if wd and b.decay:
            g_w = g_w + wd * work
        work = opt._fused_pre_update(work, lr, b.decay)
        new_work, new_slots = opt._update(work, g_w, flat_slots, lr, t)
        new_slots = dict(new_slots)
        if b.has_master:
            new_slots["master_weight"] = new_work
            out_flat = new_work.astype(flat_p.dtype)
        else:
            out_flat = new_work
        for name, val in _unflatten(out_flat, b):
            new_params[name] = val
        fused_out[f"b{i}"] = new_slots
    return new_params, {FUSED_STATE_KEY: fused_out,
                        PASSTHROUGH_KEY: pass_state}
