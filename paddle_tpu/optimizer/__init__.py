from . import lr  # noqa: F401
from .optimizer import Optimizer  # noqa: F401
from .extra import ASGD, LBFGS, Rprop  # noqa: F401
from .meta import DGCMomentum, DistributedFusedLamb, LarsMomentum, LocalSGD  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Adadelta, Adagrad, Adam, Adamax, AdamW, Lamb, Momentum, NAdam,
    RAdam, RMSProp,
)
