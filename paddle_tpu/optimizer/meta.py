"""Meta-optimizers: LARS, DGC, LocalSGD (reference:
python/paddle/incubate/optimizer/{lars_momentum?}, fleet/meta_optimizers/
{lars,dgc,localsgd}_optimizer.py + phi dgc kernels dgc_kernel.h).

TPU-native shapes:
- LARS is a plain per-param update (layerwise trust ratio on Momentum) —
  rides the base class's jitted ``apply_gradients``.
- DGC (Deep Gradient Compression, Lin et al. 2018) keeps momentum +
  residual accumulators and sends only the top-k% gradient entries each
  step.  Under a single-controller mesh the "send" IS the sparsification:
  the dense update applies ``mask * accumulated``, exactly the
  reference kernel's semantics (dgc_kernel.h: top-k threshold select,
  residual carry), and XLA's all-reduce then moves a mostly-zero tensor
  (the wire win appears under real multi-host DP).
- LocalSGD trains k local steps then averages params over the dp axis
  (fleet/meta_optimizers/localsgd_optimizer.py) — here a wrapper that
  calls ``paddle.distributed.all_reduce`` on params every k steps.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from .optimizer import Optimizer

__all__ = ["LarsMomentum", "DGCMomentum", "LocalSGD", "DistributedFusedLamb"]


class LarsMomentum(Optimizer):
    """LARS (You et al. 2017; reference fleet lars_optimizer +
    lars_momentum kernel): per-layer lr = base_lr * coeff * ||w|| /
    (||g|| + wd * ||w||)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9,
                 exclude_from_weight_decay=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self._momentum = momentum
        self._coeff = lars_coeff
        self._lars_wd = lars_weight_decay
        self._eps = epsilon
        self._exclude = tuple(exclude_from_weight_decay or ())

    def _init_slot_state(self, v):
        return {"velocity": jnp.zeros(v.shape, jnp.float32)}

    def apply_gradients(self, params, grads, state, lr, step):
        # per-param weight-decay exclusion (reference lars_optimizer
        # exclude_from_weight_decay) needs the param NAME, which the base
        # loop doesn't pass to _update — so run the loop here
        if self._grad_clip is not None:
            grads = self._grad_clip.apply_values(grads)
        lr = jnp.asarray(lr, jnp.float32)
        new_params, new_state = {}, {}
        for name, p in params.items():
            g = grads.get(name)
            if g is None:
                new_params[name] = p
                new_state[name] = state.get(name, {})
                continue
            wd = 0.0 if any(tok in name for tok in self._exclude) \
                else self._lars_wd
            s = dict(state.get(name, {}))
            new_params[name], new_state[name] = self._lars_update(
                p, g, s, lr, wd)
        return new_params, new_state

    def _lars_update(self, p, g, s, lr, wd):
        # multi_precision: compute from / update the fp32 master weight
        p32 = s["master_weight"] if "master_weight" in s \
            else p.astype(jnp.float32)
        g32 = g.astype(jnp.float32)
        w_norm = jnp.linalg.norm(p32)
        g_norm = jnp.linalg.norm(g32)
        trust = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._coeff * w_norm / (g_norm + wd * w_norm + self._eps),
            1.0)
        local_lr = lr * trust
        v = self._momentum * s["velocity"] + local_lr * (g32 + wd * p32)
        new_p32 = p32 - v
        out_s = {"velocity": v}
        if "master_weight" in s:
            out_s["master_weight"] = new_p32
        return new_p32.astype(p.dtype), out_s

    def _update(self, p, g, s, lr, t):          # functional-API fallback
        return self._lars_update(p, g, s, lr, self._lars_wd)


class DGCMomentum(Optimizer):
    """Deep Gradient Compression momentum (reference
    fleet/meta_optimizers/dgc_optimizer.py + phi/kernels/dgc_kernel.h):
    momentum correction + residual accumulation + top-k sparsification.

    ``sparsity`` is the DROP ratio per step (0.999 = send top 0.1%),
    ramped via ``rampup_begin_step``.  The update applies only the
    selected entries; unselected ones stay in the residual accumulators
    (u, v) for later steps."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 sparsity=(0.999,), rampup_begin_step=0, parameters=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, False)
        self._momentum = momentum
        self._sparsity = float(sparsity[-1] if isinstance(
            sparsity, (tuple, list)) else sparsity)
        self._rampup_begin = int(rampup_begin_step)

    def _init_slot_state(self, v):
        return {"u": jnp.zeros(v.shape, jnp.float32),    # momentum carry
                "v": jnp.zeros(v.shape, jnp.float32)}    # residual carry

    def _update(self, p, g, s, lr, t):
        g32 = g.astype(jnp.float32)
        u = self._momentum * s["u"] + g32            # momentum correction
        acc = s["v"] + u                             # residual accumulate
        n = acc.size
        k = max(1, int(n * (1.0 - self._sparsity)))
        flat = jnp.abs(acc.reshape(-1))
        # threshold = k-th largest |acc| (dgc_kernel.h top-k select)
        thresh = jax.lax.top_k(flat, k)[0][-1]
        mask = (jnp.abs(acc) >= thresh)
        ramped = t > self._rampup_begin
        mask = jnp.where(ramped, mask, jnp.ones_like(mask))
        send = jnp.where(mask, acc, 0.0)             # the "communicated" part
        new_v = jnp.where(mask, 0.0, acc)            # residual stays local
        new_u = jnp.where(mask, 0.0, u)              # momentum factor mask
        new_p = p.astype(jnp.float32) - lr * send
        return new_p.astype(p.dtype), {"u": new_u, "v": new_v}


class LocalSGD:
    """LocalSGD wrapper (reference fleet/meta_optimizers/
    localsgd_optimizer.py): run the inner optimizer for ``k_steps`` local
    steps, then average parameters across the dp group."""

    def __init__(self, inner: Optimizer, k_steps: int = 4, group=None):
        self._inner = inner
        self._k = int(k_steps)
        self._group = group
        self._local_steps = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def step(self):
        self._inner.step()
        self._local_steps += 1
        if self._local_steps % self._k == 0:
            self._sync_params()

    def _sync_params(self):
        from ..parallel import collective as C
        from ..parallel.env import get_world_size
        try:
            world = get_world_size(self._group)
        except TypeError:
            world = get_world_size()
        if world <= 1:
            return
        for p in self._inner._parameters or []:
            C.all_reduce(p, group=self._group)
            p._value = p._value / world

    def minimize(self, loss, **kw):
        loss.backward()
        self.step()
        return None, []


class DistributedFusedLamb(__import__(
        "paddle_tpu.optimizer.optimizers",
        fromlist=["Lamb"]).Lamb):
    """Sharded multi-tensor LAMB (reference:
    incubate/optimizer/distributed_fused_lamb.py + fusion/gpu/
    distributed_fused_lamb_init_kernel.cu).

    The reference flattens all params into fused fp16/fp32 buffers sharded
    across the dp group, runs one fused LAMB kernel per shard, and
    all-gathers updated params.  TPU-native: the jitted
    ``apply_gradients`` already runs the whole update as one XLA program,
    and sharding the optimizer states over the mesh is ZeRO (the sharding
    axis in DistributedEngine) — so this subclass only widens the
    constructor to the reference's surface; the LAMB math lives once, in
    :class:`~paddle_tpu.optimizer.optimizers.Lamb`."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 clip_after_allreduce=True, is_grad_scaled_by_nranks=True,
                 use_master_param_norm=True, gradient_accumulation_steps=1,
                 use_master_acc_grad=True, nproc_per_node=None, name=None):
        super().__init__(learning_rate, lamb_weight_decay, beta1, beta2,
                         epsilon, parameters, grad_clip,
                         exclude_from_weight_decay_fn, name)
