"""Speculation knobs: which draft model, how far to speculate.

The config is engine-level (one draft serves every request in the
batch) because the verify program is specialized to ``[max_batch, k+1]``
— per-request K would mean one compiled program per distinct K, exactly
the shape churn the AOT subsystem exists to kill.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Dict, Optional

__all__ = ["SpecDecodeConfig"]


@dataclass
class SpecDecodeConfig:
    """Draft/verify speculation parameters.

    draft_cfg / draft_params:
        A Llama-family config + param pytree (``wte``/``head``/``lnf_w``
        + stacked ``blocks``, the train-step layout) for the DRAFT
        model.  Must share the target's vocabulary — draft token ids
        are fed straight into the target's verify program.  The draft
        runs as a windowed dense recompute (``draft.py``), so it needs
        no KV pool of its own and no per-request state; a cancel or
        rollback costs nothing on the draft side.
    k:
        Draft tokens proposed per engine step (the verify width is
        ``k + 1``: the fed token plus k proposals).
    window:
        Draft context window in tokens.  The draft re-reads only the
        last ``window`` tokens of prompt+output each proposal — a
        fixed ``[max_batch, window]`` geometry, one compiled program.
    enabled:
        Master switch; False constructs the runner but decodes through
        the baseline single-token step (A/B and incident rollback knob).
    """

    draft_cfg: Any
    draft_params: Any
    k: int = 4
    window: int = 16
    enabled: bool = True

    def __post_init__(self):
        if self.k < 1:
            raise ValueError(f"spec_decode k must be >= 1, got {self.k}")
        if self.window < 2:
            raise ValueError(
                f"spec_decode window must be >= 2, got {self.window} "
                "(the draft needs at least the fed token plus context)")

    def validate_against(self, target_cfg) -> None:
        """The one compatibility rule that matters: token ids the draft
        emits must mean the same thing to the target."""
        if self.draft_cfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"draft vocab ({self.draft_cfg.vocab_size}) != target "
                f"vocab ({target_cfg.vocab_size}) — speculative proposals "
                "would be meaningless token ids")
        if (self.draft_cfg.max_position_embeddings
                < target_cfg.max_position_embeddings):
            raise ValueError(
                "draft max_position_embeddings "
                f"({self.draft_cfg.max_position_embeddings}) < target's "
                f"({target_cfg.max_position_embeddings}) — the windowed "
                "draft rotates by ABSOLUTE position, so its RoPE table "
                "must cover every position the target can serve")

    def manifest(self) -> Dict[str, Any]:
        """The spec geometry an AOT artifact is specialized to (the
        draft PARAM VALUES ride in the signature check, not the hash)."""
        return {
            "k": self.k,
            "window": self.window,
            "draft_model": dataclasses.asdict(self.draft_cfg),
        }
