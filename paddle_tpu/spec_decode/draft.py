"""The draft model: a windowed dense-recompute Llama forward.

Proposing K tokens per engine step must not introduce per-request
state (a draft KV pool would need its own paging, rollback, and leak
accounting) or shape churn (one program per context length is the
compile storm AOT kills).  So the draft is STATELESS: each proposal
re-runs a small dense forward over the last ``window`` tokens of
prompt+output, right-aligned in a fixed ``[max_batch, window]`` buffer
— one compiled geometry for the whole serve lifetime, exported next to
the decode step by ``aot/serve.py``.  Recompute is the right trade at
draft scale: the draft exists because it is tiny, and ``window`` is
small (default 16), so a proposal costs one [B, W] forward of a model
chosen to be ~10x smaller than the target.

The window is assembled host-side (``assemble_windows``): row ``b``
holds the last ``min(ctx_b, W)`` tokens right-aligned, zero-padded on
the left; positions and the causal+validity mask come from ``ctx_lens``
inside the traced program, so RoPE phases match the tokens' ABSOLUTE
positions (a left-truncated window still rotates token t by angle(t)).
"""

from __future__ import annotations

from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["build_draft_program", "assemble_windows"]


def build_draft_program(cfg, window: int):
    """Returns ``draft(params, win [B, W] int32, ctx_lens [B] int32) ->
    proposals [B] int32``: the greedy next token at each row's last
    valid slot.  The argmax lives INSIDE the program (not an op-by-op
    host call) so a warm-started engine drafts with zero backend
    compiles — and only ``[B]`` ints cross the host boundary per
    proposal, not ``[B, V]`` logits.  Rows with ``ctx_lens == 0``
    (inactive engine slots) produce garbage tokens the scheduler never
    reads."""
    from ..inference.serving import _make_rms_ffn
    from ..models.generation import _dense_masked_attention
    from ..models.llama import _rope_cos_sin, _rotate_half
    W = window
    H, Hkv, D = cfg.num_heads, cfg.kv_heads, cfg.head_dim
    cos_full, sin_full = _rope_cos_sin(
        cfg.max_position_embeddings, D, cfg.rope_theta,
        jnp.dtype(cfg.dtype), getattr(cfg, "rope_scaling", None))
    scale = 1.0 / (D ** 0.5)
    rms, ffn = _make_rms_ffn(cfg)

    def draft(params, win, ctx_lens):
        from ..models.generation import _collapse_blocks
        B = win.shape[0]
        blocks = _collapse_blocks(params["blocks"])
        # slot i of the window holds absolute position ctx - W + i;
        # pad slots clamp to 0 and are masked out below
        pos = jnp.maximum(
            ctx_lens[:, None] - W + jnp.arange(W)[None, :], 0)  # [B, W]
        valid = jnp.arange(W)[None, :] >= (W - ctx_lens[:, None])
        x = jnp.take(params["wte"], win, axis=0)               # [B, W, h]
        cos = jnp.take(cos_full, pos, axis=0)                  # [B, W, D]
        sin = jnp.take(sin_full, pos, axis=0)
        # causal within the window AND both ends valid
        causal = jnp.tril(jnp.ones((W, W), bool))
        mask = (causal[None, None] & valid[:, None, None, :]
                & valid[:, None, :, None])                     # [B,1,W,W]

        def rope(t):                                           # [B,W,*,D]
            return t * cos[:, :, None, :] \
                + _rotate_half(t) * sin[:, :, None, :]

        def body(carry, lp):
            x = carry
            y = rms(x, lp["ln1_w"])
            q = (y @ lp["q_w"]).reshape(B, W, H, D)
            k = (y @ lp["k_w"]).reshape(B, W, Hkv, D)
            v = (y @ lp["v_w"]).reshape(B, W, Hkv, D)
            q, k = rope(q), rope(k)
            attn = _dense_masked_attention(q, k, v, mask, scale)
            x = x + attn.reshape(B, W, -1) @ lp["o_w"]
            x = x + ffn(lp, rms(x, lp["ln2_w"]))
            return x, None

        x, _ = jax.lax.scan(body, x, blocks)
        xf = rms(x[:, -1], params["lnf_w"])                    # last slot
        logits = jnp.einsum("bh,hv->bv", xf, params["head"],
                            preferred_element_type=jnp.float32)
        return jnp.argmax(logits, -1).astype(jnp.int32)

    return draft


def assemble_windows(seqs: Sequence[Sequence[int]], window: int,
                     max_batch: int) -> tuple:
    """Host-side window packing: ``(win [max_batch, W] int32,
    ctx_lens [max_batch] int32)`` from per-slot token sequences (empty
    sequence = inactive slot)."""
    win = np.zeros((max_batch, window), np.int32)
    ctx = np.zeros((max_batch,), np.int32)
    for b, seq in enumerate(seqs):
        n = len(seq)
        ctx[b] = n
        if n == 0:
            continue
        tail: List[int] = list(seq[-window:])
        win[b, window - len(tail):] = np.asarray(tail, np.int32)
    return win, ctx
