"""Host-side draft/verify/commit orchestration for one engine.

One :class:`SpecDecodeRunner` hangs off a
``ContinuousBatchingEngine`` (constructed when ``spec_config=`` is
passed) and replaces the engine's single-token decode iteration:

    draft xK  ──►  verify (one [B, K+1] dispatch)  ──►  commit/rollback

Commit is per-slot host logic: greedy slots accept a proposal iff it
equals the target argmax at that position (bit-identical stream —
verify logits ARE baseline step logits, see ``verify.py``); sampled
slots run the rejection chain of ``sampling.py`` against the warped
target law.  Emission respects the exact baseline stop rules (first
EOS, ``max_new_tokens``) token by token, so the streaming front-end
never sees a token the baseline would not have streamed.

State machine per decode iteration (docs/spec_decode.md):

    DRAFT    k greedy proposals per active slot (windowed recompute;
             inactive slots ride along as masked rows)
    VERIFY   one fixed-width program writes K+1 KV positions per slot
             and returns the K+1 next-token logit rows
    COMMIT   per slot: accepted prefix + one correction/bonus token is
             appended (stopping at EOS/budget); ``lengths`` advances by
             exactly the appended count
    ROLLBACK the rejected tail's KV writes sit beyond the committed
             length: masked by every later attention, overwritten by
             the next append — pages stay owned by the slot, so the
             refcount pool never moves on rollback (``kv_leak_report``
             stays zero through cancels mid-speculation, pinned)
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .config import SpecDecodeConfig
from .draft import assemble_windows, build_draft_program
from .sampling import spec_sample_chain, warp_probs
from .verify import build_verify_program

__all__ = ["SpecDecodeRunner"]


class SpecDecodeRunner:
    """Speculative decode driver bound to one engine instance."""

    def __init__(self, engine, config: SpecDecodeConfig, *,
                 draft_fn=None, verify_fn=None):
        config.validate_against(engine.cfg)
        self.engine = engine
        self.config = config
        # AOT warm start hands in deserialized executables; otherwise
        # jit lazily (an engine that never decodes never compiles them)
        self._draft_fn = draft_fn
        self._verify_fn = verify_fn
        self.stats: Dict[str, int] = {
            "spec_steps": 0, "proposed": 0, "accepted": 0,
            "emitted": 0, "rollback_pages": 0,
        }

    # -- compiled programs ---------------------------------------------
    def draft_fn(self):
        if self._draft_fn is None:
            self._draft_fn = jax.jit(build_draft_program(
                self.config.draft_cfg, self.config.window))
        return self._draft_fn

    def verify_fn(self):
        if self._verify_fn is None:
            # pools are donated exactly like the decode step: verify IS
            # the decode step, iterated
            self._verify_fn = jax.jit(
                build_verify_program(self.engine._build_step()),
                donate_argnums=(1, 2))
        return self._verify_fn

    @property
    def acceptance_rate(self) -> Optional[float]:
        if self.stats["proposed"] == 0:
            return None
        return self.stats["accepted"] / self.stats["proposed"]

    # -- one decode iteration ------------------------------------------
    def run_decode(self, active: List[int]) -> None:
        """Advance every active slot by 1..K+1 tokens (in place of the
        engine's single-token decode)."""
        eng = self.engine
        K = self.config.k

        # DRAFT: K greedy proposals per slot off the windowed recompute
        seqs: List[List[int]] = []
        for s in range(eng.B):
            req = eng.slots[s]
            seqs.append([] if req is None
                        else req.prompt.tolist() + req.out)
        proposals = np.zeros((eng.B, K), np.int32)
        draft = self.draft_fn()
        for i in range(K):
            win, ctx = assemble_windows(seqs, self.config.window, eng.B)
            tok = np.asarray(draft(self.config.draft_params,
                                   jnp.asarray(win), jnp.asarray(ctx)),
                             np.int32)
            proposals[:, i] = tok
            for s in active:
                seqs[s].append(int(tok[s]))

        # VERIFY: one fixed-width dispatch appends K+1 KV positions per
        # slot and scores them against the target
        tokens_mat = np.zeros((eng.B, K + 1), np.int32)
        tokens_mat[:, 0] = eng.tokens
        tokens_mat[:, 1:] = proposals
        pre_lengths = eng.lengths.copy()
        eng.pool_k, eng.pool_v, logits = self.verify_fn()(
            eng.params, eng.pool_k, eng.pool_v,
            jnp.asarray(eng.block_table), jnp.asarray(pre_lengths),
            jnp.asarray(tokens_mat))
        logits = np.asarray(logits)                     # [B, K+1, V]
        eng.last_logits = logits[:, 0]

        # COMMIT / ROLLBACK per slot
        step_accepted = step_emitted = step_rollback = 0
        for s in active:
            req = eng.slots[s]
            ell = int(pre_lengths[s])
            if (req.temperature or 0.0) > 0.0:
                p_dists = [warp_probs(logits[s, i], req.temperature,
                                      req.top_k, req.top_p)
                           for i in range(K + 1)]
                emitted, _ = spec_sample_chain(
                    p_dists, proposals[s].tolist(), seed=req.seed,
                    start_position=ell + 1)
            else:
                emitted = []
                for i in range(K + 1):
                    want = int(logits[s, i].argmax())
                    emitted.append(want)
                    if i == K or want != int(proposals[s, i]):
                        break
            appended = 0
            for t in emitted:
                eng._append_tok(req, int(t))
                appended += 1
                if req.eos_pos is not None \
                        or len(req.out) >= req.max_new_tokens:
                    break
            # commit: KV is live for the fed token plus the first
            # appended-1 emitted tokens; everything past that is the
            # rolled-back tail
            eng.lengths[s] = ell + appended
            eng.tokens[s] = int(req.out[-1])
            accepted = sum(1 for i in range(min(appended, K))
                           if emitted[i] == int(proposals[s, i]))
            rollback = self._stale_pages(ell + appended, ell + K + 1,
                                         eng.BS)
            step_accepted += accepted
            step_emitted += appended
            step_rollback += rollback
            self.stats["proposed"] += K
            self.stats["accepted"] += accepted
            self.stats["emitted"] += appended
            self.stats["rollback_pages"] += rollback
        self.stats["spec_steps"] += 1
        self._record(active, step_accepted, step_emitted, step_rollback)

    @staticmethod
    def _stale_pages(committed_end: int, written_end: int,
                     block_size: int) -> int:
        """Pages containing KV positions [committed_end, written_end)
        that the commit rolled back (stale until overwritten)."""
        if written_end <= committed_end:
            return 0
        return (written_end - 1) // block_size \
            - committed_end // block_size + 1

    def _record(self, active: List[int], step_accepted: int,
                step_emitted: int, step_rollback: int) -> None:
        from ..observability import REGISTRY
        if not REGISTRY.enabled:
            return
        REGISTRY.counter("serve.spec.steps_total").inc()
        REGISTRY.counter("serve.spec.proposed_total").inc(
            self.config.k * len(active))
        REGISTRY.counter("serve.spec.accepted_total").inc(step_accepted)
        REGISTRY.counter("serve.spec.emitted_total").inc(step_emitted)
        REGISTRY.counter("serve.spec.rollback_pages_total").inc(
            step_rollback)
        REGISTRY.histogram("serve.spec.accepted_per_step").record(
            step_accepted / max(len(active), 1))
