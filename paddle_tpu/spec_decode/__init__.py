"""Speculative decoding for the continuous-batching serve path (ISSUE 8).

Decode is step-latency-bound: every generated token costs one full
target-model decode dispatch.  Speculative decoding amortizes that cost
— a small DRAFT model proposes K tokens per active request, and ONE
fixed-width VERIFY program scores all K+1 positions against the target
model's paged KV, so each engine step can commit several tokens.

The subsystem is lossless by construction:

* greedy requests: a proposal is accepted iff it equals the target's
  argmax at that position, and the verify program is a ``lax.scan`` of
  the engine's own decode-step body — its logits are BIT-IDENTICAL to
  sequential baseline decode, so the emitted stream is too (pinned);
* sampled requests: proposals are verified with rejection sampling
  (`sampling.py`), which provably preserves the target distribution
  for ANY proposal distribution — the draft can only change speed,
  never outputs.

Wiring: ``ContinuousBatchingEngine(spec_config=SpecDecodeConfig(...))``
routes every decode iteration through :class:`SpecDecodeRunner`;
rejected tails roll back by length (their KV writes fall beyond the
committed length, are masked by every subsequent attention, and get
overwritten by the next append at the same positions), while the
refcounted page pool keeps its exactly-once release accounting through
cancels and retires mid-speculation (``kv_leak_report`` stays zero —
regression-pinned).  The draft and verify executables are AOT-exported
next to the decode step (``aot/serve.py``) so a warm spec-decode start
performs ZERO backend compiles (``serve_spec_warm`` budget row).
"""

from .config import SpecDecodeConfig
from .draft import build_draft_program
from .runner import SpecDecodeRunner
from .sampling import spec_sample_chain, warp_probs
from .verify import build_verify_program

__all__ = [
    "SpecDecodeConfig", "SpecDecodeRunner", "build_draft_program",
    "build_verify_program", "spec_sample_chain", "warp_probs",
]
