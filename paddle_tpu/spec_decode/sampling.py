"""Losslessness-preserving rejection sampling for speculative decode.

The identity this module is built on (Leviathan et al., "Fast Inference
from Transformers via Speculative Decoding"): given a target
distribution ``p`` and a proposal ``x ~ q``, accepting ``x`` with
probability ``min(1, p(x)/q(x))`` and otherwise emitting a sample from
the residual ``norm(max(p - q, 0))`` yields a token distributed EXACTLY
as ``p`` — for any ``q``.  Chaining it over K proposals (stopping at
the first rejection, plus one bonus token from the position after the
accepted prefix) therefore emits tokens whose joint law equals baseline
ancestral sampling from the target, no matter how good or bad the draft
is.  The draft moves the ACCEPTANCE RATE, never the distribution —
pinned by the identity test in tests/test_spec_decode.py.

The engine's draft proposes greedily, so its proposal law is a one-hot
``q``; the chain then degenerates to: accept ``x`` w.p. ``p(x)``, else
sample from ``p`` with ``x`` masked out (renormalized) — still exactly
``p`` in law (substitute the one-hot into the identity above).

Randomness: each decision draws from a counter-based Philox generator
keyed by ``(request seed, absolute position)`` — deterministic per
(seed, content), independent of batch composition and host wall-clock,
the same reproducibility contract as the engine's seeded jax sampler
(which keys ``fold_in(key(seed), position)``).  Greedy requests never
touch this module.

``warp_probs`` mirrors ``inference.serving.build_sampler``'s HF
sequential-warper semantics (temperature, then top-k, then top-p over
the top-k-FILTERED mass) so the target law the rejection test preserves
is the very law the baseline sampler draws from.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["warp_probs", "position_rng", "spec_sample_chain"]


def warp_probs(logits: np.ndarray, temperature: float,
               top_k: Optional[int], top_p: Optional[float]) -> np.ndarray:
    """The engine sampler's categorical law as an explicit probability
    vector: softmax(logits/T) restricted to the sequential top-k /
    top-p keep-set.  Matches ``build_sampler`` cutoff conventions
    (kth-largest inclusive; smallest prefix with cum >= top_p)."""
    x = np.asarray(logits, np.float64) / float(temperature)
    keep = np.ones(x.shape, bool)
    if top_k and top_k > 0:
        kth = np.sort(x)[::-1][max(int(top_k), 1) - 1]
        keep &= x >= kth
    if top_p and top_p > 0.0:
        xf = np.where(keep, x, -np.inf)
        srt = np.sort(xf)[::-1]
        probs = _softmax(srt)
        cum = np.cumsum(probs)
        cutoff = srt[int(np.sum(cum < top_p))]
        keep &= xf >= cutoff
    p = _softmax(np.where(keep, x, -np.inf))
    return p


def _softmax(x: np.ndarray) -> np.ndarray:
    m = np.max(x)
    e = np.exp(x - m)
    return e / np.sum(e)


def position_rng(seed: int, position: int) -> np.random.Generator:
    """Counter-based generator for one (request, position) decision —
    reproducible across processes and independent of call order."""
    return np.random.Generator(
        np.random.Philox(key=np.uint64(np.uint32(seed)) << np.uint64(32)
                         | np.uint64(np.uint32(position))))


def spec_sample_chain(p_dists: Sequence[np.ndarray],
                      proposals: Sequence[int],
                      q_dists: Optional[Sequence[np.ndarray]] = None, *,
                      seed: int = 0, start_position: int = 0
                      ) -> Tuple[List[int], int]:
    """Run the rejection chain over K proposals plus the bonus position.

    Args:
      p_dists: K+1 target distributions (``p_dists[i]`` is the law of
        the token at position ``start_position + i``).
      proposals: the K draft tokens.
      q_dists: per-position proposal distributions; ``None`` means
        one-hot at ``proposals[i]`` (the greedy-draft case).
      seed / start_position: the Philox key inputs; position ``i``'s
        decision uses ``position_rng(seed, start_position + i)``.

    Returns ``(emitted tokens, accepted proposal count)``; emitted has
    ``accepted + 1`` entries — the accepted prefix plus either the
    residual sample at the first rejection or the bonus token.
    """
    if len(p_dists) != len(proposals) + 1:
        raise ValueError(
            f"need K+1 target dists for K proposals, got "
            f"{len(p_dists)} vs {len(proposals)}")
    emitted: List[int] = []
    for i, x in enumerate(proposals):
        p = np.asarray(p_dists[i], np.float64)
        rng = position_rng(seed, start_position + i)
        if q_dists is None:
            q_x = 1.0
            residual = p.copy()
            residual[x] = 0.0
        else:
            q = np.asarray(q_dists[i], np.float64)
            q_x = q[x]
            residual = np.maximum(p - q, 0.0)
        accept_p = 1.0 if q_x <= 0.0 else min(1.0, p[x] / q_x)
        if rng.random() < accept_p:
            emitted.append(int(x))
            continue
        z = residual.sum()
        if z <= 0.0:
            # p(x) == 1: rejection has probability zero; numerical
            # underflow can still land here — emit from p itself
            residual, z = p, p.sum()
        emitted.append(int(rng.choice(len(p), p=residual / z)))
        return emitted, i
    # every proposal accepted: bonus token from the K+1-th distribution
    p = np.asarray(p_dists[-1], np.float64)
    rng = position_rng(seed, start_position + len(proposals))
    emitted.append(int(rng.choice(len(p), p=p / p.sum())))
    return emitted, len(proposals)
