"""The verify program: K+1 target-model decode positions, one dispatch.

Bit-identity is the whole design.  The pinned acceptance criterion is
that greedy speculative decode emits EXACTLY the baseline greedy stream,
and the only way to guarantee that on every backend is to make the
verify program compute the SAME floating-point operations as the
baseline decode step — so ``build_verify_program`` takes the engine's
own ``_build_step`` closure and runs it K+1 times under ``lax.scan``
inside one jitted program.  Each scan iteration appends one token's KV
through ``paged_append`` and produces the decode-step logits for the
next position; the per-iteration HLO is the decode step's, so logits
and pool contents match sequential baseline decode bit-for-bit
(asserted by tests/test_spec_decode.py).

What this buys: one host dispatch + one device sync per K+1 positions
instead of per token, and one SCHEDULER iteration per accepted run —
engine-steps-per-token drops below 1.0 (the serve bench's extra.spec
row).  What it does not buy: intra-verify parallelism across the K+1
positions — that is the block-fusion work of ROADMAP item 2 (a chunked
parallel verify must share the fused block kernel's numerics story to
keep the bit-identity pin; until then, sequential-in-program is the
honest CPU-tier shape).

Rollback contract: the program ALWAYS writes K+1 positions of KV per
slot (fixed width); the host commits only the accepted prefix by
advancing ``lengths`` that far.  Rejected-tail writes land at positions
>= the committed length, which every subsequent attention masks out and
the next append overwrites — the pages themselves stay owned by the
slot (the engine maps a request's full page budget at admission), so
rollback never touches the refcount pool.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["build_verify_program"]


def build_verify_program(step_fn):
    """Wrap a decode-step closure (``ContinuousBatchingEngine.
    _build_step()``'s return) into ``verify(params, pool_k, pool_v,
    block_table, lengths, tokens [B, K+1]) -> (pool_k, pool_v,
    logits [B, K+1, V])``.

    ``tokens[:, 0]`` is each slot's fed token (the engine's
    ``self.tokens``), columns 1..K the draft proposals; ``logits[:, i]``
    is the target's next-token distribution after consuming
    ``tokens[:, :i+1]`` — exactly what ``step_fn`` would have returned
    on the i-th sequential call.  The K+1 width is baked at trace time
    (the jitted program is specialized per (max_batch, k) geometry,
    which the AOT manifest records)."""

    def verify(params, pool_k, pool_v, block_table, lengths, tokens):
        def body(carry, tok):
            pk, pv, ln = carry
            pk, pv, logits = step_fn(params, pk, pv, block_table, ln,
                                     tok)
            return (pk, pv, ln + 1), logits

        (pk, pv, _), logits = jax.lax.scan(
            body, (pool_k, pool_v, lengths),
            jnp.swapaxes(tokens, 0, 1))
        return pk, pv, jnp.swapaxes(logits, 0, 1)

    return verify
