"""paddle.geometric parity (reference python/paddle/geometric/ —
message-passing send_u_recv/send_ue_recv/send_uv, segment ops,
sample_neighbors, reindex_graph).

TPU-first: all graph ops lower to ``jax.ops.segment_*`` scatter/gather
(XLA-native) instead of the reference's hand-written CUDA graph kernels.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dispatch import primitive
from ..core.tensor import Tensor

__all__ = ["send_u_recv", "send_ue_recv", "send_uv", "segment_sum",
           "segment_mean", "segment_min", "segment_max", "reindex_graph",
           "sample_neighbors", "reindex_heter_graph",
           "weighted_sample_neighbors"]

_REDUCERS = {
    "sum": jax.ops.segment_sum,
    "mean": None,   # composed below
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _segment_reduce(vals, ids, num_segments, pool_type):
    if pool_type == "mean":
        s = jax.ops.segment_sum(vals, ids, num_segments=num_segments)
        cnt = jax.ops.segment_sum(jnp.ones_like(ids, vals.dtype), ids,
                                  num_segments=num_segments)
        cnt = jnp.maximum(cnt, 1)
        return s / cnt.reshape((-1,) + (1,) * (vals.ndim - 1))
    fn = _REDUCERS[pool_type]
    out = fn(vals, ids, num_segments=num_segments)
    if pool_type in ("min", "max"):
        # empty segments produce +/-inf; zero them like the reference
        out = jnp.where(jnp.isfinite(out), out, 0)
    return out


@primitive("send_u_recv")
def _send_u_recv(x, src_index, dst_index, *, reduce_op, out_size):
    vals = jnp.take(x, src_index, axis=0)
    return _segment_reduce(vals, dst_index, out_size, reduce_op)


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src] and segment-reduce onto dst (reference
    geometric/message_passing/send_recv.py)."""
    n = out_size or (x.shape[0] if hasattr(x, "shape") else None)
    return _send_u_recv(x, src_index, dst_index, reduce_op=reduce_op,
                        out_size=int(n))


_COMBINERS = {
    "add": jnp.add,
    "sub": jnp.subtract,
    "mul": jnp.multiply,
    "div": jnp.divide,
}


@primitive("send_ue_recv")
def _send_ue_recv(x, y, src_index, dst_index, *, message_op, reduce_op,
                  out_size):
    vals = _COMBINERS[message_op](jnp.take(x, src_index, axis=0), y)
    return _segment_reduce(vals, dst_index, out_size, reduce_op)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Combine node features with edge features then reduce onto dst."""
    n = out_size or x.shape[0]
    return _send_ue_recv(x, y, src_index, dst_index, message_op=message_op,
                         reduce_op=reduce_op, out_size=int(n))


@primitive("send_uv")
def _send_uv(x, y, src_index, dst_index, *, message_op):
    return _COMBINERS[message_op](jnp.take(x, src_index, axis=0),
                                  jnp.take(y, dst_index, axis=0))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message from src/dst node features."""
    return _send_uv(x, y, src_index, dst_index, message_op=message_op)


def _seg(fn_name):
    @primitive(f"segment_{fn_name}")
    def op(data, segment_ids, *, num_segments):
        return _segment_reduce(data, segment_ids, num_segments, fn_name)

    def wrapper(data, segment_ids, name=None):
        ids = segment_ids._value if isinstance(segment_ids, Tensor) \
            else jnp.asarray(segment_ids)
        n = int(jnp.max(ids)) + 1 if ids.size else 0
        return op(data, segment_ids, num_segments=n)
    return wrapper


segment_sum = _seg("sum")
segment_mean = _seg("mean")
segment_min = _seg("min")
segment_max = _seg("max")


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global node ids to local ids (reference
    geometric/reindex.py): x (center nodes) then new neighbor ids."""
    xv = np.asarray(x._value if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors._value if isinstance(neighbors, Tensor)
                    else neighbors)
    cnt = np.asarray(count._value if isinstance(count, Tensor) else count)
    uniq, inv = np.unique(np.concatenate([xv, nb]), return_inverse=True)
    # order: center nodes keep their position first
    order = {}
    for v in xv.tolist():
        order.setdefault(v, len(order))
    for v in nb.tolist():
        order.setdefault(v, len(order))
    remap = np.array([order[v] for v in uniq.tolist()])
    local = remap[inv]
    reindex_src = local[len(xv):]
    reindex_dst = np.repeat(local[:len(xv)], cnt)
    nodes = np.array(sorted(order, key=order.get))
    return (Tensor(jnp.asarray(reindex_src)),
            Tensor(jnp.asarray(reindex_dst)),
            Tensor(jnp.asarray(nodes)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniformly sample up to sample_size in-neighbors per input node from
    a CSC graph (reference geometric/sampling/neighbors.py)."""
    from ..core.rng import next_rng_key
    rv = np.asarray(row._value if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor) else colptr)
    nodes = np.asarray(input_nodes._value
                       if isinstance(input_nodes, Tensor) else input_nodes)
    key = np.asarray(jax.random.key_data(next_rng_key())).ravel()
    rng = np.random.default_rng(int(key[-1]))
    out_nb, out_cnt = [], []
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        nbrs = rv[beg:end]
        if sample_size >= 0 and len(nbrs) > sample_size:
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out_nb.append(nbrs)
        out_cnt.append(len(nbrs))
    neighbors = np.concatenate(out_nb) if out_nb else np.zeros(0, rv.dtype)
    return (Tensor(jnp.asarray(neighbors)),
            Tensor(jnp.asarray(np.array(out_cnt, np.int32))))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reference geometric/reindex.py
    reindex_heter_graph): per-edge-type neighbor lists share ONE node
    renumbering keyed on x."""
    import numpy as np

    xs = np.asarray(getattr(x, "_value", x))
    neigh_list = [np.asarray(getattr(n, "_value", n)) for n in neighbors]
    cnt_list = [np.asarray(getattr(c, "_value", c)) for c in count]
    mapping = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)

    def map_id(v):
        v = int(v)
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
        return mapping[v]

    reindexed = []
    rows = []
    for neigh, cnt in zip(neigh_list, cnt_list):
        reindexed.append(np.asarray([map_id(v) for v in neigh], np.int64))
        rows.append(np.repeat(np.arange(len(cnt)), cnt).astype(np.int64))
    import jax.numpy as _jnp
    out_src = [Tensor(_jnp.asarray(r)) for r in reindexed]
    out_dst = [Tensor(_jnp.asarray(r)) for r in rows]
    return (out_src, out_dst,
            Tensor(_jnp.asarray(np.asarray(out_nodes, np.int64))))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-biased neighbor sampling (reference geometric/sampling/
    neighbors.py weighted_sample_neighbors): sample w/o replacement with
    probability proportional to edge weight."""
    import numpy as np

    rows = np.asarray(getattr(row, "_value", row))
    cp = np.asarray(getattr(colptr, "_value", colptr))
    wts = np.asarray(getattr(edge_weight, "_value", edge_weight),
                     np.float64)
    nodes = np.asarray(getattr(input_nodes, "_value", input_nodes))
    rng = np.random.default_rng(0 if name is None else abs(hash(name)))
    out, counts, out_eids = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        neigh = rows[lo:hi]
        w = wts[lo:hi]
        if sample_size < 0 or len(neigh) <= sample_size:
            pick = np.arange(len(neigh))
        else:
            p = w / w.sum() if w.sum() > 0 else None
            pick = rng.choice(len(neigh), size=sample_size, replace=False,
                              p=p)
        out.append(neigh[pick])
        counts.append(len(pick))
        out_eids.append(lo + pick)
    import jax.numpy as _jnp
    res = (Tensor(_jnp.asarray(np.concatenate(out) if out else
                               np.zeros(0, rows.dtype))),
           Tensor(_jnp.asarray(np.asarray(counts, np.int32))))
    if return_eids:
        res = res + (Tensor(_jnp.asarray(
            np.concatenate(out_eids) if out_eids else
            np.zeros(0, np.int64))),)
    return res
