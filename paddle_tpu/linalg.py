"""paddle.linalg namespace parity (reference python/paddle/linalg.py —
re-exports of tensor.linalg).  All impls live in ops/impl/linalg.py and are
registered through ops.yaml; this module is the public namespace."""

from .ops.api import (  # noqa: F401
    bmm, cdist, cholesky, cholesky_inverse, cholesky_solve, corrcoef, cov,
    det, dist, eig, eigh, eigvals, eigvalsh, householder_product, inv,
    lstsq, lu, lu_unpack, matmul, matrix_exp, matrix_norm, matrix_power,
    matrix_rank, multi_dot, mv, norm, ormqr, pca_lowrank, pinv, qr, slogdet,
    solve, svd, svd_lowrank, svdvals, triangular_solve, vector_norm,
)

__all__ = [
    "bmm", "cdist", "cholesky", "cholesky_inverse", "cholesky_solve",
    "corrcoef", "cov", "det", "dist", "eig", "eigh", "eigvals", "eigvalsh",
    "householder_product", "inv", "lstsq", "lu", "lu_unpack", "matmul",
    "matrix_exp", "matrix_norm", "matrix_power", "matrix_rank", "multi_dot",
    "mv", "norm", "ormqr", "pca_lowrank", "pinv", "qr", "slogdet", "solve",
    "svd", "svd_lowrank", "svdvals", "triangular_solve", "vector_norm",
]
