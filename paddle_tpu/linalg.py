"""paddle.linalg namespace parity (reference python/paddle/linalg.py —
re-exports of tensor.linalg).  All impls live in ops/impl/linalg.py and are
registered through ops.yaml; this module is the public namespace."""

from .ops.api import (  # noqa: F401
    bmm, cond, cdist, cholesky, cholesky_inverse, cholesky_solve, corrcoef, cov,
    det, dist, eig, eigh, eigvals, eigvalsh, householder_product, inv,
    lstsq, lu, lu_unpack, matmul, matrix_exp, matrix_norm, matrix_power,
    matrix_rank, multi_dot, mv, norm, ormqr, pca_lowrank, pinv, qr, slogdet,
    solve, svd, svd_lowrank, svdvals, triangular_solve, vector_norm,
)
from .nn.quant import fp8_gemm as _fp8_gemm


def fp8_fp8_half_gemm_fused(x, y, transpose_x=False, transpose_y=False,
                            bias=None, scale=1.0, output_dtype="float16",
                            act="identity"):
    """Reference signature (tensor/linalg.py:329) adapted onto the fp8
    gemm kernel path (nn/quant fp8_gemm)."""
    out = _fp8_gemm(x, y, x_scale=scale, y_scale=1.0, bias=bias,
                    transpose_x=transpose_x, transpose_y=transpose_y,
                    activation=None if act == "identity" else act,
                    output_dtype=output_dtype)
    return out

__all__ = [
    "bmm", "cond", "cdist", "cholesky", "cholesky_inverse", "cholesky_solve",
    "corrcoef", "cov", "det", "dist", "eig", "eigh", "eigvals", "eigvalsh",
    "householder_product", "inv", "lstsq", "lu", "lu_unpack", "matmul",
    "matrix_exp", "matrix_norm", "matrix_power", "matrix_rank", "multi_dot",
    "mv", "norm", "ormqr", "pca_lowrank", "pinv", "qr", "slogdet", "solve",
    "svd", "svd_lowrank", "svdvals", "triangular_solve", "vector_norm",
    "fp8_fp8_half_gemm_fused",
]
