"""paddle.signal namespace parity (reference python/paddle/signal.py:
stft:179, istft:363, frame, overlap_add)."""

from .ops.api import frame, istft, overlap_add, stft  # noqa: F401

__all__ = ["frame", "istft", "overlap_add", "stft"]
