"""paddle.fft namespace parity (reference python/paddle/fft.py).  Impls in
ops/impl/spectral.py (pure jnp.fft — XLA-native FFT), registered via
ops.yaml so every entry is a taped, jit-cacheable op."""

from .ops.api import (  # noqa: F401
    fft, fft2, fftfreq, fftn, fftshift, hfft, hfft2, hfftn, ifft, ifft2,
    ifftn, ifftshift, ihfft, ihfft2, ihfftn, irfft, irfft2, irfftn, rfft,
    rfft2, rfftfreq, rfftn,
)

__all__ = [
    "fft", "fft2", "fftfreq", "fftn", "fftshift", "hfft", "hfft2", "hfftn",
    "ifft", "ifft2", "ifftn", "ifftshift", "ihfft", "ihfft2", "ihfftn",
    "irfft", "irfft2", "irfftn", "rfft", "rfft2", "rfftfreq", "rfftn",
]
