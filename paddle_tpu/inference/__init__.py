"""paddle.inference parity — the deployment predictor facade.

Reference: AnalysisPredictor (fluid/inference/api/analysis_predictor.h:105)
+ the C API surface (Config / create_predictor / get_input_handle / run /
get_output_handle, paddle_inference_api.h).

TPU-native scope: the reference predictor's pass pipeline (framework/ir
fusion passes, TRT subgraphs) collapses into XLA — a saved model here is a
serialized STABLEHLO program (jit.save), so load = deserialize + jit, and
every fusion the reference applies post-hoc is already in the compiled
artifact.  The facade keeps the reference's handle-style API so deployment
code ports 1:1, and adds the LLM serving path: ``LLMPredictor`` drives the
paged-KV / fused-decode generate() loop (MMHA + fused_multi_transformer
analog, models/generation.py).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Config", "Predictor", "create_predictor", "PredictorHandle",
           "LLMPredictor", "create_llm_predictor",
           "ContinuousBatchingEngine"]


def __getattr__(name):
    # lazy: the serving engine pulls in the decode stack
    if name == "ContinuousBatchingEngine":
        from .serving import ContinuousBatchingEngine
        return ContinuousBatchingEngine
    raise AttributeError(name)


class Config:
    """Predictor configuration (reference: paddle_analysis_config.h).

    ``Config(prog_file, params_file)`` or ``Config(model_dir)`` with the
    jit.save naming convention (<prefix>.pdmodel / <prefix>.pdparams)."""

    def __init__(self, prog_file: Optional[str] = None,
                 params_file: Optional[str] = None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            prog_file = prog_file[: -len(".pdmodel")]
        self._prefix = prog_file
        self._params_file = params_file
        self._device = None          # None = default backend
        self._memory_pool_mb = None
        self._enable_profile = False

    # -- device selection (reference enable_use_gpu / disable_gpu) --------
    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # accepted for API parity; device selection on TPU is the JAX
        # platform, not a predictor flag
        self._memory_pool_mb = memory_pool_init_size_mb
        self._device = ("accel", device_id)

    def disable_gpu(self):
        self._device = ("cpu", 0)

    def enable_profile(self):
        self._enable_profile = True

    def model_dir(self):
        return self._prefix

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdparams"

    def summary(self) -> str:
        return (f"Config(prefix={self._prefix!r}, device={self._device}, "
                f"profile={self._enable_profile})")


class PredictorHandle:
    """Input/output tensor handle (reference: ZeroCopyTensor /
    paddle_infer::Tensor — copy_from_cpu / copy_to_cpu)."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[np.ndarray] = None

    def copy_from_cpu(self, arr):
        self._value = np.asarray(arr)

    def reshape(self, shape):
        if self._value is not None:
            self._value = self._value.reshape(shape)

    def copy_to_cpu(self) -> np.ndarray:
        if self._value is None:
            raise RuntimeError(f"handle {self.name!r} holds no data yet "
                               "(run() first)")
        return np.asarray(self._value)

    def shape(self):
        return list(self._value.shape) if self._value is not None else None


class Predictor:
    """Deployment predictor over a jit.save'd STABLEHLO artifact
    (reference AnalysisPredictor: load program -> optimize -> run;
    optimization here happened at export)."""

    def __init__(self, config: Config):
        from ..jit.api import load as _jit_load
        if config._prefix is None:
            raise ValueError("Config needs the saved-model prefix")
        if not os.path.exists(config.prog_file()):
            raise FileNotFoundError(config.prog_file())
        self._layer = _jit_load(config._prefix)
        self._config = config
        n_in = self._n_program_inputs()
        self._inputs = [PredictorHandle(f"input_{i}") for i in range(n_in)]
        self._outputs: List[PredictorHandle] = []

    def _n_program_inputs(self) -> int:
        exported = self._layer._exported
        n_params = len(jax_tree_leaves(self._layer._params))
        return len(exported.in_avals) - n_params

    # -- handle API -------------------------------------------------------
    def get_input_names(self) -> List[str]:
        return [h.name for h in self._inputs]

    def get_input_handle(self, name: str) -> PredictorHandle:
        for h in self._inputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def get_output_names(self) -> List[str]:
        return [h.name for h in self._outputs]

    def get_output_handle(self, name: str) -> PredictorHandle:
        for h in self._outputs:
            if h.name == name:
                return h
        raise KeyError(name)

    def run(self, inputs: Optional[Sequence[np.ndarray]] = None):
        """Execute the program.  Either pass arrays directly (returns
        outputs, the python-API style) or pre-fill input handles and read
        output handles (the C-API style)."""
        if inputs is None:
            inputs = [h.copy_to_cpu() if h._value is not None else None
                      for h in self._inputs]
            if any(v is None for v in inputs):
                missing = [h.name for h, v in zip(self._inputs, inputs)
                           if v is None]
                raise RuntimeError(f"inputs not set: {missing}")
        outs = self._layer(*inputs)
        flat = outs if isinstance(outs, (list, tuple)) else [outs]
        vals = [np.asarray(o._value if hasattr(o, "_value") else o)
                for o in flat]
        self._outputs = [PredictorHandle(f"output_{i}")
                         for i in range(len(vals))]
        for h, v in zip(self._outputs, vals):
            h._value = v
        return vals


def jax_tree_leaves(tree):
    import jax
    return jax.tree.leaves(tree)


def create_predictor(config: Config) -> Predictor:
    """Reference: paddle_infer::CreatePredictor."""
    return Predictor(config)


# ---------------------------------------------------------------------------
# LLM serving path (paged-KV generate)
# ---------------------------------------------------------------------------

class LLMPredictor:
    """Serving facade for causal-LM generation (the reference's
    fused_multi_transformer + masked_multihead_attention serving stack,
    SURVEY §2.6): loads <prefix>.pdparams + a pickled config, drives the
    compiled prefill + decode-scan rollout (models/generation.py) with the
    fused-decode cache (MMHA analog) — no Python-per-token dispatch."""

    def __init__(self, model_family: str, cfg, params):
        from ..models import generation as gen
        self.family = model_family
        self.cfg = cfg
        self.params = params
        self._gen = {"gpt": gen.gpt_generate,
                     "llama": gen.llama_generate}[model_family]

    @classmethod
    def from_dir(cls, path: str) -> "LLMPredictor":
        import pickle

        import jax
        import jax.numpy as jnp

        from ..framework.io import load as _load
        with open(os.path.join(path, "llm_config.pkl"), "rb") as f:
            meta = pickle.load(f)
        state = _load(os.path.join(path, "model.pdparams"))
        params = jax.tree.map(jnp.asarray, state)
        return cls(meta["family"], meta["cfg"], params)

    def save(self, path: str):
        import pickle

        import jax
        import numpy as np

        from ..framework.io import save as _save
        os.makedirs(path, exist_ok=True)
        # framework.io.save handles the nested dict tree natively
        _save(jax.tree.map(np.asarray, self.params),
              os.path.join(path, "model.pdparams"))
        with open(os.path.join(path, "llm_config.pkl"), "wb") as f:
            pickle.dump({"family": self.family, "cfg": self.cfg}, f)

    def generate(self, input_ids, max_new_tokens: int, **kw):
        return self._gen(self.params, self.cfg, input_ids,
                         max_new_tokens, **kw)


def create_llm_predictor(path: str) -> LLMPredictor:
    return LLMPredictor.from_dir(path)
