"""Continuous-batching serving engine over the paged KV cache.

Iteration-level scheduling (the vLLM recipe) on TPU terms: ONE jitted
decode step advances every active sequence in a fixed-size batch; between
steps the host scheduler admits queued requests into free slots, maps
pages from the shared block pool, and retires finished sequences —
requests join and leave the batch without recompilation (all shapes are
static: [max_batch] tokens/lengths, [max_batch, max_blocks] tables).

Relation to the reference: its serving stack is fused ops driven by an
external server (fused_multi_transformer + block_multihead_attention,
SURVEY §2.6); the block/page machinery here is ops/paged_kv.py (same
design as the reference's block attention), and this module adds the
in-framework scheduler the reference leaves to the serving layer.

Greedy decoding only (batched sampling would need per-slot RNG streams);
per-sequence results are independent of WHO ELSE shares the batch —
pinned by tests/test_serving_engine.py against a batch-of-one engine.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import _rotate_half
from ..ops.paged_kv import BlockAllocator, paged_append, \
    paged_decode_attention

__all__ = ["ContinuousBatchingEngine", "GenRequest"]


@dataclass
class GenRequest:
    req_id: int
    prompt: np.ndarray                 # [T0] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    out: List[int] = field(default_factory=list)


class ContinuousBatchingEngine:
    """Llama-family continuous-batching engine (greedy).

    Args:
      cfg: LlamaConfig (dense or MoE — the FFN follows the config).
      params: train-step param pytree (wte/head/lnf_w + stacked blocks).
      max_batch: decode-batch slots (static jit shape).
      block_size / num_blocks: shared KV page pool geometry.
      max_blocks_per_seq: page-table width per slot (caps per-sequence
        length at block_size * max_blocks_per_seq).

    The engine keeps its own page table rather than reusing
    ops/paged_kv.PagedKVCache: that class sizes its table [B, num_blocks]
    (every slot could own the whole pool), while the decode gather cost
    scales with TABLE WIDTH — the engine's [B, max_blocks_per_seq] table
    keeps the per-step gather at the per-sequence cap, not the pool size.
    """

    def __init__(self, cfg, params, *, max_batch: int = 4,
                 block_size: int = 16, num_blocks: int = 256,
                 max_blocks_per_seq: Optional[int] = None):
        if getattr(cfg, "moe_num_experts", 0) and \
                getattr(cfg, "moe_router", "topk") != "topk":
            raise NotImplementedError("decode serves token-choice only")
        self.cfg = cfg
        self.params = params
        self.B = max_batch
        self.BS = block_size
        self.MB = max_blocks_per_seq or \
            -(-cfg.max_position_embeddings // block_size)
        L = cfg.num_layers
        kvh, hd = cfg.kv_heads, cfg.head_dim
        dt = jnp.dtype(cfg.dtype)
        self.pool_k = jnp.zeros((L, num_blocks, block_size, kvh, hd), dt)
        self.pool_v = jnp.zeros_like(self.pool_k)
        self.block_table = np.full((max_batch, self.MB), -1, np.int32)
        self.lengths = np.zeros((max_batch,), np.int32)
        self.tokens = np.zeros((max_batch,), np.int32)
        self.alloc = BlockAllocator(num_blocks)
        self.slots: List[Optional[GenRequest]] = [None] * max_batch
        self.queue: "collections.deque[GenRequest]" = collections.deque()
        self.finished: Dict[int, np.ndarray] = {}
        self._next_id = 0
        # pools are donated: the decode step rewrites them every
        # iteration and the old buffers must not stay live
        self._step = jax.jit(self._build_step(),
                             donate_argnums=(1, 2))
        self._prefill_cache: Dict[int, object] = {}
        self.last_logits: Optional[np.ndarray] = None   # [B, V] debug/test

    # ------------------------------------------------------------------
    # compiled per-iteration decode over every slot
    # ------------------------------------------------------------------
    def _build_step(self):
        cfg = self.cfg
        from ..models.llama import _rope_cos_sin
        from ..models.generation import _collapse_blocks
        H, Hkv, D = cfg.num_heads, cfg.kv_heads, cfg.head_dim
        eps = cfg.rms_norm_eps
        BS = self.BS
        cos_full, sin_full = _rope_cos_sin(
            cfg.max_position_embeddings, D, cfg.rope_theta,
            jnp.dtype(cfg.dtype))
        moe = getattr(cfg, "moe_num_experts", 0)

        def rms(x, w):
            ms = jnp.mean(jnp.square(x.astype(jnp.float32)), -1,
                          keepdims=True)
            return (x * jax.lax.rsqrt(ms + eps).astype(x.dtype)) * w

        def ffn(lp, y):
            if moe:
                from ..parallel.moe import moe_swiglu_ffn_grouped
                out = moe_swiglu_ffn_grouped(
                    y, lp["router_w"], lp["e_gate"], lp["e_up"],
                    lp["e_down"], top_k=cfg.moe_top_k)
                if getattr(cfg, "moe_num_shared_experts", 0):
                    out = out + (jax.nn.silu(y @ lp["s_gate"])
                                 * (y @ lp["s_up"])) @ lp["s_down"]
                return out
            return (jax.nn.silu(y @ lp["gate_w"])
                    * (y @ lp["up_w"])) @ lp["down_w"]

        def step(params, pool_k, pool_v, bt, lengths, tokens):
            B = tokens.shape[0]
            blocks = _collapse_blocks(params["blocks"])
            x = jnp.take(params["wte"], tokens, axis=0)       # [B, h]
            # per-slot rope position = current length (0-based slot of
            # the incoming token)
            cos = jnp.take(cos_full, lengths, axis=0)         # [B, D]
            sin = jnp.take(sin_full, lengths, axis=0)

            def rope1(t):                                     # [B, h?, D]
                return t * cos[:, None, :] \
                    + _rotate_half(t) * sin[:, None, :]

            def body(carry, inp):
                x = carry
                lp, pk, pv = inp
                y = rms(x, lp["ln1_w"])
                q = (y @ lp["q_w"]).reshape(B, H, D)
                k = (y @ lp["k_w"]).reshape(B, Hkv, D)
                v = (y @ lp["v_w"]).reshape(B, Hkv, D)
                q, k = rope1(q), rope1(k)
                pk, pv = paged_append(pk, pv, k, v, bt, lengths, BS)
                attn = paged_decode_attention(q, pk, pv, bt, lengths + 1)
                x = x + attn.reshape(B, -1) @ lp["o_w"]
                x = x + ffn(lp, rms(x, lp["ln2_w"]))
                return x, (pk, pv)

            x, (pk2, pv2) = jax.lax.scan(body, x,
                                         (blocks, pool_k, pool_v))
            xf = rms(x, params["lnf_w"])
            logits = jnp.einsum("bh,hv->bv", xf, params["head"],
                                preferred_element_type=jnp.float32)
            return pk2, pv2, logits

        return step

    # ------------------------------------------------------------------
    # host-side scheduler
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens: int,
                    eos_token_id: Optional[int] = None) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "argmax is already one generated token)")
        total = len(prompt) + max_new_tokens
        if total > self.MB * self.BS:
            raise ValueError(f"request needs {total} tokens, engine caps "
                             f"at {self.MB * self.BS} per sequence")
        if self._blocks_needed(total) > self.alloc.num_blocks:
            raise ValueError(
                f"request needs {self._blocks_needed(total)} pages, the "
                f"whole pool has {self.alloc.num_blocks} — it could never "
                f"admit (raise num_blocks or shrink the request)")
        if total > self.cfg.max_position_embeddings:
            raise ValueError("request exceeds max_position_embeddings")
        req = GenRequest(self._next_id, prompt, max_new_tokens,
                         eos_token_id)
        self._next_id += 1
        self.queue.append(req)
        return req.req_id

    def _blocks_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.BS)

    def _admit(self) -> None:
        """Admit queued requests into free slots while pages allow —
        prefill runs densely once per request, then its KV moves into
        the pool pages."""
        from ..models.generation import build_llama_decoder
        for slot in range(self.B):
            if not self.queue or self.slots[slot] is not None:
                continue
            req = self.queue[0]
            total = len(req.prompt) + req.max_new_tokens
            need = self._blocks_needed(total)
            if need > self.alloc.free_blocks:
                break                      # head-of-line waits for pages
            self.queue.popleft()
            phys = self.alloc.allocate(("slot", slot), need)
            self.block_table[slot, :] = -1
            self.block_table[slot, :need] = phys
            T0 = len(req.prompt)
            # dense prefill, jitted once per distinct prompt length
            jprefill = self._prefill_cache.get(T0)
            if jprefill is None:
                prefill, _ = build_llama_decoder(self.cfg, T0,
                                                 use_pallas=False)
                jprefill = jax.jit(prefill)
                self._prefill_cache[T0] = jprefill
            cache, logits = jprefill(self.params, req.prompt[None, :])
            # move prompt KV into the pool pages ON DEVICE with ONE
            # scatter per pool (a per-block loop would dispatch a full
            # pool-sized update per page; a host round trip would stall
            # every admission).  The padded tail of the last page holds
            # zeros, masked by lengths.
            nb = self._blocks_needed(T0)
            pad = nb * self.BS - T0
            kc, vc = cache["k"][:, 0], cache["v"][:, 0]  # [L, T0, Hkv, D]
            pages = np.asarray(phys[:nb])

            def paged_view(x):                 # [L, nb, BS, Hkv, D]
                x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
                return x.reshape(x.shape[0], nb, self.BS, *x.shape[2:])

            self.pool_k = self.pool_k.at[:, pages].set(
                paged_view(kc).astype(self.pool_k.dtype))
            self.pool_v = self.pool_v.at[:, pages].set(
                paged_view(vc).astype(self.pool_v.dtype))
            first = int(np.asarray(jnp.argmax(logits, -1))[0])
            req.out.append(first)
            self.slots[slot] = req
            self.lengths[slot] = T0
            self.tokens[slot] = first

    def _retire_done(self) -> None:
        for s in range(self.B):
            req = self.slots[s]
            if req is not None and (
                    len(req.out) >= req.max_new_tokens
                    or (req.eos_token_id is not None and req.out
                        and req.eos_token_id in req.out)):
                # truncate anything after the first eos
                if req.eos_token_id is not None \
                        and req.eos_token_id in req.out:
                    req.out = req.out[:req.out.index(req.eos_token_id) + 1]
                self._retire(s)

    def _retire(self, slot: int) -> None:
        req = self.slots[slot]
        self.finished[req.req_id] = np.concatenate(
            [req.prompt, np.asarray(req.out, np.int32)])
        self.alloc.release(("slot", slot))
        self.block_table[slot, :] = -1
        self.lengths[slot] = 0
        self.slots[slot] = None

    def step(self) -> Dict[int, np.ndarray]:
        """One scheduler iteration: admit, decode every active slot,
        collect tokens, retire finished.  Returns newly finished
        {req_id: full ids} (empty dict when idle)."""
        # retire first so freed slots/pages admit this very iteration;
        # then AGAIN after admission — the prefill's first token can
        # already satisfy the budget (max_new_tokens=1) or hit eos, and
        # such a request must not enter the decode batch
        self._retire_done()
        self._admit()
        self._retire_done()
        active = [s for s in range(self.B) if self.slots[s] is not None]
        if not active:
            self.last_logits = None     # nothing decoded this iteration
            out = self.finished
            self.finished = {}
            return out
        self.pool_k, self.pool_v, logits = self._step(
            self.params, self.pool_k, self.pool_v,
            jnp.asarray(self.block_table), jnp.asarray(self.lengths),
            jnp.asarray(self.tokens))
        self.last_logits = np.asarray(logits)
        nxt = np.asarray(jnp.argmax(logits, -1).astype(jnp.int32))
        for s in active:
            req = self.slots[s]
            self.lengths[s] += 1            # the fed token's KV is stored
            req.out.append(int(nxt[s]))
            self.tokens[s] = int(nxt[s])
        out = self.finished
        self.finished = {}
        return out

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        """Drive steps until queue and batch drain; returns all results."""
        results: Dict[int, np.ndarray] = {}
        while self.queue or any(s is not None for s in self.slots):
            results.update(self.step())
        return results
