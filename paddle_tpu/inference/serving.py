"""Continuous-batching serving engine over the paged KV cache.

Iteration-level scheduling (the vLLM recipe) on TPU terms: ONE jitted
decode step advances every active sequence in a fixed-size batch; between
steps the host scheduler admits queued requests into free slots, maps
pages from the shared block pool, and retires finished sequences —
requests join and leave the batch without recompilation (all shapes are
static: [max_batch] tokens/lengths, [max_batch, max_blocks] tables).

Relation to the reference: its serving stack is fused ops driven by an
external server (fused_multi_transformer + block_multihead_attention,
SURVEY §2.6); the block/page machinery here is ops/paged_kv.py (same
design as the reference's block attention), and this module adds the
in-framework scheduler the reference leaves to the serving layer.

Decoding is greedy by default; per-request sampling (temperature /
top-k / top-p) runs on per-slot PRNG streams folded per position, so a
sampled request's tokens depend only on its seed and its own content —
per-sequence results are independent of WHO ELSE shares the batch,
pinned by tests/test_serving_engine.py against a batch-of-one engine.
"""

from __future__ import annotations

import collections
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.decode_block import make_norm_ffn as _make_rms_ffn  # noqa: F401
#   ^ the norm/FFN closure pair moved to ops/decode_block.py (ISSUE 9)
#     so the decode step, the chunk fill, and the spec-decode draft all
#     read one definition; the old name stays importable for callers.

__all__ = ["ContinuousBatchingEngine", "GenRequest", "build_sampler",
           "derive_sample_seed"]


def derive_sample_seed(seed: int, sample_idx: int) -> int:
    """Deterministic per-sample seed for n>1 parallel sampling (ROADMAP
    5(b)): sample 0 keeps the request's own seed (so ``n=1`` is exactly
    the single-request path), later samples hash (seed, sample_idx) —
    the per-sample stream is then keyed (seed, sample_idx, absolute
    position) end to end, and ``submit(n=k)`` is bit-identical to k
    independent submits carrying these derived seeds (pinned by
    tests/test_prefix_cache.py)."""
    if sample_idx == 0:
        return int(seed)
    import zlib
    return int(zlib.crc32(
        np.asarray([seed, sample_idx], np.int64).tobytes()) & 0x7FFFFFFF)


class _RefPool:
    """Refcounted page pool: prefix-cached blocks are shared read-only
    between sequences and the prefix index, freed when the last reference
    drops (the vLLM block-refcount scheme)."""

    def __init__(self, num_blocks: int):
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, -1, -1))
        self.ref: Dict[int, int] = {}

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def acquire(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        out = [self._free.pop() for _ in range(n)]
        for p in out:
            self.ref[p] = 1
        return out

    def share(self, phys: List[int]) -> None:
        for p in phys:
            if p not in self.ref:
                raise RuntimeError(
                    f"KV-pool accounting bug: share() of block {p} that "
                    "holds no live reference (freed or never acquired)")
            self.ref[p] += 1

    def release(self, phys: List[int]) -> None:
        for p in phys:
            r = self.ref.get(p, 0)
            if r <= 0:
                raise RuntimeError(
                    f"KV-pool accounting bug: release() of block {p} "
                    "with no live reference (double free) — a scheduling "
                    "path released the same pages twice")
            if r == 1:
                del self.ref[p]
                self._free.append(p)
            else:
                self.ref[p] = r - 1


@dataclass
class GenRequest:
    req_id: int
    prompt: np.ndarray                 # [T0] int32
    max_new_tokens: int
    eos_token_id: Optional[int] = None
    temperature: float = 0.0           # <= 0: greedy
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    seed: int = 0
    # scheduling class (ISSUE 11): higher admits first; under
    # saturation strictly-lower-priority RUNNING work is preempted
    # (KV spilled to host RAM, resumed bit-identically later)
    priority: int = 0
    out: List[int] = field(default_factory=list)
    # index of the first EOS in ``out`` (set by the scheduler the step the
    # token is appended — O(1) per step instead of rescanning the list)
    eos_pos: Optional[int] = None


def build_sampler():
    """Row-vmapped fold-in + filter + categorical program shared by the
    engine's runtime sampler and the AOT exporter (``aot/serve.py``) —
    the deserialized program must be the very function the engine would
    have jitted.  HF sequential-warper semantics: top-p mass is computed
    over the top-k-FILTERED distribution, not the raw one."""

    def one(logits, seed, position, temperature, top_k, top_p):
        key = jax.random.fold_in(jax.random.key(seed), position)
        x = logits.astype(jnp.float32) / temperature
        srt = jnp.sort(x)[::-1]                  # descending
        # traced ranks must be POSITIVE take indices — a traced
        # negative index clamps to 0 under jit and would
        # silently disable the filter
        kth = jnp.take(srt, jnp.maximum(top_k, 1) - 1)
        x = jnp.where((top_k > 0) & (x < kth), -jnp.inf, x)
        srt2 = jnp.sort(x)[::-1]                 # filtered dist
        probs = jax.nn.softmax(srt2)
        cum = jnp.cumsum(probs)
        cidx = jnp.sum(cum < top_p)
        cutoff = jnp.take(srt2, cidx)
        x = jnp.where((top_p > 0.0) & (x < cutoff), -jnp.inf, x)
        return jax.random.categorical(key, x)

    return jax.vmap(one)


class ContinuousBatchingEngine:
    """Llama-family continuous-batching engine (greedy by default,
    per-request sampling via temperature/top_k/top_p on add_request).

    Args:
      cfg: LlamaConfig (dense or MoE — the FFN follows the config).
      params: train-step param pytree (wte/head/lnf_w + stacked blocks).
      max_batch: decode-batch slots (static jit shape).
      block_size / num_blocks: shared KV page pool geometry.
      max_blocks_per_seq: page-table width per slot (caps per-sequence
        length at block_size * max_blocks_per_seq).
      prefill_buckets: declared prefill chunk lengths (aot/buckets.py).
        When set, EVERY prompt/suffix prefill is decomposed into these
        fixed-size chunk fills (last chunk zero-padded), so variable
        load runs on a fixed set of compiled programs instead of one
        jit per distinct prompt length.
      aot_dir: warm-start from a compile-artifact directory written by
        ``paddle_tpu.aot.export_engine`` — the decode step and the
        bucketed chunk fills are DESERIALIZED (zero backend compiles)
        instead of traced.  A rotation ROOT (a directory holding
        generation subdirs plus a ``latest`` pointer, see
        ``aot.artifact``) is followed through the pointer.  Any
        manifest mismatch (version skew, geometry drift, corruption,
        donation-unsafe artifact) falls back to fresh compiles with an
        ``aot`` telemetry event; the reason is kept on
        ``self.aot_error``.
      fused_decode_block: route every per-layer decode (and the
        spec-decode verify scan, which wraps the same step closure)
        through the fused block op ``ops/decode_block.py`` (ISSUE 9).
        On the CPU/reference tier the fused op IS the per-op chain —
        greedy output is bit-identical either way (pinned) — while on
        TPU it dispatches to the VMEM-resident Pallas megakernel when
        the layer geometry fits (per-op fallback otherwise).  The knob
        is covered by the AOT artifact config hash (docs/aot.md).
      fused_prefill: route every chunk-fill layer (bucketed prompt
        fills AND prefix-cache suffix fills) through the fused prefill
        block op ``ops/decode_block.prefill_block`` (ISSUE 18).  On the
        CPU/reference tier the fused op IS the per-op chain — greedy
        output is bit-identical either way (pinned) — while on TPU it
        dispatches to the VMEM-resident Pallas prefill megakernel with
        double-buffered page DMA when the layer geometry and chunk
        length fit (per-op fallback otherwise).  The knob is covered by
        the AOT artifact config hash (docs/aot.md).
      spec_config: a :class:`~paddle_tpu.spec_decode.SpecDecodeConfig`
        enabling speculative decoding — every decode iteration drafts
        ``k`` tokens per active request and verifies them in one
        fixed-width program (``spec_decode/``).  Greedy outputs are
        bit-identical to ``spec_config=None``; sampled outputs follow
        the same target law via rejection sampling.  ``spec_stats()``
        exposes acceptance counters.
      enable_preemption: priority classes with preemption (ISSUE 11).
        Requests carry a ``priority`` (``add_request(priority=)``);
        admission serves the highest class first, and under KV/batch
        saturation the scheduler evicts strictly-lower-priority running
        requests — committed KV pages spill to a CRC-checked host-RAM
        tier (``serving/resilience.py``) and restore into fresh blocks
        on re-admission, bit-identically.  With uniform priorities
        (the default) nothing is ever preempted, so the knob is inert
        for existing workloads.
      prefix_cache_config: a :class:`~paddle_tpu.serving.prefix_cache.
        PrefixCacheConfig` tuning the cross-request prefix cache
        (ISSUE 14) — most importantly ``offload_capacity_bytes``, the
        bounded host-RAM tier that parks evicted prefix pages as
        CRC-checked byte copies and restores them by exact-byte scatter
        (no recompute) on the next hit.  Default policy (no offload)
        matches the pre-ISSUE-14 drop-on-eviction behavior.
      quant_config: a :class:`~paddle_tpu.quantization.ServeQuantConfig`
        enabling quantized serving (ISSUE 16).  ``weight_dtype``
        ("int8"/"int4", optionally grouped) serves weight-only
        quantized block matmuls: ``params`` may be a pre-exported tree
        (``quantization.quantize_params_for_serving``) or a full-width
        tree, which is PTQ-exported at construction.  ``kv_dtype``
        ("int8") stores the paged KV pool as int8 codes with
        per-(token, head) fp32 scales (``ops.paged_kv.
        QuantizedKVPool``) — roughly halving KV bytes/token at
        head_dim 64+, so the same pool admits ~2x the concurrent
        sequences.  Greedy decode stays bit-identical WITHIN a quant
        config across every serve path (fused/unfused, spec-decode,
        prefix-cache hit, preempt/restore); the config is covered by
        the AOT ``engine_config`` hash so a warm start can never
        half-load a mismatched quantization.

    The engine keeps its own page table rather than reusing
    ops/paged_kv.PagedKVCache: that class sizes its table [B, num_blocks]
    (every slot could own the whole pool), while the decode gather cost
    scales with TABLE WIDTH — the engine's [B, max_blocks_per_seq] table
    keeps the per-step gather at the per-sequence cap, not the pool size.
    """

    def __init__(self, cfg, params, *, max_batch: int = 4,
                 block_size: int = 16, num_blocks: int = 256,
                 max_blocks_per_seq: Optional[int] = None,
                 enable_prefix_caching: bool = True,
                 prefill_buckets=None, aot_dir: Optional[str] = None,
                 fused_decode_block: bool = True,
                 fused_prefill: bool = True, spec_config=None,
                 enable_preemption: bool = True, spill_tier=None,
                 prefix_cache_config=None, quant_config=None):
        if getattr(cfg, "moe_num_experts", 0) and \
                getattr(cfg, "moe_router", "topk") != "topk":
            raise NotImplementedError("decode serves token-choice only")
        if quant_config is not None and quant_config.quantized_weights \
                and getattr(cfg, "moe_num_experts", 0):
            raise NotImplementedError(
                "weight-quantized serving covers dense FFNs only — the "
                "MoE expert matmuls keep full-width weights (ROADMAP)")
        rs = getattr(cfg, "rope_scaling", None)
        if rs and rs.get("rope_type", rs.get("type")) == "dynamic":
            raise NotImplementedError(
                "dynamic-NTK rope depends on the CURRENT sequence length; "
                "the engine bakes one table at max_position_embeddings, "
                "which would mis-scale every shorter sequence — use "
                "'linear' or 'llama3' scaling for serving")
        self.cfg = cfg
        self.quant_config = quant_config
        if quant_config is not None and quant_config.quantized_weights \
                and not any(k.endswith("__q")
                            for k in params["blocks"]):
            # full-width tree handed to a quantized engine: PTQ-export
            # it here (absmax scales); calibrated trees come in already
            # exported via quantize_params_for_serving(thresholds=...)
            from ..quantization.serve import quantize_params_for_serving
            params = quantize_params_for_serving(params, quant_config)
        self.params = params
        self.fused_decode_block = bool(fused_decode_block)
        self.fused_prefill = bool(fused_prefill)
        self.B = max_batch
        self.BS = block_size
        self.MB = max_blocks_per_seq or \
            -(-cfg.max_position_embeddings // block_size)
        L = cfg.num_layers
        kvh, hd = cfg.kv_heads, cfg.head_dim
        dt = jnp.dtype(cfg.dtype)
        # pools are built from HOST zeros through the same pool-shaped
        # copy op the preemption restore path uses (jnp.array of a
        # numpy array = convert_element_type executable), so a restore
        # under traffic hits a compiled-at-construction op instead of
        # tracing one — the fleet_warm budget row pins serve-path
        # compiles at zero.  A quantized-KV config builds an int8
        # QuantizedKVPool (codes + per-(token, head) fp32 scales).
        from ..ops.paged_kv import zeros_kv_pool
        self._kv_quant = quant_config is not None \
            and quant_config.quantized_kv
        self.pool_k = zeros_kv_pool(
            (L, num_blocks, block_size, kvh, hd), dt,
            kv_quant=self._kv_quant)
        self.pool_v = zeros_kv_pool(
            (L, num_blocks, block_size, kvh, hd), dt,
            kv_quant=self._kv_quant)
        self.block_table = np.full((max_batch, self.MB), -1, np.int32)
        self.lengths = np.zeros((max_batch,), np.int32)
        self.tokens = np.zeros((max_batch,), np.int32)
        self.alloc = _RefPool(num_blocks)
        self.slot_pages: List[List[int]] = [[] for _ in range(max_batch)]
        # cross-request prefix caching (ISSUE 14): a radix tree over
        # committed prompt pages, keyed by chained block digests; the
        # cache holds one pool reference per resident block, evicted
        # LRU (leaf-first) under page pressure — optionally into a
        # bounded CRC-checked host-RAM offload tier that restores by
        # exact-byte scatter instead of recompute
        from ..serving.prefix_cache import PrefixCache
        self.enable_prefix_caching = bool(enable_prefix_caching)
        self.prefix_cache = PrefixCache(block_size,
                                        config=prefix_cache_config)
        self.stats = {"prefix_blocks_reused": 0,
                      "prefix_blocks_registered": 0,
                      "pages_allocated": 0,
                      "prefill_tokens_computed": 0}
        self.slots: List[Optional[GenRequest]] = [None] * max_batch
        self.queue: "collections.deque[GenRequest]" = collections.deque()
        self.finished: Dict[int, np.ndarray] = {}
        self._next_id = 0
        # priority preemption (ISSUE 11): spilled-KV snapshots for
        # preempted requests, keyed by req_id (serving/resilience.py
        # owns the snapshot/restore machinery + CRC conventions).  The
        # tier is BOUNDED (ISSUE 12): pass a capacity-limited
        # ``SpillTier`` and an over-cap spill evicts the oldest
        # snapshot, demoting its request to replay-from-prefix.
        self.enable_preemption = bool(enable_preemption)
        if spill_tier is None:
            from ..serving.resilience import SpillTier
            spill_tier = SpillTier()
        self._spill = spill_tier
        self.resilience = {"preemptions": 0, "restores": 0,
                           "spill_save_secs": 0.0,
                           "spill_restore_secs": 0.0,
                           "spill_evictions": 0, "prefix_replays": 0}
        # LRU-bounded (a serving workload with many distinct prompt
        # lengths must not retain unboundedly many XLA executables)
        from ..utils.lru import LRUCache
        self._prefill_cache = LRUCache(16)
        self._chunk_fill_cache = LRUCache(16)
        # declared-bucket prefill + AOT warm start (paddle_tpu/aot)
        self._buckets = None
        self._bucket_fills: Dict[int, object] = {}
        self.aot_loaded = False
        self.aot_error: Optional[str] = None
        self._step = None
        self._sampler_fn = None
        self._spec = None
        self.spec_config = spec_config
        # decode-phase accounting (extra.spec bench row).
        # decode_slot_steps counts PER-SLOT decode iterations so that
        # engine_steps_per_token is exactly 1.0 for baseline decode
        # regardless of batching — only accepted speculation pushes it
        # below 1.0.
        self.decode_steps = 0
        self.decode_slot_steps = 0
        self.decode_tokens = 0
        _spec_programs = {}
        if spec_config is not None:
            spec_config.validate_against(cfg)
        if aot_dir is not None:
            from ..aot.artifact import AotError
            from ..aot.serve import load_engine_artifacts
            try:
                (self._step, self._bucket_fills, self._buckets,
                 self._sampler_fn, _spec_programs) = \
                    load_engine_artifacts(self, aot_dir)
                self.aot_loaded = True
            except AotError as e:
                # fresh-compile fallback, loudly: the reason stays on
                # the engine and goes to the telemetry event stream
                self.aot_error = str(e)
                from ..observability import REGISTRY
                if REGISTRY.enabled:
                    REGISTRY.counter("aot.fallback_total").inc()
                    REGISTRY.event("aot", action="fallback", dir=aot_dir,
                                   reason=str(e)[:300])
        if self._buckets is None and prefill_buckets is not None:
            from ..aot.buckets import ShapeBucketRegistry
            self._buckets = ShapeBucketRegistry(prefill_buckets,
                                                max_batch=max_batch)
        if self._step is None:
            # pools are donated: the decode step rewrites them every
            # iteration and the old buffers must not stay live
            self._step = jax.jit(self._build_step(),
                                 donate_argnums=(1, 2))
        if spec_config is not None:
            from ..spec_decode import SpecDecodeRunner
            self._spec = SpecDecodeRunner(
                self, spec_config,
                draft_fn=_spec_programs.get("draft"),
                verify_fn=_spec_programs.get("verify"))
        self.last_logits: Optional[np.ndarray] = None   # [B, V] debug/test

    # ------------------------------------------------------------------
    # compiled per-iteration decode over every slot
    # ------------------------------------------------------------------
    def _quant_kw(self):
        """The weight-quantization fields every block spec in this
        engine is built with — ONE source so the decode step, the chunk
        fills, and the spec-decode verify always agree."""
        qc = self.quant_config
        if qc is None or not qc.quantized_weights:
            return {}
        return {"weight_dtype": qc.weight_dtype,
                "group_size": qc.group_size}

    def _build_step(self):
        cfg = self.cfg
        from ..models.llama import _rope_cos_sin
        from ..models.generation import _collapse_blocks
        from ..ops.decode_block import decode_block, decode_block_spec
        D = cfg.head_dim
        cos_full, sin_full = _rope_cos_sin(
            cfg.max_position_embeddings, D, cfg.rope_theta,
            jnp.dtype(cfg.dtype), getattr(cfg, "rope_scaling", None))
        rms, moe_ffn = _make_rms_ffn(cfg)
        spec = decode_block_spec(cfg, self.BS, **self._quant_kw())
        ffn_override = moe_ffn if getattr(cfg, "moe_num_experts", 0) \
            else None
        # fused on: auto tier (per-op reference on CPU — bit-identical —
        # Pallas megakernel on TPU when the geometry fits); off: the
        # per-op composition, always
        backend = None if self.fused_decode_block else "xla"

        def step(params, pool_k, pool_v, bt, lengths, tokens):
            blocks = _collapse_blocks(params["blocks"])
            x = jnp.take(params["wte"], tokens, axis=0)       # [B, h]
            # per-slot rope position = current length (0-based slot of
            # the incoming token)
            cos = jnp.take(cos_full, lengths, axis=0)         # [B, D]
            sin = jnp.take(sin_full, lengths, axis=0)

            def body(carry, inp):
                x = carry
                lp, pk, pv = inp
                x, pk, pv = decode_block(
                    x, lp, pk, pv, bt, lengths, cos, sin, spec=spec,
                    ffn=ffn_override, backend=backend)
                return x, (pk, pv)

            x, (pk2, pv2) = jax.lax.scan(body, x,
                                         (blocks, pool_k, pool_v))
            xf = rms(x, params["lnf_w"])
            logits = jnp.einsum("bh,hv->bv", xf, params["head"],
                                preferred_element_type=jnp.float32)
            return pk2, pv2, logits

        return step

    def _build_chunk_fill(self, Ts: int):
        """Suffix prefill against the paged pool: runs ``Ts`` prompt
        tokens starting at a cached prefix of length ``start``, writing
        their KV into the (private) pages and returning next-token
        logits.  This is what makes a prefix-cache hit SKIP the prefix
        compute, not just dedupe its storage.

        Called with the optional trailing ``valid`` argument (the
        declared-bucket path), only the first ``valid`` tokens are
        real: padded rows write their KV to an out-of-range block index
        (scatter drops out-of-bounds updates, so the pool is untouched)
        and the returned logits come from row ``valid - 1`` instead of
        the last row.  With ``valid == Ts`` the computation is
        identical to the unpadded call."""
        cfg = self.cfg
        from ..models.llama import _rope_cos_sin
        from ..models.generation import _collapse_blocks
        from ..ops.decode_block import decode_block_spec, prefill_block
        D = cfg.head_dim
        BS = self.BS
        cos_full, sin_full = _rope_cos_sin(
            cfg.max_position_embeddings, D, cfg.rope_theta,
            jnp.dtype(cfg.dtype), getattr(cfg, "rope_scaling", None))
        scale = 1.0 / (D ** 0.5)
        rms, moe_ffn = _make_rms_ffn(cfg)
        spec = decode_block_spec(cfg, BS, **self._quant_kw())
        ffn_override = moe_ffn if getattr(cfg, "moe_num_experts", 0) \
            else None
        # fused on: auto tier (per-op reference on CPU — bit-identical —
        # Pallas prefill megakernel on TPU when the geometry and chunk
        # length fit); off: the per-op composition, always
        backend = None if self.fused_prefill else "xla"

        def fill(params, pool_k, pool_v, bt_row, start, toks, valid=None):
            # toks [Ts]; bt_row [MB]; start: prefix length
            blocks = _collapse_blocks(params["blocks"])
            pos = start + jnp.arange(Ts)                     # [Ts]
            x = jnp.take(params["wte"], toks, axis=0)[None]  # [1, Ts, h]
            cos = jnp.take(cos_full, pos, axis=0)
            sin = jnp.take(sin_full, pos, axis=0)
            blk = jnp.take(jnp.maximum(bt_row, 0), pos // BS)
            if valid is not None:
                # bucketed call: padded rows scatter out of range (the
                # update is dropped) so stale pool pages stay intact
                from ..ops.paged_kv import pool_geometry
                blk = jnp.where(jnp.arange(Ts) < valid, blk,
                                pool_geometry(pool_k)[0])
            off = pos % BS
            jpos = jnp.arange(bt_row.shape[0] * BS)[None, None, None, :]
            mask = jpos <= pos[None, None, :, None]

            def body(carry, inp):
                x = carry
                lp, pk, pv = inp
                x, pk, pv = prefill_block(
                    x, lp, pk, pv, blk, off, bt_row, mask, cos, sin,
                    spec=spec, start=start, ffn=ffn_override,
                    scale=scale, backend=backend)
                return x, (pk, pv)

            x, (pk2, pv2) = jax.lax.scan(body, x,
                                         (blocks, pool_k, pool_v))
            last = x[:, -1] if valid is None \
                else jnp.take(x, valid - 1, axis=1)
            xf = rms(last, params["lnf_w"])
            logits = jnp.einsum("bh,hv->bv", xf, params["head"],
                                preferred_element_type=jnp.float32)
            return pk2, pv2, logits

        return fill

    def _chunk_fill(self, Ts: int):
        fn = self._chunk_fill_cache.get(Ts)
        if fn is None:
            fn = jax.jit(self._build_chunk_fill(Ts),
                         donate_argnums=(1, 2))
            self._chunk_fill_cache.put(Ts, fn)
        return fn

    def _bucket_fill(self, size: int):
        """Compiled bucketed fill for a DECLARED chunk size: AOT-loaded
        when the engine warm-started, else jitted once per bucket (the
        key set is the fixed declared-bucket set, so this cache is
        bounded by construction)."""
        fn = self._bucket_fills.get(size)
        if fn is None:
            fn = jax.jit(self._build_chunk_fill(size),
                         donate_argnums=(1, 2))
            self._bucket_fills[size] = fn
        return fn

    def _fill_prompt_bucketed(self, slot: int, req: "GenRequest",
                              start: int) -> np.ndarray:
        """Run the prompt suffix (``start`` = cached-prefix tokens)
        through declared-bucket chunk fills; returns the logits at the
        prompt's final token (from the last chunk's ``valid - 1``
        row)."""
        suffix = req.prompt[start:]
        bt_row = jnp.asarray(self.block_table[slot])
        pos, off = start, 0
        logits = None
        for size, valid in self._buckets.plan_chunks(len(suffix)):
            toks = np.zeros((size,), np.int32)
            toks[:valid] = suffix[off:off + valid]
            fill = self._bucket_fill(size)
            self.pool_k, self.pool_v, logits = fill(
                self.params, self.pool_k, self.pool_v, bt_row,
                jnp.int32(pos), jnp.asarray(toks), jnp.int32(valid))
            pos += valid
            off += valid
        return logits

    # ------------------------------------------------------------------
    # host-side scheduler
    # ------------------------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens: int,
                    eos_token_id: Optional[int] = None, *,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    seed: int = 0, priority: int = 0) -> int:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token "
                             "(an empty prompt has no last position for "
                             "the prefill to sample from)")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1 (the prefill "
                             "argmax is already one generated token)")
        total = len(prompt) + max_new_tokens
        if total > self.MB * self.BS:
            raise ValueError(f"request needs {total} tokens, engine caps "
                             f"at {self.MB * self.BS} per sequence")
        if self._blocks_needed(total) > self.alloc.num_blocks:
            raise ValueError(
                f"request needs {self._blocks_needed(total)} pages, the "
                f"whole pool has {self.alloc.num_blocks} — it could never "
                f"admit (raise num_blocks or shrink the request)")
        if total > self.cfg.max_position_embeddings:
            raise ValueError("request exceeds max_position_embeddings")
        req = GenRequest(self._next_id, prompt, max_new_tokens,
                         eos_token_id, temperature=temperature,
                         top_k=top_k, top_p=top_p, seed=seed,
                         priority=int(priority))
        self._next_id += 1
        self.queue.append(req)
        # request tracing (ISSUE 20): adopt the ambient trace the
        # frontend/supervisor activated around this call; the queue
        # mark becomes the queue_wait span at admission
        from ..observability.tracing import TRACER
        if TRACER.enabled:
            tr = TRACER.current()
            if tr is not None:
                req.trace = tr
                tr.mark("enqueued")
        return req.req_id

    @staticmethod
    def _trace_of(req: "GenRequest"):
        """The request's live trace, or None (tracing disabled, or the
        request was submitted with no trace active)."""
        from ..observability.tracing import TRACER
        if not TRACER.enabled:
            return None
        return getattr(req, "trace", None)

    def _pick_token(self, req: GenRequest, logits: np.ndarray,
                    position: int) -> int:
        """Greedy, or sample on the request's own PRNG stream folded by
        ABSOLUTE position — reproducible per (seed, content), independent
        of batch composition and admission timing."""
        if req.temperature is None or req.temperature <= 0.0:
            return int(logits.argmax())
        return int(self._sample_rows([req], np.asarray(logits)[None],
                                     [position])[0])

    def _sampler(self):
        """The compiled fixed-width sampler: AOT-loaded when the engine
        warm-started, else jitted once."""
        if self._sampler_fn is None:
            self._sampler_fn = jax.jit(build_sampler())
        return self._sampler_fn

    def _sample_rows(self, reqs: List[GenRequest], logits_rows,
                     positions) -> np.ndarray:
        """Sample one token per request (rows aligned with ``reqs``).

        Rows are PADDED to the full decode width ``max_batch`` so every
        call — any sampled sub-batch size AND the single-row admission
        path — runs ONE compiled program instead of one per distinct
        width.  That one program is what ``aot/serve.py`` serializes, so
        warm-started engines sample with zero backend compiles.  Each
        row is computed independently (vmap), so padding cannot change
        a real row's token."""
        n = len(reqs)
        lg = np.zeros((self.B, logits_rows.shape[-1]), np.float32)
        lg[:n] = logits_rows
        seeds = np.zeros((self.B,), np.int32)
        pos = np.zeros((self.B,), np.int32)
        temps = np.ones((self.B,), np.float32)   # pad rows: no div-by-0
        topk = np.zeros((self.B,), np.int32)
        topp = np.zeros((self.B,), np.float32)
        pos[:n] = np.asarray(positions, np.int32)
        for i, r in enumerate(reqs):
            seeds[i] = r.seed
            temps[i] = r.temperature
            topk[i] = r.top_k or 0
            topp[i] = r.top_p or 0.0
        toks = self._sampler()(jnp.asarray(lg), jnp.asarray(seeds),
                               jnp.asarray(pos), jnp.asarray(temps),
                               jnp.asarray(topk), jnp.asarray(topp))
        return np.asarray(toks)[:n]

    def _blocks_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self.BS)

    @property
    def prefix_index(self) -> "collections.OrderedDict[bytes, int]":
        """Compatibility view of the HBM-resident tier of the prefix
        cache: ``{chained block digest: phys page}``, LRU order — the
        leak report and the pool-invariant tests read this; the live
        structure is the radix tree (``self.prefix_cache``)."""
        return collections.OrderedDict(self.prefix_cache.resident_items())

    def _cached_prefix(self, prompt: np.ndarray):
        """Longest cached block-aligned prefix: ``(resident_blocks,
        resident_pages, offloaded_nodes)``.  Resident pages are claimed
        via ``_RefPool.share``; offloaded nodes restore by exact-byte
        scatter into freshly acquired pages (``_restore_offloaded``).
        When the prompt is an exact multiple of BS, at least one block
        is left uncached so the suffix prefill has >= 1 token to
        produce next-token logits."""
        if not self.enable_prefix_caching:
            return 0, [], []
        full = len(prompt) // self.BS
        lookup = full - 1 if len(prompt) % self.BS == 0 else full
        pages, off = self.prefix_cache.walk(
            self._block_keys(prompt, lookup))
        return len(pages), pages, off

    def _block_keys(self, prompt: np.ndarray, n: int) -> List[bytes]:
        """Chained per-block digests — ONE definition shared with the
        fleet router's affinity summaries
        (``serving.prefix_cache.block_keys``)."""
        return self.prefix_cache.keys_for(prompt, n)

    def prefix_match_blocks(self, keys: List[bytes]) -> int:
        """Longest cached chain prefix for a precomputed key list,
        WITHOUT touching cache recency or refcounts — the read-only
        summary ``EngineRouter`` consults for prefix-affinity
        placement."""
        if not self.enable_prefix_caching:
            return 0
        return self.prefix_cache.match_blocks(keys)

    def _acquire_with_eviction(self, n: int) -> Optional[List[int]]:
        """Acquire pages, LRU-evicting prefix-cache blocks on pressure
        (leaf-first, so surviving chains stay walkable).  Only blocks
        whose page is held SOLELY by the cache (ref == 1) are evicted —
        evicting a shared block frees nothing and would throw away
        prefixes other requests still hit.  With an offload budget the
        victim's exact page bytes park in the host-RAM tier before the
        page is released (restored by scatter on the next hit).
        Callers must take their own reference on reused pages BEFORE
        acquiring, or an evicted twin of a 'shared' page could be
        handed back as private and the chunk fill would overwrite
        cached prefix KV."""
        while True:
            got = self.alloc.acquire(n)
            if got is not None:
                self.stats["pages_allocated"] += n
                return got
            node = self.prefix_cache.evictable(
                lambda p: self.alloc.ref.get(p, 0))
            if node is None:
                return None
            self._evict_prefix_block(node)

    def _evict_prefix_block(self, node) -> None:
        """Evict one resident cache block: offload its exact page bytes
        to the bounded host tier when configured (host-side gather —
        the same zero-compile convention as ``snapshot_slot``), then
        release the cache's pool reference."""
        cache = self.prefix_cache
        if cache.wants_offload:
            if self._kv_quant:
                # int8 pages travel with their per-(token, head) fp32
                # scales — both CRC-stamped, both restored by scatter
                k = np.asarray(self.pool_k.data)[:, node.phys].copy()
                v = np.asarray(self.pool_v.data)[:, node.phys].copy()
                ks = np.asarray(self.pool_k.scale)[:, node.phys].copy()
                vs = np.asarray(self.pool_v.scale)[:, node.phys].copy()
                phys = cache.evict(node, k, v, ks, vs)
            else:
                k = np.asarray(self.pool_k)[:, node.phys].copy()
                v = np.asarray(self.pool_v)[:, node.phys].copy()
                phys = cache.evict(node, k, v)
        else:
            phys = cache.evict(node)
        self.alloc.release([phys])
        from ..observability import REGISTRY
        if REGISTRY.enabled:
            REGISTRY.counter("serve.prefix.evictions_total").inc()
            if cache.wants_offload:
                REGISTRY.counter("serve.prefix.offloads_total").inc()
                REGISTRY.gauge("serve.prefix.offloaded_bytes").set(
                    cache.host_bytes)

    def _restore_offloaded(self, off, priv: List[int]) -> int:
        """Scatter offloaded prefix blocks' exact bytes into the first
        ``len(off)`` freshly acquired private pages, promoting each back
        to the resident tier (the cache takes a reference, exactly as
        if the block had never left HBM).  A CRC failure stops the
        restore at that block — typed event, ``restore_failures``
        counter — and the caller recomputes the remaining suffix by
        ordinary prefill: bit-rot costs FLOPs, never tokens.  Returns
        the number of blocks restored.  One host round trip total; the
        device copy runs through the pool-shaped op pre-warmed at
        construction (zero backend compiles, the ``serve_prefix_warm``
        budget row)."""
        if not off:
            return 0
        from ..observability import REGISTRY
        from ..serving.resilience import SpillCorruptError
        pk = pv = pks = pvs = None
        restored = 0
        for j, node in enumerate(off):
            try:
                node.verify()
                if (node.k_scale is not None) != self._kv_quant:
                    # an offloaded block whose quantization disagrees
                    # with the pool (e.g. restored cache state from a
                    # differently-configured engine) can never scatter
                    # — same typed demotion as bit-rot: recompute the
                    # suffix, never corrupt the pool
                    raise SpillCorruptError(
                        f"offloaded prefix block {node.key.hex()[:12]} "
                        "quantization does not match this engine's KV "
                        "pool — demoting to suffix recompute")
            except SpillCorruptError as e:
                self.prefix_cache.drop_host(node)
                if REGISTRY.enabled:
                    REGISTRY.counter(
                        "serve.prefix.restore_failures_total").inc()
                    REGISTRY.event("serve", action="prefix_bitrot",
                                   depth=int(node.depth),
                                   error=str(e)[:200])
                break
            if pk is None:
                if self._kv_quant:
                    pk = np.asarray(self.pool_k.data).copy()
                    pv = np.asarray(self.pool_v.data).copy()
                    pks = np.asarray(self.pool_k.scale).copy()
                    pvs = np.asarray(self.pool_v.scale).copy()
                else:
                    pk = np.asarray(self.pool_k).copy()
                    pv = np.asarray(self.pool_v).copy()
            pk[:, priv[j]] = node.k_bytes
            pv[:, priv[j]] = node.v_bytes
            if self._kv_quant:
                pks[:, priv[j]] = node.k_scale
                pvs[:, priv[j]] = node.v_scale
            self.prefix_cache.promote(node, priv[j])
            self.alloc.share([priv[j]])
            restored += 1
        if pk is not None:
            # owned copies, never aliases: the decode step donates the
            # pools (see restore_into_slot for the full rationale)
            if self._kv_quant:
                from ..ops.paged_kv import QuantizedKVPool
                self.pool_k = QuantizedKVPool(jnp.array(pk),
                                              jnp.array(pks))
                self.pool_v = QuantizedKVPool(jnp.array(pv),
                                              jnp.array(pvs))
            else:
                self.pool_k = jnp.array(pk)
                self.pool_v = jnp.array(pv)
        if restored and REGISTRY.enabled:
            REGISTRY.counter("serve.prefix.restores_total").inc(restored)
            REGISTRY.gauge("serve.prefix.offloaded_bytes").set(
                self.prefix_cache.host_bytes)
        return restored

    def _note_prefix_lookup(self, hit_blocks: int) -> None:
        """Account one admission-time cache consultation (miss or
        hit).  ``hit_blocks`` counts resident + restored blocks whose
        compute the suffix prefill will skip."""
        s = self.prefix_cache.stats
        s["lookups"] += 1
        from ..observability import REGISTRY
        if REGISTRY.enabled:
            REGISTRY.counter("serve.prefix.lookups_total").inc()
        if hit_blocks:
            s["hits"] += 1
            s["hit_blocks"] += hit_blocks
            s["hit_tokens"] += hit_blocks * self.BS
            if REGISTRY.enabled:
                REGISTRY.counter("serve.prefix.hits_total").inc()
                REGISTRY.counter("serve.prefix.hit_tokens_total").inc(
                    hit_blocks * self.BS)

    def _register_prefix(self, prompt: np.ndarray,
                         table: List[int]) -> None:
        """Insert every read-only (full, decode-untouched) prompt block
        into the radix tree — the cache parks one pool reference per
        new block, so retirement releases only the slot's references
        and the prefix outlives the request.  Decode writes start at
        position len(prompt), so all ``full`` blocks are immutable for
        the sequence's lifetime."""
        if not self.enable_prefix_caching:
            return
        full = len(prompt) // self.BS
        took = self.prefix_cache.insert(self._block_keys(prompt, full),
                                        table[:full])
        if took:
            self.alloc.share(took)
            self.stats["prefix_blocks_registered"] += len(took)
            from ..observability import REGISTRY
            if REGISTRY.enabled:
                REGISTRY.counter("serve.prefix.inserts_total").inc(
                    len(took))

    def _best_waiting_index(self) -> Optional[int]:
        """Queue index of the next request to admit: highest priority
        wins; FIFO within a priority class (queue position is arrival
        order — a preempted request re-enters at the FRONT, so it
        resumes before later arrivals of its own class)."""
        best, best_key = None, None
        for i, r in enumerate(self.queue):
            key = (-r.priority, i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _releasable_pages(self, slot: int) -> int:
        """Pages preempting ``slot`` would actually free: pages whose
        only live reference is the slot's (prefix-shared pages survive
        in the index and free nothing)."""
        return sum(1 for p in self.slot_pages[slot]
                   if self.alloc.ref.get(p) == 1)

    def _preempt_for_priority(self) -> None:
        """Evict lowest-priority RUNNING work for a strictly-higher-
        priority waiter when the batch/pool is saturated (ROADMAP
        2(c)).  One victim per pass, bounded by the batch width; a
        victim is only taken when eviction can actually make the
        waiter admissible (a slot opens, and the victims' private
        pages can close the page shortfall), so low-priority work is
        never spilled pointlessly."""
        for _ in range(self.B):
            idx = self._best_waiting_index()
            if idx is None:
                return
            cand = self.queue[idx]
            snap = self._spill.get(cand.req_id)
            if snap is not None:
                need, shared = snap.num_blocks, ()
            else:
                # admission reuses the waiter's cached prefix pages and
                # acquires only the remainder — the shortfall tests
                # must see the same need, or a saturated pool would
                # spill a low-priority tenant for a waiter that was
                # already admissible via shared prefix pages (offloaded
                # blocks still consume fresh pages, so they stay in
                # ``need``)
                L, shared, _off = self._cached_prefix(cand.prompt)
                need = self._blocks_needed(
                    len(cand.prompt) + cand.max_new_tokens) - L
            shared_set = set(shared)
            # the waiter's own prefix pages are counted in ``need``
            # already, and admission pins them before acquiring — they
            # are not evictable headroom on top of that
            evictable = sum(1 for p in self.prefix_index.values()
                            if self.alloc.ref.get(p) == 1
                            and p not in shared_set)
            have_slot = any(s is None for s in self.slots)
            if have_slot and self.alloc.free_blocks + evictable >= need:
                return                 # admissible without eviction
            victims = [s for s in range(self.B)
                       if self.slots[s] is not None
                       and self.slots[s].priority < cand.priority]
            if not victims:
                return
            releasable = sum(self._releasable_pages(s) for s in victims)
            if (self.alloc.free_blocks + evictable + releasable) < need:
                return                 # eviction could never admit cand
            # cheapest spill first: lowest priority, then fewest
            # committed KV positions, then slot index (deterministic)
            victims.sort(key=lambda s: (self.slots[s].priority,
                                        int(self.lengths[s]), s))
            self.preempt(victims[0])

    def preempt(self, slot: int) -> int:
        """Evict the RUNNING request in ``slot`` for later resumption:
        snapshot its committed KV pages + decode cursor to the host-RAM
        spill tier (CRC-checked — ``serving/resilience.py``), release
        its pool references through the ordinary ``_free_slot`` path,
        and requeue it at the FRONT of the waiting queue.  The resumed
        stream is bit-identical to an unpreempted run: restore puts the
        exact page bytes into fresh blocks and the sampler is keyed by
        (seed, absolute position), so neither eviction nor re-admission
        can change a token (pinned by tests/test_serving_resilience.py).
        Returns the preempted request id."""
        req = self.slots[slot]
        if req is None:
            raise ValueError(f"slot {slot} is not running a request")
        import time
        from ..serving.resilience import snapshot_slot
        tr = self._trace_of(req)
        t_sp = tr.now() if tr is not None else 0.0
        t0 = time.perf_counter()
        snap = snapshot_slot(self, slot)
        self._spill_put(req.req_id, snap)
        self._free_slot(slot)
        self.queue.appendleft(req)
        dt = time.perf_counter() - t0
        if tr is not None:
            tr.add("preempt_spill", t_sp, tr.now(),
                   committed=int(snap.length), priority=req.priority)
            tr.mark("enqueued")    # queue_wait resumes until re-admission
        self.resilience["preemptions"] += 1
        self.resilience["spill_save_secs"] += dt
        from ..observability import REGISTRY
        if REGISTRY.enabled:
            REGISTRY.counter("serve.resilience.preemptions_total").inc()
            REGISTRY.gauge("serve.resilience.spilled_bytes").set(
                self.spilled_bytes)
            REGISTRY.histogram("serve.resilience.preempt_save_secs",
                               unit="s").record(dt)
            REGISTRY.event("serve", action="preempt", req_id=req.req_id,
                           priority=req.priority,
                           committed=int(snap.length))
        return req.req_id

    def _spill_put(self, req_id: int, snap) -> None:
        """Insert a snapshot into the (possibly capacity-bounded) spill
        tier.  Snapshots evicted to honor the cap DEMOTE their request
        to replay-from-prefix: the request keeps waiting in the queue
        with its committed tokens, and admission recomputes its KV from
        that prefix (``_replay_into_slot``) — a typed event plus the
        ``serve.resilience.spill_evictions_total`` counter per victim,
        never silent host-memory growth."""
        from ..observability import REGISTRY
        for rid in self._spill.put(req_id, snap):
            self.resilience["spill_evictions"] += 1
            if REGISTRY.enabled:
                REGISTRY.counter(
                    "serve.resilience.spill_evictions_total").inc()
                REGISTRY.event("serve", action="spill_evict", req_id=rid,
                               tier_bytes=self._spill.nbytes,
                               cap_bytes=self._spill.capacity_bytes)

    def spill_compatible(self, snap) -> bool:
        """Whether a KV snapshot from another engine can restore into
        THIS pool: identical page geometry (layers, block size, kv
        heads, head dim, dtype) and a table wide enough to hold it —
        the precondition for cross-replica snapshot transplant
        (``serving/fleet.py``).  Quantized pools additionally require
        the snapshot to carry per-page scales (and vice versa) — an
        int8 snapshot can never scatter into a bf16 pool."""
        if (getattr(snap, "k_scale", None) is not None) != \
                self._kv_quant:
            return False
        ref = self.pool_k.data if self._kv_quant else self.pool_k
        return (snap.k_pages.shape[0] == ref.shape[0]
                and snap.k_pages.shape[2:] == ref.shape[2:]
                and snap.k_pages.dtype == ref.dtype
                and snap.num_blocks <= self.MB)

    def adopt_preempted(self, req: GenRequest, snap) -> None:
        """Transplant a preempted request (committed tokens + spilled
        KV snapshot) extracted from ANOTHER engine of identical
        geometry: the snapshot enters this engine's spill tier and the
        request joins the FRONT of the queue, so admission restores the
        exact page bytes into fresh local blocks — same path as a local
        preemption, bit-identical resumption."""
        if not self.spill_compatible(snap):
            pshape = (self.pool_k.data if self._kv_quant
                      else self.pool_k).shape
            raise ValueError(
                "KV snapshot geometry does not match this engine's pool "
                f"(snapshot pages {snap.k_pages.shape}, pool {pshape})")
        if req.req_id in self._spill:
            raise ValueError(f"request {req.req_id} already spilled here")
        self.queue.appendleft(req)
        self._spill_put(req.req_id, snap)

    def _restore_preempted(self, slot: int, req: GenRequest, idx: int,
                           snap) -> bool:
        """Re-admit a preempted request: fresh blocks, spilled KV bytes
        scattered back, decode cursor restored — no recompute, no new
        first token.  False when the pool cannot host it yet."""
        import time
        from ..serving.resilience import restore_into_slot
        priv = self._acquire_with_eviction(snap.num_blocks)
        if priv is None:
            return False
        del self.queue[idx]
        tr = self._trace_of(req)
        if tr is not None:
            t_rs = tr.now()
            tq = tr.take_mark("enqueued")
            if tq is not None:
                tr.add("queue_wait", tq, t_rs)
        self.block_table[slot, :] = -1
        self.block_table[slot, :snap.num_blocks] = priv
        self.slot_pages[slot] = priv
        t0 = time.perf_counter()
        try:
            restore_into_slot(self, slot, snap)
        except BaseException:
            # exactly-once release; the snapshot is unusable, so the
            # request is DROPPED from this engine (a supervising
            # wrapper replays it from its committed prefix instead)
            self.alloc.release(self.slot_pages[slot])
            self.slot_pages[slot] = []
            self.block_table[slot, :] = -1
            del self._spill[req.req_id]
            raise
        del self._spill[req.req_id]
        self.slots[slot] = req
        self.lengths[slot] = snap.length
        self.tokens[slot] = snap.next_token
        dt = time.perf_counter() - t0
        self.resilience["restores"] += 1
        self.resilience["spill_restore_secs"] += dt
        from ..observability import REGISTRY
        if REGISTRY.enabled:
            REGISTRY.counter("serve.resilience.restores_total").inc()
            REGISTRY.gauge("serve.resilience.spilled_bytes").set(
                self.spilled_bytes)
            REGISTRY.histogram("serve.resilience.preempt_restore_secs",
                               unit="s").record(dt)
            REGISTRY.event("serve", action="restore", req_id=req.req_id,
                           priority=req.priority,
                           committed=int(snap.length))
        if tr is not None:
            tr.add("preempt_restore", t_rs, tr.now(),
                   committed=int(snap.length))
        return True

    def _replay_into_slot(self, slot: int, req: GenRequest,
                          idx: int) -> bool:
        """Re-admit a preempted request whose KV snapshot is GONE (the
        bounded spill tier evicted it): recompute the committed KV by
        prefilling the committed token prefix ``prompt + out[:-1]`` and
        resume the decode cursor at the pending token ``out[-1]``.

        Prefill-computed KV is bit-identical to decode-computed KV (the
        foundation of prefix caching and crash replay, pinned since
        ISSUE 11), so demotion costs prefill FLOPs, never tokens.  The
        final-position logits are discarded — they would only
        re-produce ``out[-1]``, which is already committed.  False when
        the pool cannot host the request yet."""
        committed = np.concatenate(
            [req.prompt, np.asarray(req.out[:-1], np.int32)]) \
            if len(req.out) > 1 else req.prompt
        need = self._blocks_needed(len(req.prompt) + req.max_new_tokens)
        L, shared, off = self._cached_prefix(committed)
        self.alloc.share(shared)
        priv = self._acquire_with_eviction(need - L)
        if priv is None:
            self.alloc.release(shared)
            return False
        restored = self._restore_offloaded(off, priv)
        self._note_prefix_lookup(L + restored)
        self.stats["prefix_blocks_reused"] += L + restored
        del self.queue[idx]
        tr = self._trace_of(req)
        if tr is not None:
            t_rp = tr.now()
            tq = tr.take_mark("enqueued")
            if tq is not None:
                tr.add("queue_wait", tq, t_rp)
        table = shared + priv
        self.block_table[slot, :] = -1
        self.block_table[slot, :need] = table
        self.slot_pages[slot] = table
        shadow = GenRequest(req.req_id, committed, 1, None)
        try:
            self._prefill_into_slot(slot, shadow, L + restored)
            self._register_prefix(req.prompt, table)
        except BaseException:
            # exactly-once release, same contract as the fresh path
            self.alloc.release(self.slot_pages[slot])
            self.slot_pages[slot] = []
            self.block_table[slot, :] = -1
            self.queue.appendleft(req)
            raise
        self.slots[slot] = req
        self.lengths[slot] = len(committed)
        self.tokens[slot] = req.out[-1]
        self.resilience["prefix_replays"] += 1
        from ..observability import REGISTRY
        if REGISTRY.enabled:
            REGISTRY.counter(
                "serve.resilience.prefix_replays_total").inc()
            REGISTRY.event("serve", action="prefix_replay",
                           req_id=req.req_id, committed=len(committed))
        if tr is not None:
            tr.add("prefix_replay", t_rp, tr.now(),
                   committed=len(committed),
                   cached_blocks=L + restored)
        return True

    def _prefill_into_slot(self, slot: int, req: GenRequest,
                           L: int) -> np.ndarray:
        """Run the prompt into the slot's (already mapped) pages and
        return next-token logits — the three prefill tiers the admission
        path chooses between.  Extracted so the fault-injection harness
        (tests/faults.py) has one seam for crash-mid-prefill."""
        from ..models.generation import build_llama_decoder
        T0 = len(req.prompt)
        # the honest prefill-cost meter the cache A/B bench reads:
        # tokens whose KV this admission actually computes (cache hits
        # and offload restores shrink it; padding never counts)
        self.stats["prefill_tokens_computed"] += T0 - L * self.BS
        table = self.slot_pages[slot]
        if self._buckets is not None:
            # declared-bucket prefill (cold prompts AND cache-hit
            # suffixes): fixed chunk programs, no per-length jit
            return self._fill_prompt_bucketed(slot, req, L * self.BS)
        if L or self.quant_config is not None:
            # suffix-only prefill against the cached pages.  Quantized
            # engines route COLD prompts here too (start=0): the dense
            # tier below computes full-width KV and scatters it into
            # the pool raw, which would skip both the quantized matmul
            # path and the pool's code+scale layout — one prefill tier
            # for every quant admission keeps greedy output
            # bit-identical across cold/hit/replay paths
            suffix = req.prompt[L * self.BS:]
            fill = self._chunk_fill(len(suffix))
            self.pool_k, self.pool_v, logits = fill(
                self.params, self.pool_k, self.pool_v,
                jnp.asarray(self.block_table[slot]),
                jnp.int32(L * self.BS), jnp.asarray(suffix))
            return logits
        # dense prefill, jitted once per distinct prompt length
        jprefill = self._prefill_cache.get(T0)
        if jprefill is None:
            prefill, _ = build_llama_decoder(self.cfg, T0,
                                             use_pallas=False)
            jprefill = jax.jit(prefill)
            self._prefill_cache.put(T0, jprefill)
        cache, logits = jprefill(self.params, req.prompt[None, :])
        # move prompt KV into the pool pages ON DEVICE with ONE
        # scatter per pool; the padded tail of the last page
        # holds zeros, masked by lengths
        nb = self._blocks_needed(T0)
        pad = nb * self.BS - T0
        kc, vc = cache["k"][:, 0], cache["v"][:, 0]
        pages = np.asarray(table[:nb])

        def paged_view(x):             # [L, nb, BS, Hkv, D]
            x = jnp.pad(x, ((0, 0), (0, pad), (0, 0), (0, 0)))
            return x.reshape(x.shape[0], nb, self.BS,
                             *x.shape[2:])

        self.pool_k = self.pool_k.at[:, pages].set(
            paged_view(kc).astype(self.pool_k.dtype))
        self.pool_v = self.pool_v.at[:, pages].set(
            paged_view(vc).astype(self.pool_v.dtype))
        return logits

    def _admit(self) -> None:
        """Admit waiting requests into free slots while pages allow,
        highest priority first (FIFO within a class).  On a
        prefix-cache hit the shared pages are reused and only the
        SUFFIX runs (paged chunk fill); cold prompts prefill densely
        and their KV moves into the pool pages; a PREEMPTED request
        restores its spilled KV into fresh blocks instead of
        recomputing.  Under saturation, strictly-lower-priority running
        requests are evicted for higher-priority waiters
        (``_preempt_for_priority``)."""
        if self.enable_preemption:
            self._preempt_for_priority()
        for slot in range(self.B):
            if self.slots[slot] is not None:
                continue
            idx = self._best_waiting_index()
            if idx is None:
                break
            req = self.queue[idx]
            snap = self._spill.get(req.req_id)
            if snap is not None:
                if not self._restore_preempted(slot, req, idx, snap):
                    break              # head-of-line waits for pages
                continue
            if req.out:
                # preempted, but the bounded spill tier evicted the
                # snapshot: demoted to replay-from-prefix
                if not self._replay_into_slot(slot, req, idx):
                    break              # head-of-line waits for pages
                continue
            T0 = len(req.prompt)
            total = T0 + req.max_new_tokens
            need = self._blocks_needed(total)
            L, shared, off = self._cached_prefix(req.prompt)
            # take the slot's reference FIRST: eviction under pressure
            # must never free (and re-hand-out) a page we are reusing
            self.alloc.share(shared)
            priv = self._acquire_with_eviction(need - L)
            if priv is None:
                self.alloc.release(shared)
                break                      # head-of-line waits for pages
            tr = self._trace_of(req)
            t_rs = tr.now() if tr is not None else 0.0
            # offloaded continuation: exact bytes scatter into the
            # leading private pages (no recompute); a CRC failure
            # cleanly demotes the rest to ordinary suffix prefill
            restored = self._restore_offloaded(off, priv)
            self._note_prefix_lookup(L + restored)
            self.stats["prefix_blocks_reused"] += L + restored
            del self.queue[idx]
            if tr is not None:
                tq = tr.take_mark("enqueued")
                if tq is not None:
                    tr.add("queue_wait", tq, t_rs)
                if off:
                    tr.add("prefix_restore", t_rs, tr.now(),
                           blocks=restored)
                if L + restored:
                    tr.event("prefix_hit", cached_blocks=L,
                             restored_blocks=restored,
                             tokens_skipped=(L + restored) * self.BS)
                t_pf = tr.now()
            table = shared + priv
            self.block_table[slot, :] = -1
            self.block_table[slot, :need] = table
            self.slot_pages[slot] = table
            try:
                logits = self._prefill_into_slot(slot, req, L + restored)
                self._register_prefix(req.prompt, table)
                first = self._pick_token(req, np.asarray(logits)[0],
                                         position=T0)
            except BaseException:
                # exactly-once page release (ISSUE 11 hardening): the
                # slot never went live, so neither cancel() nor a later
                # drain can see these references — drop them here, and
                # keep the request WAITING so a retrying caller (or a
                # supervisor replay) still owns it
                self.alloc.release(self.slot_pages[slot])
                self.slot_pages[slot] = []
                self.block_table[slot, :] = -1
                self.queue.appendleft(req)
                if tr is not None:
                    tr.add("prefill", t_pf, tr.now(), tokens=T0,
                           error=True)
                    tr.mark("enqueued")   # still waiting (retry/replay)
                raise
            if tr is not None:
                tr.add("prefill", t_pf, tr.now(), tokens=T0,
                       cached_tokens=(L + restored) * self.BS)
            self._append_tok(req, first)
            self.slots[slot] = req
            self.lengths[slot] = T0
            self.tokens[slot] = first

    @staticmethod
    def _append_tok(req: GenRequest, tok: int) -> None:
        req.out.append(tok)
        if req.eos_token_id is not None and req.eos_pos is None \
                and tok == req.eos_token_id:
            req.eos_pos = len(req.out) - 1

    def _retire_done(self) -> None:
        for s in range(self.B):
            req = self.slots[s]
            if req is not None and (len(req.out) >= req.max_new_tokens
                                    or req.eos_pos is not None):
                # truncate anything after the first eos
                if req.eos_pos is not None:
                    req.out = req.out[:req.eos_pos + 1]
                self._retire(s)

    def _free_slot(self, slot: int) -> None:
        self.alloc.release(self.slot_pages[slot])
        self.slot_pages[slot] = []
        self.block_table[slot, :] = -1
        self.lengths[slot] = 0
        self.tokens[slot] = 0
        self.slots[slot] = None

    def _retire(self, slot: int) -> None:
        req = self.slots[slot]
        self.finished[req.req_id] = np.concatenate(
            [req.prompt, np.asarray(req.out, np.int32)])
        self._free_slot(slot)

    def cancel(self, req_id: int) -> bool:
        """Abort a queued or in-flight request.  Its pages free
        immediately; no result is reported.  Returns False when the id
        is unknown or already finished.

        Accounting contract (regression-pinned by
        test_serving_engine.py::test_cancel_accounting_*): a WAITING
        request holds no page references, so removal from the queue is
        the whole operation; a SCHEDULED request holds exactly one
        reference per page in its table (including prefix-shared pages,
        whose extra references live in the prefix index) and
        ``_free_slot`` releases each exactly once — the ``_RefPool``
        raises on any double free, so a drift here fails loudly instead
        of corrupting another request's KV."""
        for i, req in enumerate(self.queue):
            if req.req_id == req_id:
                del self.queue[i]
                # a preempted waiter holds no pool references, but its
                # spilled host-RAM snapshot must not outlive it
                self._spill.pop(req_id, None)
                return True
        for slot in range(self.B):
            req = self.slots[slot]
            if req is not None and req.req_id == req_id:
                self._free_slot(slot)
                return True
        return False

    def step(self) -> Dict[int, np.ndarray]:
        """One scheduler iteration: admit, decode every active slot,
        collect tokens, retire finished.  Returns newly finished
        {req_id: full ids} (empty dict when idle)."""
        # retire first so freed slots/pages admit this very iteration;
        # then AGAIN after admission — the prefill's first token can
        # already satisfy the budget (max_new_tokens=1) or hit eos, and
        # such a request must not enter the decode batch
        self._retire_done()
        self._admit()
        self._retire_done()
        active = [s for s in range(self.B) if self.slots[s] is not None]
        if not active:
            self.last_logits = None     # nothing decoded this iteration
            out = self.finished
            self.finished = {}
            return out
        from ..observability.tracing import TRACER
        _tracing = TRACER.enabled
        if self._spec is not None and self._spec.config.enabled:
            # speculative decode: draft K, verify K+1 in one dispatch,
            # commit the accepted prefix (spec_decode/runner.py) —
            # greedy output is bit-identical to the baseline branch
            pre = sum(len(self.slots[s].out) for s in active)
            pre_by_slot = {s: len(self.slots[s].out) for s in active} \
                if _tracing else None
            m0 = time.monotonic() if _tracing else 0.0
            self._spec.run_decode(active)
            if _tracing:
                m1 = time.monotonic()
                for s in active:
                    r = self.slots[s]
                    tr = self._trace_of(r) if r is not None else None
                    if tr is not None:
                        tr.add("spec_decode_step",
                               m0 - tr.mono_t0, m1 - tr.mono_t0,
                               batch=len(active),
                               committed=len(r.out) - pre_by_slot[s])
            self.decode_steps += 1
            self.decode_slot_steps += len(active)
            self.decode_tokens += \
                sum(len(self.slots[s].out) for s in active) - pre
            out = self.finished
            self.finished = {}
            return out
        m0 = time.monotonic() if _tracing else 0.0
        self.pool_k, self.pool_v, logits = self._step(
            self.params, self.pool_k, self.pool_v,
            jnp.asarray(self.block_table), jnp.asarray(self.lengths),
            jnp.asarray(self.tokens))
        self.last_logits = np.asarray(logits)
        for s in active:
            self.lengths[s] += 1            # the fed token's KV is stored
        sampled = [s for s in active
                   if (self.slots[s].temperature or 0.0) > 0.0]
        picks: Dict[int, int] = {}
        if sampled:
            # ONE dispatch + sync for the whole sampled sub-batch
            toks = self._sample_rows(
                [self.slots[s] for s in sampled],
                self.last_logits[sampled],
                [int(self.lengths[s]) for s in sampled])
            picks = dict(zip(sampled, toks.tolist()))
        for s in active:
            req = self.slots[s]
            tok = picks.get(s)
            if tok is None:
                tok = int(self.last_logits[s].argmax())
            self._append_tok(req, int(tok))
            self.tokens[s] = int(tok)
        if _tracing:
            m1 = time.monotonic()
            for s in active:
                tr = self._trace_of(self.slots[s])
                if tr is not None:
                    tr.add("decode_step", m0 - tr.mono_t0,
                           m1 - tr.mono_t0, batch=len(active))
        self.decode_steps += 1
        self.decode_slot_steps += len(active)
        self.decode_tokens += len(active)
        out = self.finished
        self.finished = {}
        return out

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        """Drive steps until queue and batch drain; returns all results.
        ``self.finished`` is part of the liveness condition: a step that
        raised AFTER retiring a request (e.g. a typed spill-restore
        failure during admission) strands that result in ``finished``,
        and a later drain must still deliver it."""
        results: Dict[int, np.ndarray] = {}
        while self.queue or self.finished \
                or any(s is not None for s in self.slots):
            results.update(self.step())
        return results

    # ------------------------------------------------------------------
    # serve-path introspection (paddle_tpu/serving front-end + telemetry)
    # ------------------------------------------------------------------
    @property
    def queue_depth(self) -> int:
        """Requests admitted into the engine but not yet scheduled."""
        return len(self.queue)

    @property
    def active_requests(self) -> int:
        return sum(1 for s in self.slots if s is not None)

    def batch_occupancy(self) -> float:
        """Fraction of decode-batch slots currently running a request."""
        return self.active_requests / float(self.B)

    def kv_utilization(self) -> float:
        """Fraction of KV pool blocks holding live references (slots or
        prefix index)."""
        return 1.0 - self.alloc.free_blocks / float(self.alloc.num_blocks)

    def kv_leak_report(self) -> Dict[str, int]:
        """Cross-check the refcount pool against the structures that are
        supposed to hold its references (slot tables + prefix index).

        ``leaked`` counts blocks whose refcount disagrees with the
        holders, plus holder entries with no refcount; ``unaccounted``
        counts blocks that are neither free nor referenced.  Both must
        be zero after any drain — asserted by the loadgen smoke and the
        cancellation regression tests."""
        held: Dict[int, int] = {}
        for pages in self.slot_pages:
            for p in pages:
                held[p] = held.get(p, 0) + 1
        for p in self.prefix_index.values():
            held[p] = held.get(p, 0) + 1
        leaked = sum(1 for p, r in self.alloc.ref.items()
                     if held.get(p, 0) != r)
        leaked += sum(1 for p in held if p not in self.alloc.ref)
        return {
            "free_blocks": self.alloc.free_blocks,
            "index_blocks": len(self.prefix_index),
            "slot_blocks": sum(len(p) for p in self.slot_pages),
            "leaked": leaked,
            "unaccounted": (self.alloc.num_blocks - self.alloc.free_blocks
                            - len(self.alloc.ref)),
        }

    @property
    def spilled_bytes(self) -> int:
        """Host-RAM bytes currently held by preempted-request KV
        snapshots (the spill tier)."""
        return sum(s.nbytes for s in self._spill.values())

    def resilience_stats(self) -> Dict[str, object]:
        """Preemption-side resilience counters for bench rows / serve
        telemetry (the supervisor adds the crash-recovery side)."""
        s: Dict[str, object] = dict(self.resilience)
        s["spilled_requests"] = len(self._spill)
        s["spilled_bytes"] = self.spilled_bytes
        return s

    def prefix_stats(self) -> Dict[str, object]:
        """Cross-request prefix-cache counters and point-in-time state
        for bench rows / the ``serve.prefix.*`` gauges
        (``ServeMetrics.publish_engine``)."""
        s: Dict[str, object] = dict(self.prefix_cache.stats)
        s["enabled"] = self.enable_prefix_caching
        s["cached_blocks"] = self.prefix_cache.resident_blocks
        s["offloaded_blocks"] = self.prefix_cache.offloaded_blocks
        s["offloaded_bytes"] = self.prefix_cache.host_bytes
        s["prefill_tokens_computed"] = \
            self.stats["prefill_tokens_computed"]
        lk = s["lookups"]
        s["hit_rate"] = (s["hits"] / lk) if lk else None
        return s

    def spec_stats(self) -> Optional[Dict[str, object]]:
        """Speculation counters for bench rows / serve telemetry, or
        None when the engine decodes baseline (no ``spec_config``).
        ``engine_steps_per_token`` counts per-slot decode iterations
        per decode token, so baseline decode measures exactly 1.0 at
        any batch size — < 1.0 is accepted speculation, nothing else."""
        if self._spec is None:
            return None
        s: Dict[str, object] = dict(self._spec.stats)
        s["enabled"] = self._spec.config.enabled
        s["k"] = self._spec.config.k
        s["acceptance_rate"] = self._spec.acceptance_rate
        s["engine_steps_per_token"] = (
            self.decode_slot_steps / self.decode_tokens
            if self.decode_tokens else None)
        return s

    def aot_stats(self) -> Dict[str, object]:
        """Warm-start observability for bench rows/telemetry: whether
        artifacts loaded (and why not), plus declared-bucket hit/miss
        counts."""
        s: Dict[str, object] = {"aot_loaded": self.aot_loaded}
        if self.aot_error is not None:
            s["aot_error"] = self.aot_error
        if self._buckets is not None:
            s.update(self._buckets.stats())
        return s
