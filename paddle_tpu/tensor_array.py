"""TensorArray (reference phi/core/tensor_array.h + python
paddle.tensor.array_* — the LoDTensorArray used by legacy control flow).

TPU-first: a Python list of Tensors with integer indices — exactly how
the reference's dygraph mode implements it.  Inside traced (``jit``/lax)
control flow use :meth:`TensorArray.stack` + ``dynamic_update_slice`` on
the stacked array instead: traced indices cannot address a Python list,
and the stacked [n, ...] form is the static-shape representation XLA
needs.
"""

from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp

from .core.tensor import Tensor

__all__ = ["TensorArray", "create_array", "array_write", "array_read",
           "array_length"]


class TensorArray:
    def __init__(self, dtype="float32"):
        self.dtype = dtype
        self._items: List[Tensor] = []

    def append(self, x) -> "TensorArray":
        self._items.append(x if isinstance(x, Tensor) else Tensor(x))
        return self

    def write(self, i: int, x) -> "TensorArray":
        i = int(i)
        if i == len(self._items):
            self.append(x)
        else:
            self._items[i] = x if isinstance(x, Tensor) else Tensor(x)
        return self

    def read(self, i: int) -> Tensor:
        return self._items[int(i)]

    def __len__(self):
        return len(self._items)

    def __getitem__(self, i):
        return self._items[i]

    def stack(self, axis: int = 0) -> Tensor:
        return Tensor(jnp.stack([t._value for t in self._items], axis=axis))

    def pop(self, i: int = -1) -> Tensor:
        return self._items.pop(i)


def create_array(dtype="float32", initialized_list=None) -> TensorArray:
    arr = TensorArray(dtype)
    for x in initialized_list or ():
        arr.append(x)
    return arr


def array_write(x, i, array: Optional[TensorArray] = None) -> TensorArray:
    if array is None:
        array = TensorArray()
    idx = int(getattr(i, "_value", i))
    array.write(idx, x)
    return array


def array_read(array: TensorArray, i) -> Tensor:
    return array.read(int(getattr(i, "_value", i)))


def array_length(array: TensorArray):
    from .core.dtypes import index_dtype
    return Tensor(jnp.asarray(len(array), index_dtype()))
