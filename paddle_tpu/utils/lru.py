"""Small bounded LRU used to cap per-shape XLA-executable caches.

One shared implementation for every site that jits per shape signature
(models/generation.py rollout cache, inference/serving.py prefill and
chunk-fill caches): a serving workload with many distinct prompt lengths
must not retain unboundedly many compiled programs.
"""

from __future__ import annotations

import collections
from typing import Any, Optional


class LRUCache:
    def __init__(self, maxsize: int = 16):
        self.maxsize = maxsize
        self._d: "collections.OrderedDict[Any, Any]" = \
            collections.OrderedDict()

    def get(self, key) -> Optional[Any]:
        val = self._d.get(key)
        if val is not None:
            self._d.move_to_end(key)
        return val

    def put(self, key, val) -> None:
        self._d[key] = val
        self._d.move_to_end(key)
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)

    def __len__(self) -> int:
        return len(self._d)

    def clear(self) -> None:
        self._d.clear()
