"""paddle.utils parity: cpp_extension custom-op toolchain (and room for
the misc utils the reference keeps here)."""

from . import cpp_extension  # noqa: F401

import importlib as _importlib
import warnings as _warnings


def flatten(nest):
    """Flatten a nested list/tuple/dict structure to a flat list (reference:
    python/paddle/utils/layers_utils.py:166).  Tensors are leaves."""
    out = []

    def _walk(x):
        if isinstance(x, dict):
            for k in sorted(x):
                _walk(x[k])
        elif isinstance(x, (list, tuple)):
            for v in x:
                _walk(v)
        else:
            out.append(x)

    _walk(nest)
    return out


def pack_sequence_as(structure, flat_sequence):
    """Inverse of :func:`flatten` (reference: layers_utils.py:216)."""
    it = iter(flat_sequence)

    def _build(x):
        if isinstance(x, dict):
            return {k: _build(x[k]) for k in sorted(x)}
        if isinstance(x, tuple) and hasattr(x, "_fields"):   # namedtuple
            return type(x)(*[_build(v) for v in x])
        if isinstance(x, (list, tuple)):
            return type(x)(_build(v) for v in x)
        return next(it)

    return _build(structure)


def map_structure(func, *structures):
    """Apply ``func`` leaf-wise over parallel nested structures (reference:
    layers_utils.py:239)."""
    flats = [flatten(s) for s in structures]
    mapped = [func(*vals) for vals in zip(*flats)]
    return pack_sequence_as(structures[0], mapped)


def deprecated(update_to="", since="", reason="", level=0):
    """Decorator marking an API deprecated (reference: utils/deprecated.py)."""
    def wrapper(func):
        def inner(*args, **kwargs):
            if level > 0:
                _warnings.warn(
                    f"{func.__name__} is deprecated since {since}: {reason}",
                    DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        inner.__name__ = func.__name__
        inner.__doc__ = func.__doc__
        return inner
    return wrapper


def try_import(module_name, err_msg=None):
    """Import a module, raising a friendly error when absent (reference:
    utils/lazy_import.py)."""
    try:
        return _importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"Module {module_name!r} is required but not "
                          "installed.")


def require_version(min_version, max_version=None):
    """Check the installed framework version is within range (reference:
    utils/install_check.py style).  Our version scheme tracks the repo."""
    return True


def run_check():
    """Smoke-check the install: one tiny matmul on the default device
    (reference: utils/install_check.py run_check)."""
    import jax.numpy as jnp
    a = jnp.ones((2, 2))
    b = (a @ a).sum()
    print(f"paddle_tpu run_check passed (result={float(b)})")


__all__ = ["deprecated", "run_check", "require_version", "try_import",
           "flatten", "pack_sequence_as", "map_structure"]
