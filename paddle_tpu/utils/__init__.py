"""paddle.utils parity: cpp_extension custom-op toolchain (and room for
the misc utils the reference keeps here)."""

from . import cpp_extension  # noqa: F401
