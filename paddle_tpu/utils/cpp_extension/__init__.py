"""Custom C++ op toolchain (reference python/paddle/utils/cpp_extension —
``load``/``setup``/``CppExtension`` — and the phi/capi custom-op ABI).

TPU-native shape: the extension's kernels run on the HOST and enter the
XLA program as ``jax.pure_callback`` custom calls, so a loaded op works in
eager mode, under ``jax.jit``, and inside compiled train steps.  Device-side
custom kernels are written in Pallas (ops/pallas/) — the reference's CUDA
custom-op path maps to that, not to this loader.

JIT compile + load (the reference's ``load``):

    from paddle_tpu.utils.cpp_extension import load
    mod = load(name="my_ops", sources=["my_ops.cc"])
    y = mod.relu_cubed(x)          # registered via PT_REGISTER_OP

The C ABI lives in ``pt_extension.h`` (shipped next to this file); ops
receive float32 tensors and write one float32 output whose shape is the
first input's unless ``out_shape_fn`` overrides it at wrap time.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import sys
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["load", "CppExtension", "get_include", "CustomOpModule"]

_HERE = os.path.dirname(os.path.abspath(__file__))


def get_include() -> str:
    """Directory containing ``pt_extension.h`` (reference
    ``paddle.utils.cpp_extension.get_include``)."""
    return _HERE


def CppExtension(sources: Sequence[str], *args, **kwargs):
    """setuptools-style descriptor for parity with the reference's
    ``setup(ext_modules=CppExtension(...))`` flow; ``load`` consumes it."""
    return {"sources": list(sources), "args": args, "kwargs": kwargs}


class CustomOpModule:
    """Loaded extension: one attribute per registered op."""

    def __init__(self, name: str, lib: ctypes.CDLL,
                 op_names: Sequence[str],
                 out_shape_fns: Optional[Dict[str, Callable]] = None):
        self.name = name
        self._lib = lib
        self.op_names = list(op_names)
        shape_fns = out_shape_fns or {}
        for op in self.op_names:
            setattr(self, op, self._make(op, shape_fns.get(op)))

    def _compute(self, op: str, out_shape, *arrays):
        arrays = [np.ascontiguousarray(np.asarray(a, np.float32))
                  for a in arrays]
        n = len(arrays)
        data = (ctypes.POINTER(ctypes.c_float) * n)(*[
            a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))
            for a in arrays])
        shapes = np.concatenate([np.asarray(a.shape, np.int64)
                                 for a in arrays]) if n else \
            np.zeros(0, np.int64)
        ndims = np.asarray([a.ndim for a in arrays], np.int32)
        out = np.zeros(out_shape, np.float32)
        oshape = np.asarray(out_shape, np.int64)
        rc = self._lib.pt_op_compute(
            op.encode(), n, data,
            shapes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ndims.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            oshape.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(out_shape))
        if rc != 0:
            raise RuntimeError(f"custom op {op!r} not found in "
                               f"extension {self.name!r}")
        return out

    def _make(self, op: str, out_shape_fn: Optional[Callable]):
        def call(*xs, **kwargs):
            from ...core.dispatch import run_op
            vals = [jnp.asarray(getattr(x, "_value", x)) for x in xs]
            shp = (tuple(out_shape_fn(*[v.shape for v in vals]))
                   if out_shape_fn else tuple(vals[0].shape))

            def impl(*vs):
                # host callback: runs the C++ kernel; inside jit it lowers
                # to an XLA custom call (the capi custom-op execution path)
                return jax.pure_callback(
                    lambda *arrs: self._compute(op, shp, *arrs),
                    jax.ShapeDtypeStruct(shp, jnp.float32), *vs,
                    vmap_method="sequential")

            return run_op(f"{self.name}.{op}", impl, tuple(vals), {},
                          differentiable=False)

        call.__name__ = op
        return call


def _build(name: str, sources: Sequence[str], extra_cflags: Sequence[str],
           extra_include_paths: Sequence[str], build_directory: str,
           verbose: bool) -> str:
    os.makedirs(build_directory, exist_ok=True)
    tag = hashlib.sha1()
    hdrs = [os.path.join(_HERE, "pt_extension.h")]
    for d in extra_include_paths:
        for fn in sorted(os.listdir(d)):
            if fn.endswith((".h", ".hpp", ".hh")):
                hdrs.append(os.path.join(d, fn))
    for s in list(sources) + hdrs:
        with open(s, "rb") as f:
            tag.update(f.read())
    tag.update(" ".join(extra_cflags).encode())
    so = os.path.join(build_directory, f"{name}_{tag.hexdigest()[:12]}.so")
    if not os.path.exists(so):
        cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
               f"-I{_HERE}"]
        cmd += [f"-I{p}" for p in extra_include_paths]
        cmd += list(extra_cflags) + list(sources) + ["-o", so]
        if verbose:
            print("cpp_extension:", " ".join(cmd), file=sys.stderr)
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"cpp_extension build failed:\n{proc.stderr}")
    return so


def load(name: str, sources: Sequence[str], extra_cflags: Sequence[str] = (),
         extra_cuda_cflags: Sequence[str] = (),
         extra_include_paths: Sequence[str] = (),
         build_directory: Optional[str] = None, verbose: bool = False,
         out_shape_fns: Optional[Dict[str, Callable]] = None
         ) -> CustomOpModule:
    """Compile ``sources`` with g++, load the .so, and wrap every
    ``PT_REGISTER_OP`` op as a framework op (reference
    cpp_extension.load → _jit_compile → import).  ``extra_cuda_cflags``
    is accepted for source compatibility and ignored (no CUDA here)."""
    if isinstance(sources, dict):    # a CppExtension descriptor
        sources = sources["sources"]
    build_directory = build_directory or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    so = _build(name, sources, extra_cflags, extra_include_paths,
                build_directory, verbose)
    lib = ctypes.CDLL(so)
    lib.pt_num_ops.restype = ctypes.c_int
    lib.pt_op_name.restype = ctypes.c_char_p
    lib.pt_op_name.argtypes = [ctypes.c_int]
    lib.pt_op_compute.restype = ctypes.c_int
    ops = [lib.pt_op_name(i).decode() for i in range(lib.pt_num_ops())]
    if not ops:
        raise RuntimeError(
            f"extension {name!r} registered no ops (did the sources "
            "include pt_extension.h and use PT_REGISTER_OP?)")
    return CustomOpModule(name, lib, ops, out_shape_fns)
