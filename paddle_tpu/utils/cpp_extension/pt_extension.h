// paddle_tpu custom-op C ABI (the cpp_extension analog of the reference's
// paddle/phi/capi + python/paddle/utils/cpp_extension PD_BUILD_OP).
//
// An extension registers ops into a static table via PT_REGISTER_OP; the
// Python loader enumerates the table through three exported symbols and
// invokes kernels through a single dispatch entry.  Tensors cross the
// boundary as raw float32 buffers + shapes — the host-callback form that
// composes with XLA via jax.pure_callback (device-side custom kernels are
// written in Pallas instead; see ops/pallas/).
#pragma once
#include <cstdint>
#include <cstring>
#include <vector>

namespace pt_ext {

struct Tensor {
  const float* data;
  const int64_t* shape;
  int ndim;
  int64_t numel() const {
    int64_t n = 1;
    for (int i = 0; i < ndim; ++i) n *= shape[i];
    return n;
  }
};

using KernelFn = void (*)(int n_in, const Tensor* ins, float* out,
                          const int64_t* out_shape, int out_ndim);

struct OpEntry {
  const char* name;
  KernelFn fn;
};

inline std::vector<OpEntry>& registry() {
  static std::vector<OpEntry> r;
  return r;
}

struct Registrar {
  Registrar(const char* name, KernelFn fn) {
    registry().push_back({name, fn});
  }
};

}  // namespace pt_ext

#define PT_REGISTER_OP(opname, fn) \
  static ::pt_ext::Registrar pt_reg_##opname(#opname, fn);

// weak + default visibility: emitted (and deduplicated) wherever the
// header lands, so multi-TU extensions link cleanly
#define PT_EXPORT extern "C" __attribute__((weak, visibility("default")))
PT_EXPORT int pt_num_ops() {
  return static_cast<int>(pt_ext::registry().size());
}
PT_EXPORT const char* pt_op_name(int i) {
  return pt_ext::registry()[static_cast<size_t>(i)].name;
}
PT_EXPORT int pt_op_compute(const char* name, int n_in, const float** in_data,
                         const int64_t* in_shapes, const int* in_ndims,
                         float* out, const int64_t* out_shape,
                         int out_ndim) {
  std::vector<pt_ext::Tensor> ins;
  const int64_t* sp = in_shapes;
  for (int i = 0; i < n_in; ++i) {
    ins.push_back({in_data[i], sp, in_ndims[i]});
    sp += in_ndims[i];
  }
  for (auto& e : pt_ext::registry()) {
    if (std::strcmp(e.name, name) == 0) {
      e.fn(n_in, ins.data(), out, out_shape, out_ndim);
      return 0;
    }
  }
  return 1;
}
