"""paddle.device parity (reference python/paddle/device/__init__.py).

TPU-first mapping: device selection delegates to the framework's Place
handling (core/device.py); streams/events collapse into XLA's async
dispatch — ``synchronize`` blocks on all pending device work, a
``Stream`` is an ordering no-op (XLA already executes one program
stream per device), matching the SURVEY §2.4 collapse."""

from __future__ import annotations

from ..core.device import get_device, set_device  # noqa: F401

__all__ = ["set_device", "get_device", "get_available_device",
           "get_available_custom_device", "get_all_device_type",
           "get_all_custom_device_type", "get_cudnn_version",
           "is_compiled_with_cuda", "is_compiled_with_rocm",
           "is_compiled_with_xpu", "is_compiled_with_ipu",
           "is_compiled_with_cinn", "is_compiled_with_custom_device",
           "is_compiled_with_distribute", "XPUPlace", "IPUPlace",
           "Stream", "Event", "current_stream", "set_stream",
           "stream_guard", "synchronize"]


def get_available_device():
    import jax
    try:
        return [f"{d.platform}:{d.id}" for d in jax.devices()]
    except Exception:
        return ["cpu:0"]


def get_available_custom_device():
    return []


def get_all_device_type():
    import jax
    try:
        return sorted({d.platform for d in jax.devices()})
    except Exception:
        return ["cpu"]


def get_all_custom_device_type():
    return []


def get_cudnn_version():
    return None                    # no cuDNN in the TPU stack


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_ipu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str = "") -> bool:
    return False


def is_compiled_with_distribute() -> bool:
    return True                    # jax.distributed is always available


class XPUPlace:
    def __init__(self, _id: int = 0):
        self.id = _id


class IPUPlace:
    def __init__(self, _id: int = 0):
        self.id = _id


class Event:
    """XLA orders work per device; an Event is a recorded sync point."""

    def __init__(self, device=None, enable_timing: bool = False,
                 blocking: bool = False, interprocess: bool = False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()


class Stream:
    """XLA executes one program stream per device; Stream is an
    API-compatible ordering no-op."""

    def __init__(self, device=None, priority: int = 2):
        self.device = device

    def wait_event(self, event: Event):
        return None

    def wait_stream(self, stream: "Stream"):
        return None

    def record_event(self, event: Event = None) -> Event:
        ev = event or Event()
        ev.record(self)
        return ev

    def synchronize(self):
        synchronize()

    def query(self) -> bool:
        return True


_current = Stream()


def current_stream(device=None) -> Stream:
    return _current


def set_stream(stream: Stream) -> Stream:
    global _current
    prev, _current = _current, stream
    return prev


class stream_guard:
    def __init__(self, stream: Stream):
        self.stream = stream

    def __enter__(self):
        self._prev = set_stream(self.stream)
        return self.stream

    def __exit__(self, *exc):
        set_stream(self._prev)
        return False


def synchronize(device=None):
    """Block until all pending device work completes (the reference's
    device synchronize; here: fence via a tiny device round-trip —
    jax has no global barrier, but a device_get orders after all
    previously enqueued work on the default device)."""
    import jax
    import jax.numpy as jnp
    try:
        jax.device_get(jnp.zeros(()))
    # best-effort fence: if no backend even initializes there is nothing
    # enqueued to order after, so ANY failure means "already synced"
    except Exception:  # tracelint: disable=TL006
        pass
