"""paddle.onnx facade (reference python/paddle/onnx.py -> paddle2onnx).

ONNX export is a SURVEY §7 non-goal for the TPU build (the serving
format here is STABLEHLO via ``paddle.jit.save`` — portable across
XLA backends the way ONNX is across GPU runtimes — and, since ISSUE 6,
``paddle.jit.save(..., aot=True)`` embeds the fully compiled
executable for zero-compile fleet warm starts); ``export`` raises a
guard pointing at the native path."""

from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "onnx export is out of scope on the TPU build (SURVEY §7): use "
        "paddle.jit.save(layer, path, input_spec=...) — the STABLEHLO "
        "artifact is the portable serving format here (add aot=True to "
        "also embed the compiled executable for zero-compile warm "
        "starts), loadable by paddle.jit.load / "
        "paddle.inference.create_predictor")
