"""paddle_tpu — a TPU-native deep-learning framework.

Brand-new JAX/XLA/Pallas implementation with the capability surface of the
reference framework (PaddlePaddle, /root/reference — see SURVEY.md): an
imperative ``Tensor`` / ``nn.Layer`` / ``Optimizer`` / ``loss.backward()``
API with eager + traced dual execution, a single-source YAML op registry,
AMP, data loading, sharded checkpointing, and Fleet-style hybrid parallelism
(dp / tp / pp / sharding / sp / cp / ep) over ``jax.sharding`` meshes with
XLA collectives on ICI/DCN, plus Pallas fused kernels.
"""

from __future__ import annotations

__version__ = "0.1.0"

from .core import jax_compat as _jax_compat  # noqa: F401  (installs shims)
from .core import dtypes as _dtypes_mod
from .core.dtypes import (  # noqa: F401
    bfloat16, bool_, complex128, complex64, float16, float32, float64,
    float8_e4m3fn, float8_e5m2, int16, int32, int64, int8, uint8,
    finfo, iinfo, promote_types,
)
from .core.dtypes import bool_ as bool  # noqa: F401
from .core.device import (  # noqa: F401
    CPUPlace, Place, TPUPlace, device_count, get_device, is_compiled_with_tpu,
    max_memory_allocated, memory_allocated, memory_stats, set_device,
)
from .core.flags import FLAGS, get_flags, set_flags  # noqa: F401
from .core.rng import get_rng_state, seed, set_rng_state  # noqa: F401
from .core.tensor import Parameter, Tensor, to_tensor  # noqa: F401
from .core.autograd import grad, no_grad, enable_grad, set_grad_enabled, is_grad_enabled  # noqa: F401
from .core.dtypes import get_default_dtype, set_default_dtype  # noqa: F401

# functional op namespace (generated from ops.yaml) — both
# `paddle_tpu.add(x, y)` and `paddle_tpu.tensor.add(x, y)` work.
from .ops import api as tensor  # noqa: F401
from .ops.api import *  # noqa: F401,F403

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import checkpoint  # noqa: F401
from . import observability  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import metric  # noqa: F401
from . import vision  # noqa: F401
from .framework import io as _framework_io
from .framework.io import CheckpointCorruptError, load, save  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .core.autograd import backward as _backward  # noqa: F401

from . import autograd  # noqa: F401


def is_grad_enabled_():  # pragma: no cover - paddle compat shim
    return is_grad_enabled()


def ones_like(x, dtype=None):
    return tensor.ones_like(x, dtype)


def rank(x):
    return to_tensor(len(x.shape))


def numel(x):
    return to_tensor(x.size)


def shape(x):
    return to_tensor(x.shape)


def in_dynamic_mode() -> bool:
    from .jit.api import in_to_static_mode
    return not in_to_static_mode() and not _static_mode


def disable_static(place=None):
    global _static_mode
    if _static_mode:
        from . import static as _st
        _st._bind_recording(False)
    _static_mode = False
    return None


_static_mode = False


def enable_static():
    """Switch to static-graph building (reference paddle.enable_static).
    Ops touching ``static.data`` Variables record into the active Program;
    ``static.Executor.run`` jits the recording (see paddle_tpu/static)."""
    global _static_mode
    from . import static as _st
    _st._bind_recording(True)
    _static_mode = True


def in_static_mode():
    return _static_mode

from . import models  # noqa: F401
from . import inference  # noqa: F401
from . import static  # noqa: F401
from . import device  # noqa: F401
from . import regularizer  # noqa: F401
from . import hub  # noqa: F401
from . import sysconfig  # noqa: F401
from . import onnx  # noqa: F401
from . import version  # noqa: F401
from . import callbacks  # noqa: F401
from .core.string_tensor import StringTensor, to_string_tensor  # noqa: F401
import jax.numpy as _jnp
dtype = _jnp.dtype    # paddle.dtype: the dtype constructor/type alias
del _jnp
from .framework_misc import (  # noqa: F401
    ParamAttr, CUDAPlace, CUDAPinnedPlace, LazyGuard, DataParallel,
    is_tensor, is_complex, is_integer, is_floating_point, clone, tolist,
    floor_mod, set_printoptions, check_shape, disable_signal_handler,
    get_cuda_rng_state, set_cuda_rng_state, create_parameter, summary,
    flops, batch)
from . import framework_misc as _fm
import sys as _sys
_fm.install_inplace_api(_sys.modules[__name__])
del _fm, _sys
from .tensor_array import (  # noqa: F401
    TensorArray, create_array, array_write, array_read, array_length)
from . import utils  # noqa: F401
from . import parallel  # noqa: F401
from . import distributed  # noqa: F401
import importlib as _importlib

# ops.api star-import may have bound same-named functions (e.g. `fft`) on the
# package; import_module + explicit rebind makes the namespace modules win,
# matching the reference where paddle.fft / paddle.signal are modules.
linalg = _importlib.import_module(".linalg", __name__)
fft = _importlib.import_module(".fft", __name__)
signal = _importlib.import_module(".signal", __name__)
from . import distribution  # noqa: F401,E402
from . import profiler  # noqa: F401,E402
from . import sparse  # noqa: F401,E402
from . import geometric  # noqa: F401,E402
from . import quantization  # noqa: F401,E402
from . import text  # noqa: F401,E402
from . import audio  # noqa: F401,E402
from . import native  # noqa: F401,E402
