"""Cross-request prefix cache (ISSUE 14): a radix tree over committed
KV pages, with a bounded CRC-checked host-RAM offload tier.

The engine's within-batch prefix index (``inference/serving.py``) keeps
prompt pages alive between requests of ONE engine, but it is a flat
exact-key map with drop-on-eviction semantics: page pressure throws the
prefix away, and a fleet router has no way to ask "who holds this
prefix?".  This module promotes it to a real cache subsystem:

* **Radix/trie index** — one node per token BLOCK, keyed by the chained
  per-block digest (``block_keys``: ``key_b = H(key_{b-1} || tokens_b)``
  — the vLLM scheme, O(T) total).  Because each key commits to the whole
  chain before it, child links ARE prefix extension: walking the trie
  along a prompt's block keys yields the longest cached page-aligned
  prefix.  Node payloads are either an HBM-resident pool page (the cache
  holds one ``_RefPool`` reference, taken/released by the ENGINE — the
  cache never touches the pool itself) or an offloaded host-RAM byte
  copy.
* **Two-tier eviction** — under pool pressure the engine asks for an
  eviction victim: least-recently-used resident node first, preferring
  nodes with no resident children (leaf-first keeps chains walkable).
  With an offload budget (``PrefixCacheConfig.offload_capacity_bytes``)
  the victim's exact page bytes are parked in the bounded host tier,
  CRC32-stamped with the same convention as the preemption spill format
  (``serving/resilience.KVSnapshot``); past the budget the OLDEST host
  block is dropped entirely.  An offloaded prefix restores by exact-byte
  scatter into fresh blocks — no recompute — and a CRC failure at
  restore time is a typed :class:`~paddle_tpu.serving.resilience.
  SpillCorruptError` that the engine downgrades to a clean recompute of
  the remaining suffix, never silent corruption.
* **Placement summaries** — :meth:`PrefixCache.match_blocks` answers
  "how many leading blocks of this chain do you hold?" without touching
  LRU state; the fleet router consults it per replica to route a
  request sharing a cached prefix to the replica already holding it
  (``serving/fleet.py`` prefix affinity).

Everything here is host-side scheduler state — nothing is traced, and
the restore path reuses the engine's pre-warmed pool-shaped copy op, so
cache hits, evictions, offloads, and restores all run at ZERO backend
compiles (the ``serve_prefix_warm`` COMPILE_BUDGET.md row pins this).
See docs/serving.md ("Cross-request prefix cache") for the policy
description and the ``serve.prefix.*`` metric catalogue.
"""

from __future__ import annotations

import collections
import hashlib
import zlib
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["PrefixCache", "PrefixCacheConfig", "block_keys"]

# bump when the block-key scheme or cached-page semantics change: the
# AOT serve manifest records it (aot/serve.engine_config), so artifact
# generations and engines always agree on what a cached chain means
SCHEME = "sha1-chain/v1"


def block_keys(tokens: np.ndarray, n: int, block_size: int) -> List[bytes]:
    """Chained per-block digests over the first ``n`` blocks of
    ``tokens``: ``key_b = H(key_{b-1} || block_b bytes)`` — O(T) total
    instead of O(T^2) cumulative-bytes keys, same exact-prefix
    semantics.  The ONE hashing definition shared by the engine's
    admission walk and the router's affinity summaries (they must agree
    byte-for-byte or affinity would route on phantom prefixes)."""
    tokens = np.asarray(tokens, np.int32)
    keys: List[bytes] = []
    prev = b""
    for b in range(n):
        h = hashlib.sha1(
            prev + tokens[b * block_size:(b + 1) * block_size].tobytes())
        prev = h.digest()
        keys.append(prev)
    return keys


@dataclass(frozen=True)
class PrefixCacheConfig:
    """Policy knobs for the cross-request prefix cache.

    offload_capacity_bytes:
        Host-RAM budget for the offload tier.  0 (the default) disables
        offload entirely — eviction under pool pressure then DROPS the
        prefix (the pre-ISSUE-14 behavior), paying recompute on the
        next hit instead of host bytes.  Past the budget the oldest
        offloaded block is dropped (evict-oldest, the SpillTier
        convention).  The knob is pure policy: it never changes a
        compiled program, so it is NOT part of the AOT config hash
        (only the key ``SCHEME`` is).
    """

    offload_capacity_bytes: int = 0

    def __post_init__(self):
        if self.offload_capacity_bytes < 0:
            raise ValueError("offload_capacity_bytes must be >= 0")


@dataclass
class _Node:
    """One cached token block.  Exactly one of three payload states:
    RESIDENT (``phys`` set — the cache holds one pool reference, owned
    by the engine), OFFLOADED (``k_bytes``/``v_bytes`` set — exact page
    bytes in host RAM, CRC-stamped), or a bare placeholder (neither —
    kept only while it still has children; lookups stop at it)."""

    key: bytes
    parent: Optional["_Node"]
    depth: int
    children: Dict[bytes, "_Node"] = field(default_factory=dict)
    phys: Optional[int] = None
    k_bytes: Optional[np.ndarray] = None
    v_bytes: Optional[np.ndarray] = None
    # quantized pools (ISSUE 16): k_bytes/v_bytes hold int8 codes and
    # the per-(token, head) fp32 scales park here; the CRCs chain over
    # codes THEN scales (the KVSnapshot convention)
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None
    crc_k: int = 0
    crc_v: int = 0

    @property
    def resident(self) -> bool:
        return self.phys is not None

    @property
    def offloaded(self) -> bool:
        return self.k_bytes is not None

    @property
    def host_nbytes(self) -> int:
        if self.k_bytes is None:
            return 0
        n = self.k_bytes.nbytes + self.v_bytes.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n

    @staticmethod
    def _crc(pages: np.ndarray, scale: Optional[np.ndarray]) -> int:
        crc = zlib.crc32(pages.tobytes())
        if scale is not None:
            crc = zlib.crc32(scale.tobytes(), crc)
        return crc

    def verify(self) -> None:
        """Raise :class:`SpillCorruptError` unless the offloaded bytes
        still match their offload-time checksums (the KVSnapshot/
        framework-io convention: every spilled array carries a CRC32,
        verified on read)."""
        from .resilience import SpillCorruptError
        if self._crc(self.k_bytes, self.k_scale) != self.crc_k or \
                self._crc(self.v_bytes, self.v_scale) != self.crc_v:
            raise SpillCorruptError(
                f"offloaded prefix block {self.key.hex()[:12]} (depth "
                f"{self.depth}) failed its CRC check — host-RAM bit-rot; "
                "the suffix must be recomputed from the last good block")


class PrefixCache:
    """Radix tree over committed KV pages, keyed by token-block content.

    The ENGINE owns the refcount pool; this class only records which
    page a resident node parks and hands victims back for the engine to
    release — so the ``_RefPool`` exactly-once accounting (and its
    loud double-free errors) stay the single source of truth.

    Args:
      block_size: the engine's KV page size in tokens.
      config: :class:`PrefixCacheConfig` policy knobs.
    """

    SCHEME = SCHEME

    def __init__(self, block_size: int,
                 config: Optional[PrefixCacheConfig] = None):
        self.BS = int(block_size)
        self.config = config or PrefixCacheConfig()
        self._root = _Node(key=b"", parent=None, depth=-1)
        # LRU maps are keyed by id(node): node identity, never digest —
        # a dropped-and-reinserted chain must not collide with a
        # detached twin still awaiting cleanup
        self._lru: "collections.OrderedDict[int, _Node]" = \
            collections.OrderedDict()
        self._host_lru: "collections.OrderedDict[int, _Node]" = \
            collections.OrderedDict()
        self.host_bytes = 0
        self.stats: Dict[str, int] = {
            "lookups": 0, "hits": 0, "hit_blocks": 0, "hit_tokens": 0,
            "inserts": 0, "evictions": 0, "offloads": 0, "restores": 0,
            "restore_failures": 0, "offload_drops": 0,
        }

    # -- introspection --------------------------------------------------
    @property
    def resident_blocks(self) -> int:
        return len(self._lru)

    @property
    def offloaded_blocks(self) -> int:
        return len(self._host_lru)

    @property
    def wants_offload(self) -> bool:
        """Whether eviction should bother capturing page bytes."""
        return self.config.offload_capacity_bytes > 0

    def resident_items(self) -> List[Tuple[bytes, int]]:
        """(key, phys) of every resident node, LRU order (oldest
        first) — the engine's ``prefix_index`` compatibility view and
        the leak report read this."""
        return [(n.key, n.phys) for n in self._lru.values()]

    def keys_for(self, prompt: np.ndarray, n: int) -> List[bytes]:
        return block_keys(prompt, n, self.BS)

    # -- lookup ---------------------------------------------------------
    def walk(self, keys: List[bytes]) -> Tuple[List[int], List["_Node"]]:
        """Longest cached chain prefix for ``keys``: returns
        ``(resident_pages, offloaded_nodes)``.  Residents strictly
        precede offloaded nodes (leaf-first eviction keeps resident
        nodes a rooted prefix of every chain); the walk stops at the
        first uncached or placeholder node.  Touches LRU recency for
        every node visited."""
        pages: List[int] = []
        off: List[_Node] = []
        node = self._root
        for key in keys:
            child = node.children.get(key)
            if child is None:
                break
            if child.resident:
                if off:
                    break   # defensive: never hand out a torn chain
                pages.append(child.phys)
                self._lru.move_to_end(id(child))
            elif child.offloaded:
                off.append(child)
                self._host_lru.move_to_end(id(child))
            else:
                break       # placeholder: chain broken here
            node = child
        return pages, off

    def match_blocks(self, keys: List[bytes]) -> int:
        """Longest cached chain prefix WITHOUT touching LRU state — the
        read-only summary the fleet router's prefix-affinity placement
        consults per replica."""
        node, n = self._root, 0
        for key in keys:
            child = node.children.get(key)
            if child is None or not (child.resident or child.offloaded):
                break
            n += 1
            node = child
        return n

    # -- insert ---------------------------------------------------------
    def insert(self, keys: List[bytes], pages: List[int]) -> List[int]:
        """Register ``keys[i] -> pages[i]`` as resident nodes; returns
        the pages the cache took NEW custody of — the caller must take
        one pool reference on each (``alloc.share``) so the page
        survives the slot that computed it.  Blocks already resident
        are skipped (their existing page keeps serving; recency is
        refreshed); an offloaded twin is superseded by the freshly
        computed page (the host copy is dropped)."""
        node = self._root
        took: List[int] = []
        for key, phys in zip(keys, pages):
            child = node.children.get(key)
            if child is None:
                child = _Node(key=key, parent=node, depth=node.depth + 1)
                node.children[key] = child
            if child.resident:
                self._lru.move_to_end(id(child))
            else:
                if child.offloaded:
                    self._drop_host(child, detach=False)
                child.phys = phys
                self._lru[id(child)] = child
                took.append(phys)
                self.stats["inserts"] += 1
            node = child
        return took

    # -- eviction / offload ---------------------------------------------
    def evictable(self, refcount: Callable[[int], int]
                  ) -> Optional["_Node"]:
        """The next eviction victim: the least-recently-used resident
        node whose page the cache alone holds (``refcount(phys) == 1``),
        preferring nodes with no resident children so chains stay
        walkable; when only mid-chain nodes qualify, the oldest of
        those is returned (liveness beats chain integrity — the
        orphaned descendants remain individually evictable).  None when
        nothing can be freed."""
        fallback: Optional[_Node] = None
        for node in self._lru.values():
            if refcount(node.phys) != 1:
                continue
            if any(c.resident for c in node.children.values()):
                if fallback is None:
                    fallback = node
                continue
            return node
        return fallback

    def evict(self, node: "_Node",
              k_bytes: Optional[np.ndarray] = None,
              v_bytes: Optional[np.ndarray] = None,
              k_scale: Optional[np.ndarray] = None,
              v_scale: Optional[np.ndarray] = None) -> int:
        """Drop ``node``'s residency and return its page for the caller
        to release.  With page bytes (and an offload budget) the block
        parks in the host tier instead of vanishing — CRC-stamped, and
        bounded by dropping the OLDEST host block past the budget.
        Quantized pools pass the page's fp32 scales alongside the int8
        codes; both are stamped and restored together."""
        phys = node.phys
        node.phys = None
        del self._lru[id(node)]
        self.stats["evictions"] += 1
        if k_bytes is not None and self.wants_offload:
            node.k_bytes = k_bytes
            node.v_bytes = v_bytes
            node.k_scale = k_scale
            node.v_scale = v_scale
            node.crc_k = node._crc(k_bytes, k_scale)
            node.crc_v = node._crc(v_bytes, v_scale)
            self._host_lru[id(node)] = node
            self.host_bytes += node.host_nbytes
            self.stats["offloads"] += 1
            cap = self.config.offload_capacity_bytes
            while self.host_bytes > cap and self._host_lru:
                oldest = next(iter(self._host_lru.values()))
                self._drop_host(oldest)
                self.stats["offload_drops"] += 1
        else:
            self._detach_if_bare(node)
        return phys

    def promote(self, node: "_Node", phys: int) -> None:
        """An offloaded node's bytes were scattered into fresh page
        ``phys``: make it resident again (the caller takes the cache's
        pool reference) and drop the host copy."""
        self._drop_host(node, detach=False)
        node.phys = phys
        self._lru[id(node)] = node
        self.stats["restores"] += 1

    def drop_host(self, node: "_Node") -> None:
        """Discard an offloaded node's bytes (CRC failure at restore
        time): the block — and everything cached below it — can no
        longer be served without recompute."""
        self.stats["restore_failures"] += 1
        self._drop_host(node)

    # -- internals ------------------------------------------------------
    def _drop_host(self, node: "_Node", detach: bool = True) -> None:
        if node.offloaded:
            self.host_bytes -= node.host_nbytes
            node.k_bytes = None
            node.v_bytes = None
            node.k_scale = None
            node.v_scale = None
            node.crc_k = node.crc_v = 0
            self._host_lru.pop(id(node), None)
        if detach:
            self._detach_if_bare(node)

    def _detach_if_bare(self, node: "_Node") -> None:
        """Unlink payload-less childless nodes from the tree, walking
        up while the parent becomes bare too (placeholders must not
        accumulate)."""
        while node is not self._root and node.parent is not None \
                and not node.resident and not node.offloaded \
                and not node.children:
            parent = node.parent
            if parent.children.get(node.key) is node:
                del parent.children[node.key]
            node.parent = None
            node = parent
