"""Streaming serving front-end (ISSUE 7).

The production face of the continuous-batching engine: a per-request
lifecycle with streaming token delivery, SLO-aware admission control
and deadlines, a seeded open-loop Poisson load generator, and the
serve-path metric catalogue over the PR 5 telemetry registry.

Layering::

    serving.http.HttpServingServer    HTTP/SSE network front door
            │
    serving.PoissonLoadGenerator      offered load + SLO report
            │
    serving.ServingFrontend           lifecycle/streams/admission
            │
    inference.ContinuousBatchingEngine   batch scheduler + paged KV
            │
    aot.export_engine / aot_dir       zero-compile warm start

The wire (ISSUE 13): ``serving/http.py`` serves the front-end over
stdlib HTTP/SSE — disconnect-safe streaming, slow-client isolation,
``request_id`` idempotent retry with committed-prefix replay, graceful
SIGTERM drain, and a typed status mapping of the whole terminal-state
lattice (``python -m paddle_tpu.serving.http --model llama_tiny``).

Resilience (ISSUE 11): ``serving/resilience.py`` adds priority
preemption with CRC-checked host-RAM KV spill/restore and the
:class:`SupervisedEngine` crash wrapper (retry/backoff, AOT-warm
rebuild + deterministic replay, circuit breaker).

Prefix cache (ISSUE 14): ``serving/prefix_cache.py`` promotes the
engine's within-batch prefix sharing to a cross-request radix tree
over committed KV pages with a bounded CRC-checked host-RAM offload
tier; ``EngineRouter`` placement learns prefix affinity (route to the
replica already holding the prefix, anti-herd capped), and
``ServingFrontend.submit(n=k)`` fans one prompt out to k
refcount-shared parallel samples.

See ``docs/serving.md`` for the state machine, the streaming API, the
admission knobs, and the metric catalogue.
"""

from .fleet import EngineRouter, FleetExhaustedError, ReplicaState
from .frontend import (AdmissionConfig, RequestAborted, RequestHandle,
                       RequestRejected, RequestState, ServingFrontend)
from .http import HttpServingServer
from .loadgen import LoadGenConfig, LoadReport, PoissonLoadGenerator
from .metrics import ServeMetrics
from .prefix_cache import PrefixCache, PrefixCacheConfig
from .resilience import (EngineCrashError, KVSnapshot, PortableRequest,
                         RecoveryExhaustedError, ResilienceError,
                         RetryPolicy, SpillCorruptError, SpillTier,
                         SupervisedEngine, TransientStepError)

__all__ = [
    "AdmissionConfig", "EngineCrashError", "EngineRouter",
    "FleetExhaustedError", "HttpServingServer", "KVSnapshot",
    "LoadGenConfig", "LoadReport", "PoissonLoadGenerator",
    "PortableRequest", "PrefixCache", "PrefixCacheConfig",
    "RecoveryExhaustedError", "ReplicaState",
    "RequestAborted", "RequestHandle", "RequestRejected",
    "RequestState", "ResilienceError", "RetryPolicy", "ServeMetrics",
    "ServingFrontend", "SpillCorruptError", "SpillTier",
    "SupervisedEngine", "TransientStepError",
]
