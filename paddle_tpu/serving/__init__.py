"""Streaming serving front-end (ISSUE 7).

The production face of the continuous-batching engine: a per-request
lifecycle with streaming token delivery, SLO-aware admission control
and deadlines, a seeded open-loop Poisson load generator, and the
serve-path metric catalogue over the PR 5 telemetry registry.

Layering::

    serving.PoissonLoadGenerator      offered load + SLO report
            │
    serving.ServingFrontend           lifecycle/streams/admission
            │
    inference.ContinuousBatchingEngine   batch scheduler + paged KV
            │
    aot.export_engine / aot_dir       zero-compile warm start

See ``docs/serving.md`` for the state machine, the streaming API, the
admission knobs, and the metric catalogue.
"""

from .frontend import (AdmissionConfig, RequestAborted, RequestHandle,
                       RequestRejected, RequestState, ServingFrontend)
from .loadgen import LoadGenConfig, LoadReport, PoissonLoadGenerator
from .metrics import ServeMetrics

__all__ = [
    "AdmissionConfig", "LoadGenConfig", "LoadReport",
    "PoissonLoadGenerator", "RequestAborted", "RequestHandle",
    "RequestRejected", "RequestState", "ServeMetrics", "ServingFrontend",
]
