"""Serve-path telemetry: the metric facade the streaming front-end and
the load generator record through.

Everything goes to the PR 5 :class:`~paddle_tpu.observability.
MetricsRegistry` (the process-wide ``REGISTRY`` by default), so the
serve metrics ride the existing sinks unchanged: the JSONL stream, the
Prometheus dump, and — load-bearing for incident forensics — the
:class:`~paddle_tpu.observability.FlightRecorder` ring, which means a
crash anywhere in the process captures the last N ``serve`` lifecycle
events (submits, rejects, timeouts, cancels, finishes) in its black-box
dump with no extra wiring.

Metric catalogue (all names under ``serve.``; docs/serving.md):

===============================  =========  =============================
name                             kind       meaning
===============================  =========  =============================
serve.submitted_total            counter    requests accepted by admission
serve.rejected_total             counter    requests refused at submit
serve.timeouts_total             counter    deadline / max_queue_time kills
serve.cancelled_total            counter    client-initiated cancels
serve.finished_total             counter    requests that ran to completion
serve.tokens_streamed_total      counter    tokens delivered to handles
serve.queue_depth                gauge      engine waiting-queue length
serve.batch_occupancy            gauge      busy decode slots / max_batch
serve.kv_utilization             gauge      1 - free_blocks / num_blocks
serve.kv_free_blocks             gauge      free pool pages right now
serve.ttft_secs                  histogram  submit -> first streamed token
serve.tpot_secs                  histogram  inter-token latency (decode)
serve.e2e_secs                   histogram  submit -> finish (FINISHED only)
serve.backpressure_wait_secs     histogram  producer blocked on full stream
===============================  =========  =============================

Speculative-decode rows (``serve.spec.*``, live only when the engine
has a ``spec_config``; counters recorded by ``spec_decode/runner.py``,
gauges refreshed here per scheduler iteration; docs/spec_decode.md):

================================  =========  ============================
serve.spec.steps_total            counter    draft/verify/commit rounds
serve.spec.proposed_total         counter    draft tokens proposed
serve.spec.accepted_total         counter    proposals verify accepted
serve.spec.emitted_total          counter    tokens committed via spec
serve.spec.rollback_pages_total   counter    pages holding rolled-back KV
serve.spec.accepted_per_step      histogram  accepted per slot per round
serve.spec.acceptance_rate        gauge      cumulative accepted/proposed
serve.spec.steps_per_token        gauge      per-slot decode steps/token
                                             (baseline == 1.0; < 1.0 is
                                             the speculation win)
================================  =========  ============================

Resilience rows (``serve.resilience.*``; counters/histograms recorded
by ``inference/serving.py`` preemption hooks and
``serving/resilience.py``'s :class:`SupervisedEngine`; gauges refreshed
here per scheduler iteration; docs/serving.md):

========================================  =========  ==================
serve.resilience.preemptions_total        counter    running requests evicted (KV spilled)
serve.resilience.restores_total           counter    preempted requests resumed
serve.resilience.spilled_bytes            gauge      host-RAM KV spill tier size
serve.resilience.spilled_requests         gauge      requests currently spilled
serve.resilience.preempt_save_secs        histogram  snapshot+spill latency
serve.resilience.preempt_restore_secs     histogram  restore-into-fresh-blocks latency
serve.resilience.spill_evictions_total    counter    snapshots evicted by the bounded tier
serve.resilience.prefix_replays_total     counter    demoted requests replayed from prefix
serve.resilience.transient_retries_total  counter    retried transient step faults
serve.resilience.slow_steps_total         counter    steps past the slow-step budget
serve.resilience.crashes_total            counter    declared engine crashes
serve.resilience.recoveries_total         counter    successful rebuild+replay cycles
serve.resilience.replayed_requests_total  counter    requests replayed across crashes
serve.resilience.recovery_secs            histogram  teardown->replayed latency
serve.resilience.circuit_open_total       counter    recoveries refused (breaker open)
========================================  =========  ==================

Fleet rows (``serve.fleet.*``, live only when the front-end drives an
``EngineRouter``; counters recorded by ``serving/fleet.py``, gauges
refreshed here per scheduler iteration from ``fleet_stats()``;
docs/serving.md).  The per-replica ``serve.*`` state rolls up into the
fleet gauges — one flight-ring dump shows the whole fleet's health at
the crash:

==========================================  =========  ==============
serve.fleet.replicas                        gauge      fleet size (incl. dead)
serve.fleet.healthy / degraded /            gauge      health census by state
  draining / dead
serve.fleet.queue_depth                     gauge      summed replica queues
serve.fleet.batch_occupancy                 gauge      mean over live replicas
serve.fleet.kv_utilization                  gauge      aggregate pool pressure
serve.fleet.placements_total                counter    requests placed
serve.fleet.replacements_total              counter    cross-replica re-placements
serve.fleet.snapshot_migrations_total       counter    re-placements that moved KV bytes
serve.fleet.rebalanced_total                counter    stuck waiters migrated
serve.fleet.replica_deaths_total            counter    replicas declared dead
serve.fleet.drains_total                    counter    graceful drains started
==========================================  =========  ==============

Prefix-cache rows (``serve.prefix.*``, ISSUE 14; counters recorded by
``inference/serving.py`` admission/eviction hooks, gauges refreshed
here per scheduler iteration from ``prefix_stats()``;
docs/serving.md).  Lookups count admission-time cache consultations
(hit or miss); hit_tokens are prompt tokens whose prefill the cache
skipped — the direct prefill-FLOP savings meter:

==========================================  =========  ==============
serve.prefix.lookups_total                  counter    admissions that consulted the cache
serve.prefix.hits_total                     counter    admissions claiming >= 1 cached block
serve.prefix.hit_tokens_total               counter    prompt tokens NOT re-prefilled
serve.prefix.inserts_total                  counter    blocks registered into the radix tree
serve.prefix.evictions_total                counter    resident blocks evicted under pressure
serve.prefix.offloads_total                 counter    evicted blocks parked in host RAM
serve.prefix.restores_total                 counter    offloaded blocks restored by byte scatter
serve.prefix.restore_failures_total         counter    CRC failures at restore (recompute fallback)
serve.prefix.cached_blocks                  gauge      HBM-resident cached blocks
serve.prefix.offloaded_blocks               gauge      host-RAM tier blocks
serve.prefix.offloaded_bytes                gauge      host-RAM tier size
serve.prefix.hit_rate                       gauge      cumulative hits / lookups
serve.fleet.affinity_hits_total             counter    placements won by prefix affinity
serve.fleet.affinity_capped_total           counter    affinity overridden by the anti-herd cap
==========================================  =========  ==============

HTTP wire rows (``serve.http.*``, live only when requests arrive over
the network front door — ``serving/http.py``; docs/serving.md).  The
wire is where real traffic's failures originate, so every failure mode
the server absorbs is a counter:

==========================================  =========  ==============
serve.http.connections_total                counter    accepted HTTP connections
serve.http.active_connections               gauge      connections being served now
serve.http.requests_total                   counter    /v1/generate bodies parsed
serve.http.disconnect_cancels_total         counter    mid-stream client disconnects
                                                       that cancelled the request
serve.http.dedup_hits_total                 counter    retries attached to a live or
                                                       finished stream (no double submit)
serve.http.write_stall_timeouts_total       counter    SSE writes past the per-connection
                                                       deadline (stalled reader isolated)
serve.http.abandoned_total                  counter    graced disconnects never retried
serve.http.shutdown_drain_secs              histogram  SIGTERM -> drained latency
==========================================  =========  ==============

Every recording entry point checks ``registry.enabled`` first, so a
front-end without telemetry pays one branch per call (the PR 5
zero-cost-disabled contract).  All of this is host-side scheduler code,
never traced — the tracelint ratchet pins this package at zero TL001
findings.
"""

from __future__ import annotations

from typing import Optional

from ..observability import REGISTRY, MetricsRegistry

__all__ = ["ServeMetrics"]


class ServeMetrics:
    """Thin, enabled-guarded facade over the metrics registry."""

    def __init__(self, registry: Optional[MetricsRegistry] = None):
        self._reg = REGISTRY if registry is None else registry

    @property
    def registry(self) -> MetricsRegistry:
        return self._reg

    @property
    def enabled(self) -> bool:
        return self._reg.enabled

    # -- lifecycle events ----------------------------------------------
    def event(self, action: str, **fields) -> None:
        if self._reg.enabled:
            self._reg.event("serve", action=action, **fields)

    def on_submit(self, req_id: int, prompt_len: int,
                  max_new_tokens: int) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.submitted_total").inc()
        self._reg.event("serve", action="submit", req_id=req_id,
                        prompt_len=prompt_len,
                        max_new_tokens=max_new_tokens)

    def on_reject(self, reason: str) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.rejected_total").inc()
        self._reg.event("serve", action="reject", reason=reason[:200])

    def on_timeout(self, req_id: int, phase: str) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.timeouts_total").inc()
        self._reg.event("serve", action="timeout", req_id=req_id,
                        phase=phase)

    def on_cancel(self, req_id: int) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.cancelled_total").inc()
        self._reg.event("serve", action="cancel", req_id=req_id)

    def on_finish(self, req_id: int, e2e_s: float, n_tokens: int) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.finished_total").inc()
        self._reg.histogram("serve.e2e_secs", unit="s").record(e2e_s)
        self._reg.event("serve", action="finish", req_id=req_id,
                        e2e_s=round(e2e_s, 6), n_tokens=n_tokens)

    # -- token stream ---------------------------------------------------
    def on_first_token(self, req_id: int, ttft_s: float) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.tokens_streamed_total").inc()
        self._reg.histogram("serve.ttft_secs", unit="s").record(ttft_s)
        self._reg.event("serve", action="first_token", req_id=req_id,
                        ttft_s=round(ttft_s, 6))

    def on_tokens(self, n: int, tpot_s: float) -> None:
        """``n`` decode tokens whose mean inter-arrival was ``tpot_s``."""
        if not self._reg.enabled:
            return
        self._reg.counter("serve.tokens_streamed_total").inc(n)
        h = self._reg.histogram("serve.tpot_secs", unit="s")
        for _ in range(n):
            h.record(tpot_s)

    def on_backpressure(self, waited_s: float) -> None:
        if not self._reg.enabled:
            return
        self._reg.histogram("serve.backpressure_wait_secs",
                            unit="s").record(waited_s)

    # -- HTTP wire (serving/http.py) -------------------------------------
    def on_connection(self, active: int, *, opened: bool) -> None:
        """A connection opened or closed; ``active`` is the server's
        live-connection count AFTER the change (the gauge value)."""
        if not self._reg.enabled:
            return
        if opened:
            self._reg.counter("serve.http.connections_total").inc()
        self._reg.gauge("serve.http.active_connections").set(active)

    def on_http_request(self) -> None:
        if self._reg.enabled:
            self._reg.counter("serve.http.requests_total").inc()

    def on_disconnect_cancel(self, req_id, n_streamed: int) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.http.disconnect_cancels_total").inc()
        self._reg.event("serve", action="http_disconnect_cancel",
                        req_id=req_id, n_streamed=n_streamed)

    def on_dedup_hit(self, request_id: str, live: bool) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.http.dedup_hits_total").inc()
        self._reg.event("serve", action="http_dedup_hit",
                        request_id=str(request_id)[:100], live=live)

    def on_write_stall(self, req_id, waited_s: float) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.http.write_stall_timeouts_total").inc()
        self._reg.event("serve", action="http_write_stall",
                        req_id=req_id, waited_s=round(waited_s, 4))

    def on_abandoned(self, request_id: str) -> None:
        if not self._reg.enabled:
            return
        self._reg.counter("serve.http.abandoned_total").inc()
        self._reg.event("serve", action="http_abandoned",
                        request_id=str(request_id)[:100])

    def on_shutdown_drain(self, secs: float, drained: int,
                          cancelled: int) -> None:
        if not self._reg.enabled:
            return
        self._reg.histogram("serve.http.shutdown_drain_secs",
                            unit="s").record(secs)
        self._reg.event("serve", action="http_shutdown_drain",
                        secs=round(secs, 4), drained=drained,
                        cancelled=cancelled)

    # -- gauges ---------------------------------------------------------
    def publish_engine(self, engine) -> None:
        """Refresh the point-in-time gauges from engine state (called
        once per scheduler iteration, not per token)."""
        if not self._reg.enabled:
            return
        self._reg.gauge("serve.queue_depth").set(engine.queue_depth)
        self._reg.gauge("serve.batch_occupancy").set(
            engine.batch_occupancy())
        self._reg.gauge("serve.kv_utilization").set(
            engine.kv_utilization())
        self._reg.gauge("serve.kv_free_blocks").set(
            engine.alloc.free_blocks)
        spec = engine.spec_stats() if hasattr(engine, "spec_stats") \
            else None
        if spec is not None:
            if spec["acceptance_rate"] is not None:
                self._reg.gauge("serve.spec.acceptance_rate").set(
                    spec["acceptance_rate"])
            if spec["engine_steps_per_token"] is not None:
                self._reg.gauge("serve.spec.steps_per_token").set(
                    spec["engine_steps_per_token"])
        res = engine.resilience_stats() \
            if hasattr(engine, "resilience_stats") else None
        if res is not None:
            self._reg.gauge("serve.resilience.spilled_bytes").set(
                res["spilled_bytes"])
            self._reg.gauge("serve.resilience.spilled_requests").set(
                res["spilled_requests"])
        prefix = engine.prefix_stats() \
            if hasattr(engine, "prefix_stats") else None
        if prefix is not None:
            # .get defaults: an all-dead fleet's rollup has no replica
            # rows to sum, and gauges must still publish zeros
            g = self._reg.gauge
            g("serve.prefix.cached_blocks").set(
                prefix.get("cached_blocks", 0))
            g("serve.prefix.offloaded_blocks").set(
                prefix.get("offloaded_blocks", 0))
            g("serve.prefix.offloaded_bytes").set(
                prefix.get("offloaded_bytes", 0))
            if prefix.get("hit_rate") is not None:
                g("serve.prefix.hit_rate").set(prefix["hit_rate"])
        fleet = engine.fleet_stats() \
            if hasattr(engine, "fleet_stats") else None
        if fleet is not None:
            g = self._reg.gauge
            g("serve.fleet.replicas").set(fleet["replicas"])
            for state in ("healthy", "degraded", "draining", "dead"):
                g(f"serve.fleet.{state}").set(fleet[state])
            g("serve.fleet.queue_depth").set(fleet["queue_depth"])
            g("serve.fleet.batch_occupancy").set(
                fleet["batch_occupancy"])
            g("serve.fleet.kv_utilization").set(
                fleet["kv_utilization"])
