"""Serving resilience (ISSUE 11): KV spill/restore for priority
preemption, and a supervising engine wrapper that survives step faults.

The serve stack before this module had exactly one failure mode: an
engine exception aborted every live stream (the front-end's typed
abort-all path).  This module adds the two layers between "fine" and
"abort everything":

* **KV snapshot / restore** — the page-level save→verify→publish
  discipline of ``checkpoint/`` applied to the serving KV pool, but
  into a host-RAM spill tier instead of disk.  ``snapshot_slot`` reads
  a running slot's committed KV pages off the device and CRC32-stamps
  them (the ``framework/io.py`` manifest convention); ``restore_into_
  slot`` verifies the checksums and scatters the exact bytes into
  fresh blocks.  Because the engine's decode reads KV only through the
  block table and the sampler is keyed by (seed, absolute position), a
  preempt/restore cycle is **bit-identical** to an unpreempted run —
  pinned by tests/test_serving_resilience.py.

* :class:`SupervisedEngine` — a drop-in engine wrapper (the
  ``ServingFrontend`` drives it unchanged) with three escalation
  levels:

  1. **transient faults** (:class:`TransientStepError`) retry the step
     with bounded exponential backoff;
  2. a **declared crash** (any other ``Exception``, retries exhausted,
     or a run of slow steps past ``RetryPolicy.slow_step_s``) tears
     the engine down, rebuilds it through the caller's factory — AOT-
     warm factories (``aot.serve.warm_engine_factory``) rebuild with
     ZERO backend compiles, ratcheted by the ``serve_recovery_warm``
     budget row — and **replays every live request from its committed
     token prefix**: the replayed request's prompt is
     ``original prompt + tokens already streamed``, so the resumed
     stream continues gap-free and (greedy / seeded-sampled)
     bit-identically, invisible to the consumer;
  3. a **circuit breaker** (``max_restarts`` within
     ``restart_window_s``) raises :class:`RecoveryExhaustedError`,
     which lands in the front-end's existing crash path: flight-ring
     dump + typed abort of every live stream.

  Every recovery dumps the flight-recorder ring (the serve event ring
  is the post-mortem timeline) and records the ``serve.resilience.*``
  metric family.

``BaseException`` faults (``KeyboardInterrupt``, the checkpoint
harness's ``SimulatedCrash``) are never swallowed — supervision is for
engine faults, not for the process being killed.
"""

from __future__ import annotations

import collections
import time
import zlib
from dataclasses import dataclass
from typing import Callable, Dict, Optional

import numpy as np

from ..observability import REGISTRY
from ..observability.tracing import TRACER

__all__ = [
    "EngineCrashError", "KVSnapshot", "PortableRequest",
    "RecoveryExhaustedError", "ResilienceError", "RetryPolicy",
    "SpillCorruptError", "SpillTier", "SupervisedEngine",
    "TransientStepError", "restore_into_slot", "snapshot_slot",
]


class ResilienceError(RuntimeError):
    """Base for typed resilience failures."""


class SpillCorruptError(ResilienceError):
    """A spilled KV snapshot failed its CRC check at restore time.  The
    snapshot (and, on a bare engine, the request) is dropped — a
    supervising wrapper replays the request from its committed token
    prefix instead, so nothing is lost above the supervisor."""


class TransientStepError(RuntimeError):
    """A step fault the supervisor should RETRY (bounded backoff)
    rather than treat as an engine crash — the fault-injection marker
    for retryable conditions (tests/faults.py raises it)."""


class EngineCrashError(RuntimeError):
    """A declared engine crash: the supervisor tears down, rebuilds,
    and replays.  Any non-transient ``Exception`` escaping
    ``engine.step()`` is treated the same way; this type exists so
    policies (slow-step escalation) and injectors can declare one
    explicitly."""


class RecoveryExhaustedError(ResilienceError):
    """The restart circuit breaker opened: more than
    ``RetryPolicy.max_restarts`` rebuilds inside
    ``restart_window_s``.  Escalates to the front-end's typed
    abort-all path (every live stream gets a terminal state)."""


# ---------------------------------------------------------------------
# KV spill tier: page snapshots with the checkpoint CRC convention
# ---------------------------------------------------------------------
@dataclass
class KVSnapshot:
    """One preempted request's committed serving state, held in host
    RAM: the exact bytes of its committed KV pages plus the decode
    cursor (committed length + pending fed token).  The sampler needs
    no extra state — it is keyed by (seed, absolute position), both of
    which the request/cursor already carry."""

    req_id: int
    length: int                # committed KV positions
    next_token: int            # pending fed token (decode cursor)
    num_blocks: int            # full table width to re-acquire
    k_pages: np.ndarray        # [L, used_pages, BS, Hkv, D]
    v_pages: np.ndarray
    # quantized pools (ISSUE 16): k_pages/v_pages hold the int8 codes
    # and the per-(token, head) fp32 scales ride here — the CRCs chain
    # over codes THEN scales, so bit-rot in either is caught
    k_scale: Optional[np.ndarray] = None   # [L, used_pages, BS, Hkv]
    v_scale: Optional[np.ndarray] = None
    crc_k: int = 0
    crc_v: int = 0

    def __post_init__(self):
        if not self.crc_k and not self.crc_v:
            self.crc_k = self._crc(self.k_pages, self.k_scale)
            self.crc_v = self._crc(self.v_pages, self.v_scale)

    @staticmethod
    def _crc(pages: np.ndarray, scale: Optional[np.ndarray]) -> int:
        crc = zlib.crc32(pages.tobytes())
        if scale is not None:
            crc = zlib.crc32(scale.tobytes(), crc)
        return crc

    @property
    def nbytes(self) -> int:
        n = self.k_pages.nbytes + self.v_pages.nbytes
        if self.k_scale is not None:
            n += self.k_scale.nbytes + self.v_scale.nbytes
        return n

    def verify(self) -> None:
        """Raise :class:`SpillCorruptError` unless the page bytes still
        match their spill-time checksums (framework/io.py convention:
        every array member carries a CRC32, verified on read)."""
        if self._crc(self.k_pages, self.k_scale) != self.crc_k or \
                self._crc(self.v_pages, self.v_scale) != self.crc_v:
            raise SpillCorruptError(
                f"spilled KV snapshot for request {self.req_id} failed "
                "its CRC check — host-RAM bit-rot or a write raced the "
                "spill; the request must be replayed from its committed "
                "token prefix")


def snapshot_slot(engine, slot: int) -> KVSnapshot:
    """Read the committed KV pages of a RUNNING slot off the device and
    CRC-stamp them.  Only pages holding committed positions
    (``ceil(length / block_size)``) are copied — pages reserved for the
    not-yet-generated tail carry no state worth saving (any stale bytes
    there are masked by ``lengths`` exactly as on a fresh slot).

    The gather runs HOST-side (one pool transfer + numpy indexing)
    rather than as a traced ``pool[:, idx]``: a device gather is an
    op-by-op backend compile per distinct page count, which would break
    the fleet's zero-compile contract (``fleet_warm`` budget row) the
    first time a drain spilled an unseen length.  Spill/restore are
    rare, host-bound control-plane events; the extra copy is the cheap
    side of that trade."""
    from ..ops.paged_kv import is_quantized_pool
    req = engine.slots[slot]
    length = int(engine.lengths[slot])
    used = -(-length // engine.BS)
    pages = engine.slot_pages[slot]
    idx = np.asarray(pages[:used], np.int64)
    ks = vs = None
    if is_quantized_pool(engine.pool_k):
        k = np.asarray(engine.pool_k.data)[:, idx].copy()
        v = np.asarray(engine.pool_v.data)[:, idx].copy()
        ks = np.asarray(engine.pool_k.scale)[:, idx].copy()
        vs = np.asarray(engine.pool_v.scale)[:, idx].copy()
    else:
        k = np.asarray(engine.pool_k)[:, idx].copy()
        v = np.asarray(engine.pool_v)[:, idx].copy()
    return KVSnapshot(req_id=req.req_id, length=length,
                      next_token=int(engine.tokens[slot]),
                      num_blocks=len(pages), k_pages=k, v_pages=v,
                      k_scale=ks, v_scale=vs)


def restore_into_slot(engine, slot: int, snap: KVSnapshot) -> None:
    """Verify and scatter a snapshot's page bytes into the slot's
    freshly acquired blocks (``engine.slot_pages[slot]``).  The
    device→host→device round trip preserves bytes exactly, so decode
    resumed from the restored pages is bit-identical to one that was
    never preempted.  Host-side scatter for the same zero-compile
    reason as :func:`snapshot_slot`."""
    import jax.numpy as jnp

    from ..ops.paged_kv import QuantizedKVPool, is_quantized_pool
    snap.verify()
    quant = is_quantized_pool(engine.pool_k)
    if (snap.k_scale is not None) != quant:
        raise SpillCorruptError(
            f"KV snapshot for request {snap.req_id} "
            f"{'carries' if snap.k_scale is not None else 'lacks'} "
            "quantization scales but the engine's pool "
            f"{'is' if quant else 'is not'} quantized — the snapshot "
            "cannot scatter; replay from the committed token prefix")
    used = snap.k_pages.shape[1]
    pages = np.asarray(engine.slot_pages[slot][:used], np.int64)
    # jnp.array (owned copy), NOT jax.device_put/jnp.asarray: both can
    # zero-copy ALIAS the numpy buffer on CPU, and the decode step
    # DONATES the pools — XLA reusing memory numpy still owns is a
    # use-after-free.  The copy runs through a pool-shaped
    # convert_element_type executable that the engine pre-warms at
    # construction, so restores under traffic stay at zero backend
    # compiles (fleet_warm budget row).
    if quant:
        pk = np.asarray(engine.pool_k.data).copy()
        pv = np.asarray(engine.pool_v.data).copy()
        pks = np.asarray(engine.pool_k.scale).copy()
        pvs = np.asarray(engine.pool_v.scale).copy()
        pk[:, pages] = snap.k_pages
        pv[:, pages] = snap.v_pages
        pks[:, pages] = snap.k_scale
        pvs[:, pages] = snap.v_scale
        engine.pool_k = QuantizedKVPool(jnp.array(pk), jnp.array(pks))
        engine.pool_v = QuantizedKVPool(jnp.array(pv), jnp.array(pvs))
        return
    pk = np.asarray(engine.pool_k).copy()
    pv = np.asarray(engine.pool_v).copy()
    pk[:, pages] = snap.k_pages
    pv[:, pages] = snap.v_pages
    engine.pool_k = jnp.array(pk)
    engine.pool_v = jnp.array(pv)


# ---------------------------------------------------------------------
# bounded host-RAM spill tier (ISSUE 12 satellite)
# ---------------------------------------------------------------------
class SpillTier:
    """Bounded host-RAM store for spilled :class:`KVSnapshot` objects,
    shared by priority preemption and graceful drain.

    Host RAM is a real resource: a saturated fleet preempting
    long-context requests could otherwise grow the spill tier without
    limit until the OS kills the serving process — a worse failure than
    the one preemption avoids.  ``capacity_bytes`` caps the tier;
    inserting past the cap EVICTS snapshots (``policy="evict-oldest"``
    — the snapshot spilled longest ago is the one whose request has
    waited longest and is cheapest to recompute relative to its wait).
    An evicted request is NOT lost: it is demoted to
    **replay-from-prefix** — the engine's admission path detects a
    queued request with committed tokens but no snapshot and recomputes
    its KV from the committed token prefix (bit-identical, just paid in
    prefill FLOPs instead of host bytes).  Every eviction is a typed
    ``spill_evict`` event plus the
    ``serve.resilience.spill_evictions_total`` counter.

    The dict-like surface (``tier[rid]``, ``rid in tier``, ``pop``,
    ``del``) keeps the engine's bookkeeping unchanged; only the
    capacity-checked :meth:`put` differs from a plain dict.
    """

    def __init__(self, capacity_bytes: Optional[int] = None,
                 policy: str = "evict-oldest"):
        if policy != "evict-oldest":
            raise ValueError(f"unknown spill policy {policy!r} "
                             "(have: evict-oldest)")
        if capacity_bytes is not None and capacity_bytes < 0:
            raise ValueError("capacity_bytes must be >= 0 or None")
        self.capacity_bytes = capacity_bytes
        self.policy = policy
        self._snaps: "collections.OrderedDict[int, KVSnapshot]" = \
            collections.OrderedDict()
        self.evictions = 0

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self._snaps.values())

    def put(self, req_id: int, snap: KVSnapshot) -> list:
        """Insert a snapshot; returns the req_ids EVICTED to make room
        (possibly including ``req_id`` itself when one snapshot alone
        exceeds the cap).  The caller demotes evicted requests to
        replay-from-prefix and records the typed event."""
        self._snaps[req_id] = snap
        evicted = []
        if self.capacity_bytes is not None:
            while self._snaps and self.nbytes > self.capacity_bytes:
                rid, _ = self._snaps.popitem(last=False)
                evicted.append(rid)
                self.evictions += 1
        return evicted

    def get(self, req_id: int, default=None):
        return self._snaps.get(req_id, default)

    def pop(self, req_id: int, *default):
        return self._snaps.pop(req_id, *default)

    def values(self):
        return self._snaps.values()

    def keys(self):
        return self._snaps.keys()

    def __getitem__(self, req_id: int) -> KVSnapshot:
        return self._snaps[req_id]

    def __delitem__(self, req_id: int) -> None:
        del self._snaps[req_id]

    def __contains__(self, req_id: int) -> bool:
        return req_id in self._snaps

    def __len__(self) -> int:
        return len(self._snaps)


# ---------------------------------------------------------------------
# supervised engine: retry / rebuild / replay
# ---------------------------------------------------------------------
@dataclass(frozen=True)
class RetryPolicy:
    """Escalation knobs for :class:`SupervisedEngine`.

    max_retries:
        Transient-fault retries per step before escalating to a
        declared crash.
    backoff_base_s / backoff_factor / backoff_max_s:
        Bounded exponential backoff between transient retries
        (``base * factor**(attempt-1)``, capped).
    slow_step_s:
        A step slower than this counts as a slow step (None disables
        the detector — wall-clock on a shared CI host is noisy).
    slow_steps_to_crash:
        Consecutive slow steps that escalate to a declared crash (a
        hung-but-not-dead engine must not stall streams forever).
    max_restarts / restart_window_s:
        Circuit breaker: more than ``max_restarts`` rebuilds within the
        window raises :class:`RecoveryExhaustedError` instead of
        rebuilding again.
    """

    max_retries: int = 3
    backoff_base_s: float = 0.02
    backoff_factor: float = 2.0
    backoff_max_s: float = 1.0
    slow_step_s: Optional[float] = None
    slow_steps_to_crash: int = 3
    max_restarts: int = 3
    restart_window_s: float = 60.0


@dataclass
class _Tracked:
    """Supervisor bookkeeping for one live request.  ``req`` is the
    OUTER GenRequest — the object the caller (and the front-end's
    stream delivery) holds.  Before any crash the inner engine runs
    that very object; after a rebuild ``inner`` is the replayed
    request inside the fresh engine and newly committed tokens are
    bridged into ``req`` so consumers never notice the splice."""

    req: object
    kwargs: Dict[str, object]
    max_new: int
    priority: int
    inner: object = None
    base: int = 0               # outer tokens committed before replay


@dataclass
class PortableRequest:
    """A live request lifted OUT of one supervised engine so another
    replica can carry it (the fleet router's re-placement currency —
    ``serving/fleet.py``).  ``out`` is the committed token prefix the
    consumer has already (or could have) seen; ``snapshot`` is the
    CRC-checked KV page bytes when the source replica was healthy
    enough to spill them (page bytes are replica-agnostic: any engine
    with the same geometry can scatter them into fresh blocks), or
    None — in which case the target replays from the committed token
    prefix instead (bit-identical either way, the snapshot just saves
    the prefill recompute)."""

    prompt: np.ndarray
    out: list
    kwargs: Dict[str, object]
    max_new: int
    priority: int
    snapshot: Optional[KVSnapshot] = None

    @property
    def done(self) -> bool:
        return len(self.out) >= self.max_new


class _DeadEngine:
    """Sentinel installed when a recovery rebuild itself fails: every
    engine-surface access raises the typed circuit-breaker error
    instead of ``AttributeError`` on ``None``, so callers that keep
    driving the wrapper after the escalation still land in the
    front-end's typed abort-all path."""

    def __init__(self, cause: BaseException):
        object.__setattr__(self, "_cause", cause)

    def __getattr__(self, name):
        cause = object.__getattribute__(self, "_cause")
        err = RecoveryExhaustedError(
            "engine rebuild failed during crash recovery — the "
            f"supervisor has no live engine; rebuild error: "
            f"{type(cause).__name__}: {cause}")
        err.__cause__ = cause
        raise err


class SupervisedEngine:
    """Crash-supervised wrapper around a ``ContinuousBatchingEngine``.

    Args:
      factory: zero-arg callable building a fresh engine.  Use an AOT-
        warm factory (``aot.serve.warm_engine_factory``) so rebuilds
        deserialize every compiled program instead of tracing — the
        ``serve_recovery_warm`` compile-budget row pins recovery at
        ZERO backend compiles.
      policy: :class:`RetryPolicy` escalation knobs.
      registry: metrics registry (defaults to the process registry).
      clock / sleep: injectable time sources (tests drive backoff and
        the circuit-breaker window without real waiting).

    The wrapper duck-types the engine surface the serving front-end
    uses (``add_request`` / ``cancel`` / ``step`` / ``queue`` /
    introspection helpers), so ``ServingFrontend(SupervisedEngine(...))``
    serves streams that survive engine crashes.
    """

    def __init__(self, factory: Callable[[], object], *,
                 policy: Optional[RetryPolicy] = None, registry=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self._factory = factory
        self.policy = policy or RetryPolicy()
        self._reg = REGISTRY if registry is None else registry
        self._clock = clock
        self._sleep = sleep
        self.engine = factory()
        # The supervisor owns the caller-visible id space: a rebuilt
        # engine restarts ITS counter at 0, so reusing inner rids would
        # collide with still-live outer ids after any pre-crash request
        # finished.  Outer ids are monotone and never reused; each
        # inner GenRequest is re-keyed to its outer id on creation, so
        # the inner engine's finished/spill/cancel bookkeeping (all
        # keyed off ``req.req_id``) speaks outer ids too.
        self._next_outer_id = 0
        self._tracked: "collections.OrderedDict[int, _Tracked]" = \
            collections.OrderedDict()
        self._pending_finished: Dict[int, np.ndarray] = {}
        self._restart_times: "collections.deque[float]" = \
            collections.deque()
        self._consecutive_slow = 0
        self.last_error: Optional[BaseException] = None
        self.stats: Dict[str, int] = {
            "transient_retries": 0, "slow_steps": 0, "crashes": 0,
            "recoveries": 0, "replayed_requests": 0, "circuit_opens": 0,
            "rebuild_failures": 0,
        }

    # -- engine surface -------------------------------------------------
    def add_request(self, prompt_ids, max_new_tokens: int,
                    eos_token_id: Optional[int] = None, *,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    seed: int = 0, priority: int = 0) -> int:
        inner_rid = self.engine.add_request(
            prompt_ids, max_new_tokens, eos_token_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed, priority=priority)
        req = next(r for r in reversed(self.engine.queue)
                   if r.req_id == inner_rid)
        rid = self._next_outer_id
        self._next_outer_id += 1
        req.req_id = rid        # re-key to the supervisor's id space
        self._tracked[rid] = _Tracked(
            req=req,
            kwargs={"eos_token_id": eos_token_id,
                    "temperature": temperature, "top_k": top_k,
                    "top_p": top_p, "seed": seed},
            max_new=int(max_new_tokens), priority=int(priority),
            inner=req)
        return rid

    def cancel(self, req_id: int) -> bool:
        if self._pending_finished.pop(req_id, None) is not None:
            # terminal result synthesized during a recovery but not yet
            # delivered: cancelling drops the delivery — and must NOT
            # fall through to the engine, whose id space never held
            # this request after the rebuild
            return True
        t = self._tracked.pop(req_id, None)
        if t is None:
            # unknown or already finished.  Never forward an untracked
            # outer id into the engine: after a rebuild the inner
            # counter restarted, so a stale outer id could name (and
            # cancel) an unrelated request
            return False
        self.engine.cancel(req_id)
        return True

    def step(self) -> Dict[int, np.ndarray]:
        """One supervised scheduler iteration: retry transients with
        backoff, recover declared crashes via rebuild + replay, then
        hand back newly finished requests keyed by their ORIGINAL ids."""
        p = self.policy
        attempt = 0
        while True:
            t0 = self._clock()
            try:
                finished = self.engine.step()
            except (KeyboardInterrupt, SystemExit):
                raise
            except RecoveryExhaustedError:
                raise       # breaker already open (dead-engine access)
            except TransientStepError as e:
                attempt += 1
                self.stats["transient_retries"] += 1
                if self._reg.enabled:
                    self._reg.counter(
                        "serve.resilience.transient_retries_total").inc()
                self._event("retry", attempt=attempt,
                            error=f"{type(e).__name__}: {e}"[:200])
                if attempt > p.max_retries:
                    self._recover(e)
                    return self._absorb({})
                self._sleep(min(
                    p.backoff_base_s * p.backoff_factor ** (attempt - 1),
                    p.backoff_max_s))
                continue
            except Exception as e:
                self._recover(e)
                return self._absorb({})
            dt = self._clock() - t0
            if p.slow_step_s is not None and dt > p.slow_step_s:
                self._consecutive_slow += 1
                self.stats["slow_steps"] += 1
                if self._reg.enabled:
                    self._reg.counter(
                        "serve.resilience.slow_steps_total").inc()
                self._event("slow_step", secs=round(dt, 4),
                            consecutive=self._consecutive_slow)
                if self._consecutive_slow >= p.slow_steps_to_crash:
                    n = self._consecutive_slow
                    self._consecutive_slow = 0
                    self._recover(EngineCrashError(
                        f"{n} consecutive steps slower than "
                        f"{p.slow_step_s}s — declaring the engine hung"))
                    return self._absorb({})
            else:
                self._consecutive_slow = 0
            return self._absorb(finished)

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        """Drive supervised steps until every tracked request resolves."""
        results: Dict[int, np.ndarray] = {}
        while self._tracked or self._pending_finished:
            results.update(self.step())
        return results

    # -- introspection (front-end / loadgen / bench surface) ------------
    @property
    def queue(self):
        return self.engine.queue

    @property
    def slots(self):
        return self.engine.slots

    @property
    def alloc(self):
        return self.engine.alloc

    @property
    def cfg(self):
        return self.engine.cfg

    @property
    def queue_depth(self) -> int:
        return self.engine.queue_depth

    @property
    def active_requests(self) -> int:
        return self.engine.active_requests

    @property
    def live_requests(self) -> int:
        return len(self._tracked)

    def _blocks_needed(self, n_tokens: int) -> int:
        return self.engine._blocks_needed(n_tokens)

    def batch_occupancy(self) -> float:
        return self.engine.batch_occupancy()

    def kv_utilization(self) -> float:
        return self.engine.kv_utilization()

    def kv_leak_report(self) -> Dict[str, int]:
        return self.engine.kv_leak_report()

    def spec_stats(self):
        return self.engine.spec_stats()

    def aot_stats(self):
        return self.engine.aot_stats()

    def resilience_stats(self) -> Dict[str, object]:
        """Engine preemption counters merged with the supervisor's
        crash-recovery counters — one dict for bench rows / gauges."""
        s: Dict[str, object] = dict(self.engine.resilience_stats())
        s.update(self.stats)
        s["restarts_in_window"] = len(self._restart_times)
        return s

    def __getattr__(self, name):
        # anything not supervised is plain engine surface
        if name == "engine":     # not set yet: don't recurse
            raise AttributeError(name)
        return getattr(self.engine, name)

    # -- cross-replica re-placement surface (serving/fleet.py) ----------
    def extract_request(self, req_id: int) -> Optional[PortableRequest]:
        """Lift a live request out of this engine for re-placement on
        another replica (the fleet router's drain/rebalance path).

        A RUNNING request is preempted first — its committed KV pages
        spill through the ordinary CRC-checked snapshot path — so the
        returned :class:`PortableRequest` carries the page bytes and
        the target replica can restore them instead of recomputing.
        The request stops existing here (no terminal state is
        delivered); the caller owns its continuation.  Returns None
        for unknown / already-finished ids (a pending synthesized
        result is NOT extractable — collect it from ``step()``)."""
        t = self._tracked.pop(req_id, None)
        if t is None:
            return None
        self._bridge(t)                 # fold any unabsorbed tokens in
        eng = self.engine
        for slot in range(eng.B):
            r = eng.slots[slot]
            if r is not None and r.req_id == req_id:
                eng.preempt(slot)       # snapshot committed KV first
                break
        snap = eng._spill.pop(req_id, None)
        eng.cancel(req_id)              # queued by now; frees nothing
        return PortableRequest(
            prompt=t.req.prompt, out=list(t.req.out),
            kwargs=dict(t.kwargs), max_new=t.max_new,
            priority=t.priority, snapshot=snap)

    def adopt_request(self, portable: PortableRequest) -> int:
        """Admit a request extracted from ANOTHER replica, resuming it
        under a fresh id in this supervisor's id space.

        With a KV snapshot (same pool geometry — all replicas of one
        fleet are built from one factory), the page bytes are seeded
        into this engine's spill tier and admission restores them into
        fresh blocks exactly as if the preemption had happened here: no
        recompute, bit-identical.  Without one, the request is replayed
        from its committed token prefix (the crash-recovery machinery's
        path — also bit-identical)."""
        kw = portable.kwargs
        snap = portable.snapshot
        if snap is not None and self.engine.spill_compatible(snap):
            from ..inference.serving import GenRequest
            rid = self._next_outer_id
            self._next_outer_id += 1
            req = GenRequest(
                rid, portable.prompt, portable.max_new,
                kw["eos_token_id"], temperature=kw["temperature"],
                top_k=kw["top_k"], top_p=kw["top_p"], seed=kw["seed"],
                priority=portable.priority)
            req.out = [int(x) for x in portable.out]
            if kw["eos_token_id"] is not None \
                    and kw["eos_token_id"] in req.out:
                # keep the retire contract for a committed eos the
                # source had not retired yet
                req.eos_pos = req.out.index(kw["eos_token_id"])
            snap.req_id = rid           # re-keyed to this id space
            if TRACER.enabled:
                # adopt under the ambient trace (the fleet's re-place
                # path activates the original request's trace): no
                # add_request runs on this path, so stamp it here
                atr = TRACER.current()
                if atr is not None:
                    req.trace = atr
                    atr.mark("enqueued")
            self.engine.adopt_preempted(req, snap)
            self._tracked[rid] = _Tracked(
                req=req, kwargs=dict(kw), max_new=portable.max_new,
                priority=portable.priority, inner=req)
            return rid
        committed = np.concatenate(
            [portable.prompt, np.asarray(portable.out, np.int32)]) \
            if portable.out else portable.prompt
        return self.add_request(
            committed, portable.max_new - len(portable.out),
            kw["eos_token_id"], temperature=kw["temperature"],
            top_k=kw["top_k"], top_p=kw["top_p"], seed=kw["seed"],
            priority=portable.priority)

    def take_pending_result(self, req_id: int) -> Optional[np.ndarray]:
        """Pop a terminal result synthesized during a recovery but not
        yet delivered through ``step()`` (the drain path collects these
        directly instead of extracting a request that no longer
        exists)."""
        return self._pending_finished.pop(req_id, None)

    def tracked_request(self, req_id: int):
        """The live outer ``GenRequest`` for ``req_id`` (tokens
        accumulate here across this engine's internal crash replays),
        or None once terminal."""
        t = self._tracked.get(req_id)
        return None if t is None else t.req

    # -- internals ------------------------------------------------------
    def _bridge(self, t: _Tracked) -> None:
        """Fold a replayed request's fresh inner tokens into its outer
        object (no-op before any crash, when inner IS the outer)."""
        if t.inner is t.req:
            return
        bridged = len(t.req.out) - t.base
        new = t.inner.out[bridged:]
        if new:
            t.req.out.extend(int(x) for x in new)
        if t.inner.eos_pos is not None and t.req.eos_pos is None:
            t.req.eos_pos = t.base + t.inner.eos_pos

    def _absorb(self, finished: Dict[int, np.ndarray]
                ) -> Dict[int, np.ndarray]:
        """Bridge replayed requests' fresh tokens into the outer
        request objects and translate finished ids back to the
        caller's originals."""
        for t in self._tracked.values():
            self._bridge(t)
        out: Dict[int, np.ndarray] = {}
        for rid, t in list(self._tracked.items()):
            # inner requests are re-keyed to their outer ids at
            # creation, so the engine's finished dict speaks outer ids
            if rid not in finished:
                continue
            arr = finished.pop(rid)
            if t.inner is not t.req:
                # exact final sync (retire may have truncated at eos)
                t.req.out = t.req.out[:t.base] + [int(x)
                                                  for x in t.inner.out]
                arr = np.concatenate(
                    [t.req.prompt, np.asarray(t.req.out, np.int32)])
            out[rid] = arr
            del self._tracked[rid]
        out.update(finished)        # untracked passthrough (defensive)
        if self._pending_finished:
            out.update(self._pending_finished)
            self._pending_finished = {}
        return out

    def _recover(self, exc: BaseException) -> None:
        """Declared crash: circuit-breaker check, flight dump, rebuild
        through the factory, replay every live request from its
        committed token prefix."""
        p = self.policy
        now = self._clock()
        self.last_error = exc
        self.stats["crashes"] += 1
        if self._reg.enabled:
            self._reg.counter("serve.resilience.crashes_total").inc()
        self._event("crash", error=f"{type(exc).__name__}: {exc}"[:300])
        self._dump_flight(
            f"engine recovery: {type(exc).__name__}: {exc}")
        while self._restart_times and \
                now - self._restart_times[0] > p.restart_window_s:
            self._restart_times.popleft()
        if len(self._restart_times) >= p.max_restarts:
            self.stats["circuit_opens"] += 1
            if self._reg.enabled:
                self._reg.counter(
                    "serve.resilience.circuit_open_total").inc()
            self._event("circuit_open",
                        restarts=len(self._restart_times))
            raise RecoveryExhaustedError(
                f"{len(self._restart_times)} engine restarts within "
                f"{p.restart_window_s}s — circuit breaker open; last "
                f"error: {type(exc).__name__}: {exc}") from exc
        self._restart_times.append(now)
        t0 = self._clock()
        # drop the crashed engine's pools before rebuilding; the
        # sentinel (not None) keeps every engine-surface access typed
        # if the rebuild itself fails
        self.engine = _DeadEngine(exc)
        try:
            rebuilt = self._factory()
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as build_err:
            self.engine = _DeadEngine(build_err)
            self.stats["rebuild_failures"] += 1
            self.stats["circuit_opens"] += 1
            if self._reg.enabled:
                self._reg.counter(
                    "serve.resilience.rebuild_failures_total").inc()
                self._reg.counter(
                    "serve.resilience.circuit_open_total").inc()
            self._event("rebuild_failed",
                        error=f"{type(build_err).__name__}: "
                              f"{build_err}"[:300])
            raise RecoveryExhaustedError(
                "engine rebuild failed during crash recovery — "
                "escalating to the typed abort-all path; rebuild "
                f"error: {type(build_err).__name__}: {build_err}"
            ) from build_err
        self.engine = rebuilt
        replayed = 0
        for rid, t in list(self._tracked.items()):
            req = t.req
            if req.eos_pos is not None or len(req.out) >= t.max_new:
                # crashed between producing the final token and the
                # retire: synthesize the terminal result from the
                # committed prefix (eos truncation included)
                if req.eos_pos is not None:
                    req.out = req.out[:req.eos_pos + 1]
                self._pending_finished[rid] = np.concatenate(
                    [req.prompt, np.asarray(req.out, np.int32)])
                del self._tracked[rid]
                continue
            committed = np.concatenate(
                [req.prompt, np.asarray(req.out, np.int32)]) \
                if req.out else req.prompt
            kw = t.kwargs
            # request tracing (ISSUE 20): replay under the ORIGINAL
            # trace — the fresh inner GenRequest adopts it through the
            # ambient channel, so the post-crash spans (queue_wait,
            # replay prefill, decode) stay on one trace_id
            tr = getattr(req, "trace", None) if TRACER.enabled else None
            t_rp = tr.now() if tr is not None else 0.0
            with TRACER.activating(tr):
                inner_rid = self.engine.add_request(
                    committed, t.max_new - len(req.out),
                    kw["eos_token_id"], temperature=kw["temperature"],
                    top_k=kw["top_k"], top_p=kw["top_p"],
                    seed=kw["seed"], priority=t.priority)
            t.inner = next(r for r in reversed(self.engine.queue)
                           if r.req_id == inner_rid)
            t.inner.req_id = rid    # replayed under the same outer id
            t.base = len(req.out)
            if tr is not None:
                tr.add("crash_replay", t_rp, tr.now(),
                       committed=int(len(committed)),
                       error=f"{type(exc).__name__}")
                tr.meta["replayed"] = True
            replayed += 1
        dt = self._clock() - t0
        self.stats["recoveries"] += 1
        self.stats["replayed_requests"] += replayed
        if self._reg.enabled:
            self._reg.counter("serve.resilience.recoveries_total").inc()
            self._reg.counter(
                "serve.resilience.replayed_requests_total").inc(replayed)
            self._reg.histogram("serve.resilience.recovery_secs",
                                unit="s").record(dt)
        self._event("recovered", replayed=replayed, secs=round(dt, 6))

    def _event(self, action: str, **fields) -> None:
        if self._reg.enabled:
            self._reg.event("serve", action=f"resilience_{action}",
                            **fields)

    def _dump_flight(self, reason: str) -> None:
        """Flight-ring post-mortem on every recovery — the serve event
        ring around the crash is the incident timeline."""
        try:
            from ..observability.flight_recorder import FlightRecorder
            for sink in self._reg.sinks:
                if isinstance(sink, FlightRecorder) \
                        and sink.directory is not None:
                    sink.dump(reason)
        except Exception as dump_err:   # the dump must not mask recovery
            self._event("flight_dump_failed",
                        error=str(dump_err)[:200])
