"""Network front door (ISSUE 13): a stdlib-only HTTP/SSE serving
endpoint over :class:`~paddle_tpu.serving.frontend.ServingFrontend`.

PRs 11-12 made everything *behind* the front-end fault-tolerant
(supervised recovery, fleet re-placement, graceful drain); this module
puts a robust wire on that resilient core.  The network edge is where
real traffic's failures actually originate — clients disconnect
mid-stream, readers stall, requests are retried after ambiguous
errors, and the process is restarted under load — so every one of
those is a first-class, metered, tested path here, not an accident:

* **Client-disconnect propagation** — a broken/closed socket
  mid-stream cancels the request through the existing
  ``frontend.cancel`` → ``engine.cancel`` path, freeing the decode
  slot and its refcounted KV pages within one scheduler iteration of
  detection (detection itself is bounded by the SSE heartbeat cadence:
  an idle stream still writes ``:`` comment frames, so a dead socket
  surfaces even between tokens).  Disconnect storms drain at zero
  leaked KV blocks — pinned by tests/test_serving_http.py.
* **Slow-client isolation** — each connection carries a write deadline
  (``io_timeout_s`` on the socket); a stalled reader (zero TCP window)
  times the *handler thread's* write out and is cancelled, while the
  frontend's bounded ``stream_capacity`` / ``backpressure_timeout_s``
  machinery keeps the *driver thread* delivering to batchmates — one
  stalled reader never blocks the scheduler or its batch.
* **Idempotent retry** — a client-supplied ``request_id`` enters a
  dedup window: a retry after a timed-out/ambiguous response attaches
  to the live stream, replaying already-streamed tokens from the
  committed prefix (``RequestHandle.stream_from``) instead of
  double-submitting.  A disconnect on an identified request keeps it
  generating for ``retry_grace_s`` so the retry finds a live stream;
  only an un-retried grace expiry cancels.
* **Graceful shutdown** — SIGTERM (or :meth:`HttpServingServer.
  begin_shutdown`) flips ``/readyz`` to 503, answers new work with
  503 + ``Retry-After``, drains in-flight streams, then tears down and
  returns a zero-leak report (``kv_leak_report`` must show zero).
* **Typed status mapping** — every terminal state the resilience
  stack can produce has exactly one wire representation:

  =============================  =====================================
  lattice state                  HTTP
  =============================  =====================================
  REJECTED (queue/KV saturated)  429 + ``Retry-After``
  REJECTED (fleet exhausted /    503 + ``Retry-After``
  no live replica)
  TIMED_OUT, ``deadline``        408
  TIMED_OUT, ``max_queue_time``  503 + ``Retry-After`` (load shedding)
  CANCELLED                      499 (client closed request)
  malformed request              400
  draining (shutdown)            503 + ``Retry-After``
  FINISHED                       200
  =============================  =====================================

  Mid-SSE, terminals arrive as a final ``done`` / ``error`` event
  carrying the same ``code`` — the stream is already 200 by then.

Endpoints (``docs/serving.md`` has the full wire contract):

  ``POST /v1/generate``   SSE token stream (default) or blocking JSON
  ``POST /v1/cancel``     cancel by client ``request_id`` / server id
  ``GET  /healthz``       process liveness (200 while serving)
  ``GET  /readyz``        placement readiness — fleet ``placeable()``
  ``GET  /metrics``       Prometheus text (``write_prometheus`` format)

Everything here is host-side connection plumbing on stdlib
``http.server`` — no new dependencies, nothing traced, and an AOT-warm
engine behind it serves traffic at ZERO backend compiles
(``serve_http_warm`` budget row).

Quickstart::

    python -m paddle_tpu.serving.http --model llama_tiny --port 8821

    curl -N -X POST localhost:8821/v1/generate \\
        -d '{"prompt_ids": [3, 14, 15], "max_new_tokens": 8}'
"""

from __future__ import annotations

import collections
import http.server
import json
import signal
import socket
import socketserver
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import numpy as np

from .fleet import FleetExhaustedError
from .frontend import (RequestAborted, RequestHandle, RequestRejected,
                       RequestState, ServingFrontend)
from .metrics import ServeMetrics

__all__ = ["HttpServingServer", "HttpTransport", "WireHandle",
           "iter_sse", "main"]


# ---------------------------------------------------------------------
# wire-facing request/status helpers
# ---------------------------------------------------------------------
class _BadRequest(ValueError):
    """Malformed wire request — maps to 400 with a reason body."""


def _parse_generate(body: Dict[str, Any]) -> Dict[str, Any]:
    """Validate a /v1/generate body into frontend.submit kwargs.
    Anything malformed raises :class:`_BadRequest` (→ 400); load
    problems are NOT decided here — admission does that."""
    if not isinstance(body, dict):
        raise _BadRequest("body must be a JSON object")
    ids = body.get("prompt_ids")
    if not isinstance(ids, list) or not ids \
            or not all(isinstance(t, int) and not isinstance(t, bool)
                       for t in ids):
        raise _BadRequest("prompt_ids must be a non-empty list of ints")
    mnt = body.get("max_new_tokens")
    if not isinstance(mnt, int) or isinstance(mnt, bool) or mnt < 1:
        raise _BadRequest("max_new_tokens must be an int >= 1")
    out: Dict[str, Any] = {"prompt_ids": np.asarray(ids, np.int32),
                           "max_new_tokens": mnt}
    for key, typ in (("eos_token_id", int), ("top_k", int), ("seed", int),
                     ("priority", int), ("temperature", (int, float)),
                     ("top_p", (int, float)),
                     ("deadline_s", (int, float)),
                     ("max_queue_time_s", (int, float))):
        v = body.get(key)
        if v is None:
            continue
        if not isinstance(v, typ) or isinstance(v, bool):
            raise _BadRequest(f"{key} must be {typ}")
        out[key] = v
    rid = body.get("request_id")
    if rid is not None and (not isinstance(rid, str) or not rid
                            or len(rid) > 200):
        raise _BadRequest("request_id must be a non-empty string "
                          "(<= 200 chars)")
    stream = body.get("stream", True)
    if not isinstance(stream, bool):
        raise _BadRequest("stream must be a bool")
    return out


def _reject_status(reason: str) -> int:
    """REJECTED reason → status: capacity the caller should back off
    from is 429; a fleet with nowhere to place anything is 503."""
    r = (reason or "").lower()
    if "no live replica" in r or "fleet" in r or "dead" in r:
        return 503
    return 429


def _terminal_code(state: RequestState, reason: Optional[str]) -> int:
    """The one wire code for each abnormal terminal lattice state."""
    if state is RequestState.TIMED_OUT:
        return 408 if reason == "deadline" else 503
    if state is RequestState.CANCELLED:
        return 499
    if state is RequestState.REJECTED:
        return _reject_status(reason or "")
    return 200


@dataclass
class _Tracked:
    """Server bookkeeping for one submitted handle: the dedup/attach
    window entry (keyed by client request_id when given, and always by
    server req_id for /v1/cancel)."""

    handle: RequestHandle
    request_id: Optional[str]
    expires_t: float                 # drop from the window after this
    consumers: int = 0               # connections currently streaming
    grace_t: Optional[float] = None  # disconnected: cancel at this time


class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
    daemon_threads = True
    # a FIN mid-accept-queue must not take the listener down
    allow_reuse_address = True
    owner: "HttpServingServer"

    def handle_error(self, request, client_address):
        # stdlib default prints a traceback for every client that goes
        # away mid-handshake; connection aborts are business as usual
        # for a front door — account them instead of spamming stderr.
        # Anything that is NOT a connection fault is a real bug: keep
        # the stdlib traceback so it never disappears silently.
        import sys
        exc = sys.exc_info()[1]
        self.owner._on_handler_error(client_address, exc)
        if not isinstance(exc, (BrokenPipeError, ConnectionError,
                                socket.timeout, TimeoutError)):
            super().handle_error(request, client_address)


class HttpServingServer:
    """HTTP/SSE front door over a :class:`ServingFrontend`.

    Args:
      frontend: the front-end to serve (its engine may be a bare
        ``ContinuousBatchingEngine``, a ``SupervisedEngine``, or an
        ``EngineRouter`` fleet — ``/readyz`` adapts).  The server owns
        driving it: :meth:`start` launches the frontend's background
        driver thread.
      host / port: bind address; ``port=0`` picks an ephemeral port
        (read it back from ``server.port``).
      io_timeout_s: per-connection socket deadline, both directions —
        a stalled reader's SSE write (or a slowloris header read) times
        out and the connection is torn down.
      heartbeat_s: idle SSE streams emit a ``:`` comment frame this
        often; it is also the disconnect-detection cadence while no
        token is flowing.
      heartbeat_pad_bytes: padding appended to heartbeat comments
        (anti-buffering padding for proxies; the stalled-reader chaos
        tests use it to fill kernel socket buffers deterministically).
      event_pad_bytes: padding inside every ``token`` event's JSON
        (same proxy-buster purpose; same chaos use — makes a stalled
        reader's TCP window fill within a bounded token count).
      dedup_window_s: how long a client ``request_id`` stays
        attachable after its stream finishes (idempotent-retry window).
      retry_grace_s: how long an identified request keeps generating
        after its consumer disconnects, waiting for a retry to attach;
        expiry cancels it (an anonymous disconnect cancels at once).
      drain_timeout_s: default graceful-shutdown drain budget.
      retry_after_s: the ``Retry-After`` header value on 429/503.
      sndbuf_bytes: optional SO_SNDBUF override on accepted sockets
        (chaos tests shrink it so a stalled reader back-pressures the
        writer within the test's patience).
      registry: metrics registry (defaults to the process registry via
        :class:`ServeMetrics`).
    """

    def __init__(self, frontend: ServingFrontend, *,
                 host: str = "127.0.0.1", port: int = 0,
                 io_timeout_s: float = 20.0,
                 heartbeat_s: float = 0.5,
                 heartbeat_pad_bytes: int = 0,
                 event_pad_bytes: int = 0,
                 dedup_window_s: float = 30.0,
                 retry_grace_s: float = 2.0,
                 drain_timeout_s: float = 30.0,
                 retry_after_s: float = 1.0,
                 sndbuf_bytes: Optional[int] = None,
                 registry=None):
        self.frontend = frontend
        self.metrics = ServeMetrics(registry) if registry is not None \
            else frontend.metrics
        self.io_timeout_s = float(io_timeout_s)
        self.heartbeat_s = float(heartbeat_s)
        self.heartbeat_pad_bytes = int(heartbeat_pad_bytes)
        self.event_pad_bytes = int(event_pad_bytes)
        self.dedup_window_s = float(dedup_window_s)
        self.retry_grace_s = float(retry_grace_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self.retry_after_s = float(retry_after_s)
        self.sndbuf_bytes = sndbuf_bytes
        self._lock = threading.RLock()
        self._by_request_id: Dict[str, _Tracked] = {}
        self._by_rid: "collections.OrderedDict[int, _Tracked]" = \
            collections.OrderedDict()
        self._active = 0
        self._aborted_conns = 0
        self._draining = False
        self._drain_report: Optional[Dict[str, Any]] = None
        self._drain_done = threading.Event()
        self._stop_housekeeper = threading.Event()
        self._housekeeper: Optional[threading.Thread] = None
        self._serve_thread: Optional[threading.Thread] = None
        self._httpd = _Server((host, port), _RequestHandler)
        self._httpd.owner = self

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def draining(self) -> bool:
        return self._draining

    def start(self) -> "HttpServingServer":
        """Start the frontend driver, the accept loop, and the
        housekeeper.  Idempotent."""
        self.frontend.start()
        if self._serve_thread is None or not self._serve_thread.is_alive():
            self._serve_thread = threading.Thread(
                target=self._httpd.serve_forever,
                kwargs={"poll_interval": 0.05},
                name="serving-http-accept", daemon=True)
            self._serve_thread.start()
        if self._housekeeper is None or not self._housekeeper.is_alive():
            self._stop_housekeeper.clear()
            self._housekeeper = threading.Thread(
                target=self._housekeep, name="serving-http-housekeeper",
                daemon=True)
            self._housekeeper.start()
        return self

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT → graceful shutdown (main thread only — the
        CLI path).  The handler returns immediately; the drain runs on
        a background thread so the signal context stays trivial."""
        def _on_signal(signum, frame):
            # Deliberate fire-and-forget: the signal context must return
            # immediately and nothing can wait on this thread — the
            # drain itself signals completion via _drain_done.
            threading.Thread(  # locklint: disable=LK006
                target=self.begin_shutdown,
                kwargs={"reason": signal.Signals(signum).name},
                name="serving-http-shutdown", daemon=True).start()

        signal.signal(signal.SIGTERM, _on_signal)
        signal.signal(signal.SIGINT, _on_signal)

    def begin_shutdown(self, *, drain_timeout_s: Optional[float] = None,
                       reason: str = "shutdown"
                       ) -> Dict[str, Any]:
        """Graceful shutdown: stop taking new work (503 + Retry-After,
        ``/readyz`` 503), drain in-flight streams through the frontend,
        cancel whatever outlives the drain budget, tear down, and
        return the zero-leak report.  Idempotent — concurrent callers
        all get the same report."""
        with self._lock:
            already = self._draining
            self._draining = True
        if already:
            self._drain_done.wait()
            return dict(self._drain_report or {})
        budget = self.drain_timeout_s if drain_timeout_s is None \
            else float(drain_timeout_s)
        t0 = time.monotonic()
        self.metrics.event("http_shutdown_begin", reason=reason)
        drained_clean = True
        while self.frontend.live_requests > 0:
            if time.monotonic() - t0 > budget:
                drained_clean = False
                break
            time.sleep(0.01)
        cancelled = 0
        if not drained_clean:
            with self._lock:
                stragglers = [t.handle for t in self._by_rid.values()
                              if not t.handle.state.terminal]
            for h in stragglers:
                if self.frontend.cancel(
                        h, reason="shutdown drain deadline"):
                    cancelled += 1
        # give connection threads a moment to flush terminal events
        conn_t0 = time.monotonic()
        while self._active > 0 and time.monotonic() - conn_t0 < 5.0:
            time.sleep(0.01)
        self._stop_housekeeper.set()
        self._httpd.shutdown()
        # shutdown() returns once serve_forever exits; join the worker
        # threads so close() never returns with live threads behind it
        # (current-thread guard: begin_shutdown may run ON them)
        if self._serve_thread is not None \
                and self._serve_thread is not threading.current_thread():
            self._serve_thread.join(timeout=5.0)
        if self._housekeeper is not None \
                and self._housekeeper is not threading.current_thread():
            self._housekeeper.join(timeout=5.0)
        self.frontend.close(cancel_pending=True)
        leak = self.frontend.engine.kv_leak_report()
        drain_secs = time.monotonic() - t0
        with self._lock:
            drained = len([t for t in self._by_rid.values()
                           if t.handle.state is RequestState.FINISHED])
        report = {
            "reason": reason,
            "drain_secs": round(drain_secs, 4),
            "drained_within_budget": drained_clean,
            "finished_total": drained,
            "cancelled_at_deadline": cancelled,
            "kv_leak_report": leak,
            "kv_leaked_blocks": leak["leaked"] + leak["unaccounted"],
        }
        self.metrics.on_shutdown_drain(drain_secs, drained, cancelled)
        with self._lock:   # concurrent callers read it after the event
            self._drain_report = report
        self._drain_done.set()
        return dict(report)

    def close(self) -> Dict[str, Any]:
        """Graceful shutdown + full teardown (the context-manager
        exit); returns the drain report."""
        report = self.begin_shutdown(reason="close")
        self._httpd.server_close()
        return report

    def __enter__(self) -> "HttpServingServer":
        return self.start()

    def __exit__(self, *exc_info) -> None:
        self.close()

    def chaos(self, fn):
        """Run ``fn(frontend.engine)`` under the frontend's scheduler
        lock — the ops/chaos entry point for fleet surgery
        (``kill_replica``, ``drain``) while the driver thread is
        pumping.  Returns ``fn``'s result."""
        with self.frontend._lock:
            return fn(self.frontend.engine)

    # ------------------------------------------------------------------
    # ready / health
    # ------------------------------------------------------------------
    def ready(self) -> Dict[str, Any]:
        """The /readyz payload: ready iff not draining, the frontend is
        alive, and (for a fleet) at least one replica is placeable."""
        from .resilience import ResilienceError
        engine_reason = None
        try:
            placeable = getattr(self.frontend.engine, "placeable", None)
            census = getattr(self.frontend.engine, "health_census", None)
            ok_place = placeable() if callable(placeable) else True
            census_val = census() if callable(census) else None
        except ResilienceError as e:
            # a dead supervisor / exhausted fleet answers every engine-
            # surface access with its typed error — that IS not-ready
            ok_place, census_val = False, None
            engine_reason = f"{type(e).__name__}: {e}"
        ok = (not self._draining and self.frontend.error is None
              and ok_place)
        out: Dict[str, Any] = {"ready": bool(ok)}
        if self._draining:
            out["reason"] = "draining"
        elif self.frontend.error is not None:
            out["reason"] = ("frontend crashed: "
                             f"{type(self.frontend.error).__name__}")
        elif not ok:
            out["reason"] = engine_reason or "no placeable replica"
        if census_val is not None:
            out["health_census"] = census_val
        return out

    # ------------------------------------------------------------------
    # submit / attach / cancel (handler-thread entry points)
    # ------------------------------------------------------------------
    def submit_or_attach(self, kwargs: Dict[str, Any],
                         request_id: Optional[str]):
        """Submit a new request, or attach to the live/terminal stream
        a previous submit with the same ``request_id`` created.
        Returns ``(tracked, dedup_hit)``."""
        with self._lock:
            if request_id is not None:
                t = self._by_request_id.get(request_id)
                if t is not None:
                    t.grace_t = None          # a consumer is (re)attached
                    t.consumers += 1
                    t.expires_t = time.monotonic() + self.dedup_window_s
                    self.metrics.on_dedup_hit(
                        request_id, live=not t.handle.state.terminal)
                    return t, True
            handle = self.frontend.submit(**kwargs)
            if handle.trace is not None and request_id is not None:
                # index the trace under the CLIENT id too, so
                # GET /v1/trace/<request_id> resolves either id space
                handle.trace.request_id = request_id
            t = _Tracked(handle=handle, request_id=request_id,
                         expires_t=time.monotonic() + self.dedup_window_s,
                         consumers=1)
            # a REJECTED submit never enters the window: a retry after
            # 429/503 + Retry-After SHOULD be a fresh admission attempt,
            # not a replay of the rejection
            if handle.state is not RequestState.REJECTED:
                if request_id is not None:
                    self._by_request_id[request_id] = t
                if handle.req_id is not None:
                    self._by_rid[handle.req_id] = t
            return t, False

    def release(self, t: _Tracked, *, disconnected: bool) -> None:
        """A consumer detached from ``t``'s stream.  A clean detach on
        a terminal handle just drops the refcount; a disconnect on a
        live identified request arms the retry grace timer, and on an
        anonymous request cancels immediately (slot + KV pages free
        within one scheduler iteration)."""
        cancel = False
        with self._lock:
            t.consumers = max(t.consumers - 1, 0)
            if disconnected and not t.handle.state.terminal \
                    and t.consumers == 0:
                if t.request_id is not None and self.retry_grace_s > 0:
                    t.grace_t = time.monotonic() + self.retry_grace_s
                else:
                    cancel = True
        if cancel:
            n = t.handle.n_streamed
            if self.frontend.cancel(t.handle,
                                    reason="client disconnected"):
                self.metrics.on_disconnect_cancel(t.handle.req_id, n)

    def cancel_request(self, *, request_id: Optional[str] = None,
                       req_id: Optional[int] = None) -> Dict[str, Any]:
        """/v1/cancel body → result.  Looks up by client request_id
        first, then by server req_id."""
        with self._lock:
            t = None
            if request_id is not None:
                t = self._by_request_id.get(request_id)
            if t is None and req_id is not None:
                t = self._by_rid.get(req_id)
        if t is None:
            return {"cancelled": False, "found": False}
        ok = self.frontend.cancel(t.handle, reason="cancelled by client")
        return {"cancelled": bool(ok), "found": True,
                "state": t.handle.state.value,
                "req_id": t.handle.req_id}

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _conn_opened(self) -> None:
        with self._lock:
            self._active += 1
            n = self._active
        self.metrics.on_connection(n, opened=True)

    def _conn_closed(self) -> None:
        with self._lock:
            self._active = max(self._active - 1, 0)
            n = self._active
        self.metrics.on_connection(n, opened=False)

    def _on_handler_error(self, client_address,
                          exc: Optional[BaseException] = None) -> None:
        with self._lock:
            self._aborted_conns += 1
        self.metrics.event("http_connection_aborted",
                           peer=str(client_address),
                           error=(f"{type(exc).__name__}: {exc}"[:200]
                                  if exc is not None else "unknown"))

    def _housekeep(self) -> None:
        """Expire retry-grace timers (cancel abandoned disconnected
        requests) and prune the dedup window."""
        while not self._stop_housekeeper.wait(0.02):
            now = time.monotonic()
            to_cancel: List[_Tracked] = []
            with self._lock:
                for t in list(self._by_request_id.values()):
                    if t.grace_t is not None and now >= t.grace_t \
                            and not t.handle.state.terminal:
                        t.grace_t = None
                        to_cancel.append(t)
                for key, t in list(self._by_request_id.items()):
                    if now >= t.expires_t and t.consumers == 0 \
                            and t.handle.state.terminal:
                        del self._by_request_id[key]
                for rid, t in list(self._by_rid.items()):
                    if now >= t.expires_t and t.consumers == 0 \
                            and t.handle.state.terminal:
                        del self._by_rid[rid]
            for t in to_cancel:
                if self.frontend.cancel(
                        t.handle,
                        reason="client disconnected (retry grace "
                               "expired)"):
                    self.metrics.on_abandoned(t.request_id or "")
                    self.metrics.on_disconnect_cancel(
                        t.handle.req_id, t.handle.n_streamed)


# ---------------------------------------------------------------------
# the request handler
# ---------------------------------------------------------------------
class _RequestHandler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "paddle-tpu-serve/1.0"

    @property
    def srv(self) -> HttpServingServer:
        return self.server.owner

    # quiet the default per-request stderr logging; the metric/event
    # stream is the log
    def log_message(self, fmt, *args):
        pass

    def setup(self):
        owner = self.server.owner
        self.timeout = owner.io_timeout_s
        super().setup()
        if owner.sndbuf_bytes is not None:
            self.connection.setsockopt(socket.SOL_SOCKET,
                                       socket.SO_SNDBUF,
                                       int(owner.sndbuf_bytes))
        owner._conn_opened()

    def finish(self):
        try:
            super().finish()
        finally:
            self.server.owner._conn_closed()

    # -- plumbing -------------------------------------------------------
    def _send_json(self, code: int, payload: Dict[str, Any],
                   headers: Optional[Dict[str, str]] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)
        self.wfile.flush()

    def _retry_after(self) -> Dict[str, str]:
        return {"Retry-After": f"{self.srv.retry_after_s:g}"}

    def _read_body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length") or 0)
        if n <= 0:
            raise _BadRequest("missing request body")
        if n > 10 * 1024 * 1024:
            raise _BadRequest("request body too large")
        raw = self.rfile.read(n)
        try:
            return json.loads(raw)
        except (ValueError, UnicodeDecodeError) as e:
            raise _BadRequest(f"invalid JSON body: {e}") from e

    # -- GET ------------------------------------------------------------
    def do_GET(self):
        if self.path == "/healthz":
            self._send_json(200, {
                "status": "ok",
                "live_requests": self.srv.frontend.live_requests,
                "draining": self.srv.draining})
        elif self.path == "/readyz":
            payload = self.srv.ready()
            self._send_json(200 if payload["ready"] else 503, payload,
                            None if payload["ready"]
                            else self._retry_after())
        elif self.path == "/metrics":
            # publish-on-scrape: the engine gauges (kv_utilization,
            # queue_depth, fleet census) are otherwise only fresh when
            # a frontend step happens to run _publish — an idle server
            # would serve Prometheus stale zeros forever
            from .resilience import ResilienceError
            fe = self.srv.frontend
            try:
                with fe._lock:
                    fe._publish()
            except ResilienceError:
                # dead engine surface: scrape whatever gauges exist —
                # the crash counters are the signal Prometheus needs
                pass
            text = self.srv.metrics.registry.prometheus_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(text)))
            self.end_headers()
            self.wfile.write(text)
            self.wfile.flush()
        elif self.path.startswith("/v1/trace/"):
            self._trace_debug(self.path[len("/v1/trace/"):])
        else:
            self._send_json(404, {"error": f"unknown path {self.path}"})

    def _trace_debug(self, key: str) -> None:
        """``GET /v1/trace/<key>``: one request's span tree (live or
        from the finished ring) as JSON — ``key`` is the server req_id,
        the client request_id, or the trace_id (tried in that order)."""
        from ..observability.tracing import TRACER
        if not TRACER.enabled:
            self._send_json(404, {
                "error": "tracing is disabled (enable "
                         "paddle_tpu.observability.TRACER)"})
            return
        tr = None
        if key.isdigit():
            tr = TRACER.lookup(rid=int(key))
        if tr is None:
            tr = TRACER.lookup(request_id=key)
        if tr is None:
            tr = TRACER.lookup(trace_id=key)
        if tr is None:
            self._send_json(404, {"error": f"no trace for {key!r}"})
            return
        self._send_json(200, tr.to_dict())

    # -- POST -----------------------------------------------------------
    def do_POST(self):
        try:
            if self.path == "/v1/generate":
                self._generate()
            elif self.path == "/v1/cancel":
                body = self._read_body()
                rid = body.get("request_id")
                num = body.get("req_id")
                if rid is None and num is None:
                    raise _BadRequest(
                        "cancel needs request_id or req_id")
                self._send_json(200, self.srv.cancel_request(
                    request_id=rid, req_id=num))
            else:
                self._send_json(404,
                                {"error": f"unknown path {self.path}"})
        except _BadRequest as e:
            self._send_json(400, {"error": str(e)})
        except FleetExhaustedError as e:
            self._send_json(503, {"error": str(e)},
                            self._retry_after())
        except (BrokenPipeError, ConnectionResetError, socket.timeout):
            # response path died with the client; the generate handler
            # already routed the request through release(disconnected)
            self.close_connection = True

    def _generate(self) -> None:
        srv = self.srv
        body = self._read_body()
        kwargs = _parse_generate(body)
        request_id = body.get("request_id")
        stream = body.get("stream", True)
        srv.metrics.on_http_request()
        if srv.draining:
            self._send_json(
                503, {"error": "server is draining (shutdown in "
                               "progress)", "state": "DRAINING"},
                self._retry_after())
            return
        try:
            tracked, dedup = srv.submit_or_attach(kwargs, request_id)
        except ValueError as e:
            # the frontend raises ValueError only for malformed
            # requests (load problems come back as REJECTED handles)
            self._send_json(400, {"error": str(e)})
            return
        handle = tracked.handle
        if handle.state is RequestState.REJECTED:
            code = _reject_status(handle.reason or "")
            self._send_json(code, {"state": "REJECTED",
                                   "error": handle.reason},
                            self._retry_after())
            srv.release(tracked, disconnected=False)
            return
        if stream:
            self._stream_sse(tracked, dedup)
        else:
            self._blocking_json(tracked)

    @staticmethod
    def _with_trace(handle: RequestHandle,
                    payload: Dict[str, Any]) -> Dict[str, Any]:
        tr = getattr(handle, "trace", None)
        if tr is not None:
            payload["trace_id"] = tr.trace_id
        return payload

    # -- blocking JSON mode ---------------------------------------------
    def _blocking_json(self, tracked: _Tracked) -> None:
        srv = self.srv
        handle = tracked.handle
        try:
            try:
                result = handle.result()
                payload = self._with_trace(handle, {
                    "state": "FINISHED",
                    "req_id": handle.req_id,
                    "tokens": handle.tokens(),
                    "ids": np.asarray(result).tolist()})
                self._send_json(200, payload)
            except RequestRejected:
                self._send_json(_reject_status(handle.reason or ""),
                                self._with_trace(handle, {
                                    "state": "REJECTED",
                                    "error": handle.reason}),
                                self._retry_after())
            except RequestAborted as e:
                code = _terminal_code(e.state, handle.reason)
                hdrs = self._retry_after() if code == 503 else None
                self._send_json(code,
                                self._with_trace(handle, {
                                    "state": e.state.value,
                                    "req_id": handle.req_id,
                                    "reason": handle.reason,
                                    "tokens": handle.tokens()}),
                                hdrs)
        except (BrokenPipeError, ConnectionResetError,
                socket.timeout, OSError):
            srv.release(tracked, disconnected=True)
            self.close_connection = True
            return
        srv.release(tracked, disconnected=False)

    # -- SSE streaming mode ----------------------------------------------
    def _sse_headers(self, handle: RequestHandle, replayed: bool) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.send_header("X-Request-Id", str(handle.req_id))
        tr = getattr(handle, "trace", None)
        if tr is not None:
            self.send_header("X-Trace-Id", tr.trace_id)
        if replayed:
            self.send_header("X-Replayed", "true")
        self.end_headers()
        self.close_connection = True

    def _sse_event(self, event: str, payload: Dict[str, Any]) -> None:
        self.wfile.write(
            f"event: {event}\ndata: {json.dumps(payload)}\n\n".encode())
        self.wfile.flush()

    def _heartbeat(self) -> None:
        pad = "x" * self.srv.heartbeat_pad_bytes
        self.wfile.write(f": hb {pad}\n\n".encode())
        self.wfile.flush()

    def _stream_sse(self, tracked: _Tracked, dedup: bool) -> None:
        """The streaming path: replay the committed prefix (a dedup
        attach starts at index 0 — idempotent retry), then follow the
        live stream, heartbeating while idle.  Any socket failure
        routes through ``release(disconnected=True)``: anonymous
        requests cancel within one scheduler iteration, identified
        ones arm the retry grace timer."""
        srv = self.srv
        handle = tracked.handle
        last_write = [time.monotonic()]

        def heartbeat():
            if time.monotonic() - last_write[0] >= srv.heartbeat_s:
                self._heartbeat()
                last_write[0] = time.monotonic()

        try:
            self._sse_headers(handle, replayed=dedup)
            try:
                for i, tok in handle.stream_from(
                        0, poll_s=min(srv.heartbeat_s, 0.05),
                        idle_cb=heartbeat):
                    ev = {"i": i, "t": int(tok)}
                    if srv.event_pad_bytes:
                        ev["pad"] = "x" * srv.event_pad_bytes
                    self._sse_event("token", ev)
                    last_write[0] = time.monotonic()
                result = handle.result(timeout=30.0)
                self._sse_event("done", self._with_trace(handle, {
                    "state": "FINISHED", "req_id": handle.req_id,
                    "n": handle.n_streamed,
                    "tokens": handle.tokens(),
                    "ids": np.asarray(result).tolist()}))
            except RequestRejected:
                self._sse_event("error", self._with_trace(handle, {
                    "state": "REJECTED",
                    "code": _reject_status(handle.reason or ""),
                    "reason": handle.reason}))
            except RequestAborted as e:
                self._sse_event("error", self._with_trace(handle, {
                    "state": e.state.value,
                    "code": _terminal_code(e.state, handle.reason),
                    "req_id": handle.req_id,
                    "reason": handle.reason,
                    "n": handle.n_streamed}))
        except socket.timeout:
            srv.metrics.on_write_stall(handle.req_id, srv.io_timeout_s)
            srv.release(tracked, disconnected=True)
            self.close_connection = True
            return
        except (BrokenPipeError, ConnectionResetError, OSError):
            srv.release(tracked, disconnected=True)
            self.close_connection = True
            return
        srv.release(tracked, disconnected=False)


# ---------------------------------------------------------------------
# wire client: the loadgen transport (and the test suite's SSE client)
# ---------------------------------------------------------------------
def iter_sse(resp):
    """Parse an SSE byte stream into ``(event, payload_dict)`` pairs;
    comment/heartbeat frames are skipped.  ``resp`` is anything with
    ``readline()`` (an ``http.client.HTTPResponse``)."""
    event: Optional[str] = None
    data: List[str] = []
    while True:
        line = resp.readline()
        if not line:
            return
        line = line.rstrip(b"\r\n")
        if not line:
            if event is not None:
                yield event, json.loads("\n".join(data)) if data else {}
            event, data = None, []
            continue
        if line.startswith(b":"):
            continue                              # heartbeat / comment
        if line.startswith(b"event:"):
            event = line[len(b"event:"):].strip().decode()
        elif line.startswith(b"data:"):
            data.append(line[len(b"data:"):].strip().decode())


class WireHandle:
    """Client-side mirror of a :class:`RequestHandle` for one request
    streamed over HTTP/SSE — the surface the load generator reads
    (state / n_streamed / ttft / cancel), fed by a reader thread."""

    def __init__(self, transport: "HttpTransport", request_id: str,
                 payload: Dict[str, Any]):
        self._tp = transport
        self.request_id = request_id
        self.payload = payload
        self.req_id: Optional[int] = None
        self.submit_t = time.monotonic()
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.reason: Optional[str] = None
        self.wire_error: Optional[str] = None
        self.status: Optional[int] = None         # HTTP status
        self.code: Optional[int] = None           # terminal lattice code
        self._lock = threading.Lock()
        self._tokens: Dict[int, int] = {}
        self._state = RequestState.QUEUED
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"wire-{request_id}")
        self._thread.start()

    # -- RequestHandle-compatible surface -------------------------------
    @property
    def state(self) -> RequestState:
        return self._state

    @property
    def n_streamed(self) -> int:
        with self._lock:
            return len(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def tokens(self) -> List[int]:
        with self._lock:
            return [self._tokens[i] for i in sorted(self._tokens)]

    def cancel(self) -> bool:
        if self._state.terminal:
            return False
        return self._tp._cancel(self.request_id)

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    # -- reader thread ---------------------------------------------------
    def _run(self) -> None:
        import http.client as hc
        conn = hc.HTTPConnection(self._tp.host, self._tp.port,
                                 timeout=self._tp.timeout_s)
        try:
            conn.request("POST", "/v1/generate",
                         json.dumps(self.payload),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            self.status = resp.status
            if resp.status != 200:
                body = resp.read().decode(errors="replace")
                self._finish_from_status(resp.status, body)
                return
            rid = resp.getheader("X-Request-Id")
            if rid is not None and rid != "None":
                self.req_id = int(rid)
            for event, payload in iter_sse(resp):
                now = time.monotonic()
                if event == "token":
                    with self._lock:
                        first = not self._tokens
                        self._tokens[int(payload["i"])] = \
                            int(payload["t"])
                    if first and self.first_token_t is None:
                        self.first_token_t = now
                    if self._state is RequestState.QUEUED:
                        self._state = RequestState.RUNNING
                elif event == "done":
                    self.finish_t = now
                    self._state = RequestState.FINISHED
                    return
                elif event == "error":
                    self.finish_t = now
                    self.reason = payload.get("reason")
                    self.code = payload.get("code")
                    self._state = RequestState(
                        payload.get("state", "CANCELLED"))
                    return
            # EOF without a terminal event: ambiguous wire death
            self.wire_error = "stream ended without terminal event"
            self._state = RequestState.CANCELLED
        except (OSError, ValueError) as e:
            self.wire_error = f"{type(e).__name__}: {e}"
            if not self._state.terminal:
                self._state = RequestState.CANCELLED
        finally:
            conn.close()

    def _finish_from_status(self, status: int, body: str) -> None:
        self.finish_t = time.monotonic()
        try:
            payload = json.loads(body)
        except ValueError:
            payload = {}
        self.reason = payload.get("error") or body[:200]
        self.code = status
        if status in (429, 503):
            self._state = RequestState.REJECTED
        elif status == 408:
            self._state = RequestState.TIMED_OUT
        else:
            self._state = RequestState.CANCELLED

    def __repr__(self) -> str:
        return (f"WireHandle({self.request_id}, "
                f"state={self._state.value}, "
                f"streamed={self.n_streamed})")


class HttpTransport:
    """Load-generator transport that submits over the HTTP/SSE wire
    instead of calling ``frontend.submit`` in-process.

    Same seed, same engine vocab → the SAME request sequence as the
    in-process transport (pinned by tests): the loadgen's plan is a
    pure function of its seed, and both transports consume the plan
    through one kwargs builder, so wire chaos results are directly
    comparable to the in-process fleet-chaos baselines (PR 12).

    ``server=`` (optional) points at a co-located
    :class:`HttpServingServer` for end-of-run introspection
    (``kv_leak_report``) — over a real network the leak check runs
    server-side instead."""

    def __init__(self, host: str, port: int, *,
                 server: Optional[HttpServingServer] = None,
                 vocab_size: Optional[int] = None,
                 timeout_s: float = 60.0, tag: str = "lg"):
        self.host = host
        self.port = port
        self.server = server
        self.timeout_s = float(timeout_s)
        self.tag = tag
        self._n = 0
        self.submitted: List[Dict[str, Any]] = []
        self.handles: List[WireHandle] = []
        if vocab_size is None:
            if server is None:
                raise ValueError("HttpTransport needs vocab_size= (or a "
                                 "co-located server= to read it from)")
            vocab_size = int(server.frontend.engine.cfg.vocab_size)
        self.vocab_size = int(vocab_size)

    def submit(self, **kwargs) -> WireHandle:
        """Submit one request (frontend.submit kwargs) over the wire."""
        payload: Dict[str, Any] = {
            "prompt_ids": np.asarray(kwargs.pop("prompt_ids"),
                                     np.int32).tolist(),
            "max_new_tokens": int(kwargs.pop("max_new_tokens")),
            "stream": True,
        }
        for k, v in kwargs.items():
            if v is not None:
                payload[k] = v
        request_id = f"{self.tag}-{self._n}"
        self._n += 1
        payload["request_id"] = request_id
        self.submitted.append(dict(payload))
        h = WireHandle(self, request_id, payload)
        self.handles.append(h)
        return h

    def _cancel(self, request_id: str) -> bool:
        import http.client as hc
        conn = hc.HTTPConnection(self.host, self.port,
                                 timeout=self.timeout_s)
        try:
            conn.request("POST", "/v1/cancel",
                         json.dumps({"request_id": request_id}),
                         {"Content-Type": "application/json"})
            resp = conn.getresponse()
            ok = resp.status == 200 and \
                json.loads(resp.read()).get("cancelled", False)
            return bool(ok)
        except (OSError, ValueError):
            return False
        finally:
            conn.close()

    def pump(self, sleep) -> None:
        """The loadgen's between-arrivals tick: the server drives its
        own scheduler, so the wire client only yields."""
        sleep(0.002)

    def drain(self, timeout_s: float = 120.0) -> None:
        """Wait for every reader thread to reach a terminal event."""
        deadline = time.monotonic() + timeout_s
        for h in self.handles:
            h.join(max(deadline - time.monotonic(), 0.0))

    def kv_leak_report(self) -> Dict[str, int]:
        if self.server is not None:
            return self.server.frontend.engine.kv_leak_report()
        # remote server: the leak invariant is checked server-side
        return {"free_blocks": -1, "index_blocks": -1, "slot_blocks": -1,
                "leaked": 0, "unaccounted": 0}

    def prefix_stats(self) -> Optional[Dict[str, Any]]:
        """Co-located server's prefix-cache counters (the loadgen's
        ``LoadReport.prefix`` section), or None over a real network —
        hit-rate is then read server-side from ``serve.prefix.*``."""
        if self.server is None:
            return None
        fn = getattr(self.server.frontend.engine, "prefix_stats", None)
        return fn() if callable(fn) else None


# ---------------------------------------------------------------------
# CLI: python -m paddle_tpu.serving.http --model llama_tiny --port 8821
# ---------------------------------------------------------------------
def _build_frontend(args) -> ServingFrontend:
    import jax

    from .. import parallel as dist
    from ..inference.serving import ContinuousBatchingEngine
    from ..models import llama as llama_zoo
    from ..parallel.topology import HybridTopology, set_topology
    from .frontend import AdmissionConfig

    cfg_fn = getattr(llama_zoo, args.model, None)
    if cfg_fn is None:
        raise SystemExit(f"unknown model {args.model!r} (the zoo has "
                         "llama_tiny / llama_7b / ...)")
    cfg = cfg_fn()
    topo = dist.init_topology(devices=jax.devices()[:1])
    _, init_fn = llama_zoo.build_llama_train_step(cfg, topo,
                                                  num_microbatches=1)
    params = init_fn(args.seed)["params"]
    set_topology(HybridTopology())
    eng_kw: Dict[str, Any] = dict(
        max_batch=args.max_batch, block_size=args.block_size,
        num_blocks=args.num_blocks,
        prefill_buckets=tuple(args.prefill_buckets),
        aot_dir=args.aot_dir)
    if args.replicas > 1:
        from ..aot.serve import warm_engine_factory
        from .fleet import EngineRouter
        if args.aot_dir is None:
            raise SystemExit("--replicas > 1 needs --aot-dir (replicas "
                             "share one AOT artifact generation)")
        factory = warm_engine_factory(cfg, params, aot_dir=args.aot_dir,
                                      **{k: v for k, v in eng_kw.items()
                                         if k != "aot_dir"})
        engine: Any = EngineRouter([factory] * args.replicas)
    else:
        engine = ContinuousBatchingEngine(cfg, params, **eng_kw)
    return ServingFrontend(
        engine,
        admission=AdmissionConfig(max_queue_len=args.max_queue_len),
        stream_capacity=args.stream_capacity)


def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.serving.http",
        description="HTTP/SSE serving endpoint over the "
                    "continuous-batching engine")
    ap.add_argument("--model", default="llama_tiny",
                    help="model-zoo config name (default: llama_tiny)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8821)
    ap.add_argument("--max-batch", type=int, default=4)
    ap.add_argument("--block-size", type=int, default=16)
    ap.add_argument("--num-blocks", type=int, default=256)
    ap.add_argument("--prefill-buckets", type=int, nargs="+",
                    default=[16])
    ap.add_argument("--aot-dir", default=None,
                    help="AOT artifact dir for a zero-compile warm "
                         "start (docs/aot.md)")
    ap.add_argument("--replicas", type=int, default=1,
                    help="EngineRouter fleet size (needs --aot-dir)")
    ap.add_argument("--max-queue-len", type=int, default=256)
    ap.add_argument("--stream-capacity", type=int, default=512)
    ap.add_argument("--drain-timeout-s", type=float, default=30.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)

    from ..observability import REGISTRY
    from ..observability.tracing import TRACER
    REGISTRY.enable()
    TRACER.enable()
    fe = _build_frontend(args)
    server = HttpServingServer(fe, host=args.host, port=args.port,
                               drain_timeout_s=args.drain_timeout_s)
    server.install_signal_handlers()
    server.start()
    print(json.dumps({"serving": f"http://{server.host}:{server.port}",
                      "model": args.model,
                      "replicas": args.replicas}))
    server._drain_done.wait()           # until SIGTERM/SIGINT drains
    report = dict(server._drain_report or {})
    print(json.dumps({"shutdown": report}))
    return 0 if report.get("kv_leaked_blocks", 1) == 0 else 1


if __name__ == "__main__":
    import sys
    sys.exit(main(sys.argv[1:]))
