"""Streaming serving front-end: a real request lifecycle over the
continuous-batching engine.

The engine (``inference/serving.py``) is a batch scheduler: results
appear when a request retires.  Production serving needs the opposite
shape — tokens the moment each ``engine.step()`` produces them, explicit
terminal states, deadlines, and a front door that says *no* under load
instead of queueing unboundedly.  This module adds exactly that layer,
host-side only (nothing here is traced):

Request lifecycle state machine::

    submit() ──► REJECTED                 admission control refused
       │
       ▼
    QUEUED ────► CANCELLED │ TIMED_OUT    cancel() / max_queue_time
       │
       ▼  engine schedules; prefill streams the first token
    RUNNING ───► CANCELLED │ TIMED_OUT    cancel() / deadline mid-decode
       │
       ▼
    FINISHED

* **Streaming delivery** — :meth:`ServingFrontend.submit` returns a
  :class:`RequestHandle`; iterating it yields tokens as they are
  produced.  With a ``stream_capacity`` and a background driver
  (:meth:`ServingFrontend.start`), a slow consumer backpressures the
  producer (bounded buffer, blocking push — tokens are never dropped or
  reordered); without a driver, iterating the handle drives the
  scheduler itself, so single-threaded use needs no thread at all.
* **Robust scheduling** — per-request ``deadline_s`` and
  ``max_queue_time_s`` expire requests in bounded time (a deadline hit
  mid-decode frees the engine slot and its refcounted KV pages within
  one scheduler iteration via ``engine.cancel``); ``cancel()`` works in
  both the waiting-queue and scheduled phases.
* **Admission control** — :class:`AdmissionConfig` rejects at submit
  when the waiting queue or the projected KV-block demand saturates,
  so overload degrades into fast ``REJECTED`` responses instead of
  unbounded memory growth.
* **Telemetry** — queue depth, batch occupancy, KV utilization,
  admission rejects, TTFT/per-token latency, and stream backpressure
  wait time via :class:`~paddle_tpu.serving.metrics.ServeMetrics`; the
  flight recorder (a registry sink) captures the serve event ring on
  any crash, and a driver-thread crash additionally dumps it explicitly
  and aborts every live stream so consumers never hang.
* **Speculative decoding** — construct the engine with
  ``spec_config=`` (``paddle_tpu/spec_decode``) and the front-end
  serves over the draft/verify decode loop unchanged: greedy streams
  stay bit-identical (pinned), multi-token commits arrive as ordinary
  per-step deliveries, and the ``serve.spec.*`` gauges ride
  :meth:`ServeMetrics.publish_engine`.
"""

from __future__ import annotations

import collections
import enum
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .metrics import ServeMetrics
from ..observability.tracing import TRACER

__all__ = ["AdmissionConfig", "RequestAborted", "RequestHandle",
           "RequestRejected", "RequestState", "ServingFrontend"]


class RequestState(enum.Enum):
    """Lifecycle states; exactly one terminal state per request."""

    QUEUED = "QUEUED"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    CANCELLED = "CANCELLED"
    TIMED_OUT = "TIMED_OUT"
    REJECTED = "REJECTED"

    @property
    def terminal(self) -> bool:
        return self in _TERMINAL


_TERMINAL = frozenset({RequestState.FINISHED, RequestState.CANCELLED,
                       RequestState.TIMED_OUT, RequestState.REJECTED})


class RequestError(RuntimeError):
    """Base for terminal-state errors raised by handles."""


class RequestRejected(RequestError):
    """Admission control refused the request at submit."""


class RequestAborted(RequestError):
    """The request ended CANCELLED or TIMED_OUT before finishing."""

    def __init__(self, state: RequestState, reason: Optional[str]):
        super().__init__(f"request {state.value}"
                         + (f": {reason}" if reason else ""))
        self.state = state
        self.reason = reason


@dataclass(frozen=True)
class AdmissionConfig:
    """Submit-time load shedding knobs.

    max_queue_len:
        Reject when this many accepted requests are still waiting for a
        decode slot (None = unbounded queue).
    max_queue_time_s:
        Default queue-time budget for every request (overridable per
        submit); a request that waits longer is shed as TIMED_OUT.
    kv_demand_factor:
        Reject when the summed page demand of all live requests plus
        the new one would exceed ``factor * num_blocks``.  Demand beyond
        1.0x is legitimate (requests queue for pages), but unbounded
        demand is how a traffic spike turns into an unbounded queue —
        2.0 is a reasonable production default.
    """

    max_queue_len: Optional[int] = 128
    max_queue_time_s: Optional[float] = None
    kv_demand_factor: Optional[float] = None


class RequestHandle:
    """One submitted request: stream, terminal state, and timings.

    Iterate to stream tokens (raises :class:`RequestAborted` /
    :class:`RequestRejected` on abnormal terminals); call
    :meth:`result` for the engine's full ``prompt + generated`` ids.
    Token ids delivered through the stream are exactly the ids the
    batch API returns — bit-identical, pinned by tests.
    """

    def __init__(self, frontend: "ServingFrontend", prompt: np.ndarray,
                 max_new_tokens: int, stream_capacity: Optional[int],
                 submit_t: float,
                 on_token: Optional[Callable] = None):
        self._fe = frontend
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.req_id: Optional[int] = None
        self.trace = None          # the request's Trace when tracing is on
        self.submit_t = submit_t
        self.first_token_t: Optional[float] = None
        self.finish_t: Optional[float] = None
        self.reason: Optional[str] = None
        self.on_token = on_token
        self.backpressure_wait_s = 0.0
        self._cap = stream_capacity
        self._cond = threading.Condition()
        self._tokens: List[int] = []
        self._cursor = 0
        self._state = RequestState.QUEUED
        self._result: Optional[np.ndarray] = None

    # -- public surface -------------------------------------------------
    @property
    def state(self) -> RequestState:
        return self._state

    @property
    def n_streamed(self) -> int:
        return len(self._tokens)

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    def tokens(self) -> List[int]:
        """Snapshot of every token streamed so far."""
        with self._cond:
            return list(self._tokens)

    def cancel(self) -> bool:
        """Abort this request (either phase).  Frees its engine slot and
        KV pages; tokens already streamed remain readable."""
        return self._fe.cancel(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block (or drive the scheduler, when no driver thread runs)
        until terminal; returns the full ``prompt + generated`` ids for
        FINISHED, raises :class:`RequestRejected` / :class:`
        RequestAborted` otherwise."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while True:
            with self._cond:
                # result() consumes the stream: a bounded buffer must
                # not backpressure a consumer that only wants the tail
                self._cursor = len(self._tokens)
                self._cond.notify_all()
                st = self._state
                if st is RequestState.FINISHED:
                    return self._result
                self._raise_if_aborted(st)
                if self._fe._driver_alive():
                    if deadline is not None \
                            and time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"request {self.req_id} still {st.value} "
                            f"after {timeout}s")
                    self._cond.wait(0.05)
                    continue
            if deadline is not None and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"request {self.req_id} still {self._state.value} "
                    f"after {timeout}s")
            self._fe.step()

    def __iter__(self) -> "RequestHandle":
        return self

    def __next__(self) -> int:
        while True:
            with self._cond:
                if self._cursor < len(self._tokens):
                    tok = self._tokens[self._cursor]
                    self._cursor += 1
                    self._cond.notify_all()    # wake a blocked producer
                    return tok
                st = self._state
                if st is RequestState.FINISHED:
                    raise StopIteration
                self._raise_if_aborted(st)
                if self._fe._driver_alive():
                    self._cond.wait(0.05)
                    continue
            # no driver thread: the consumer IS the scheduler
            self._fe.step()

    def stream_from(self, start: int = 0, *, poll_s: float = 0.05,
                    idle_cb: Optional[Callable[[], None]] = None):
        """Yield ``(index, token)`` pairs beginning at stream index
        ``start`` — the re-attachable consumer surface the HTTP/SSE wire
        is built on (``serving/http.py``): a retried connection replays
        the committed prefix from index 0 and then continues live,
        instead of double-submitting the request.

        Unlike ``__next__`` (one shared cursor), the caller owns the
        position; the shared backpressure cursor only ever advances
        (``max``), so an attached replay can never re-arm backpressure
        for tokens the producer already delivered.  Tokens are grabbed
        a chunk at a time and yielded OUTSIDE the handle lock — a slow
        socket write never blocks the delivering driver thread.

        ``idle_cb`` runs (outside the lock) roughly every ``poll_s``
        while no new token is available — the wire uses it for SSE
        heartbeats, which is also how a dead client socket is noticed
        while the stream is idle.  Terminates when the request reaches
        a terminal state (raising like ``__next__`` for abnormal
        terminals once every committed token has been yielded)."""
        i = start
        while True:
            chunk: List[int] = []
            st = None
            with self._cond:
                if i < len(self._tokens):
                    chunk = self._tokens[i:]
                    if len(self._tokens) > self._cursor:
                        self._cursor = len(self._tokens)
                        self._cond.notify_all()
                else:
                    st = self._state
                    if st is RequestState.FINISHED:
                        return
                    if st in _TERMINAL:
                        self._raise_if_aborted(st)
                    if self._fe._driver_alive():
                        self._cond.wait(poll_s)
            if chunk:
                for tok in chunk:
                    yield i, tok
                    i += 1
                continue
            if idle_cb is not None:
                idle_cb()
            if st is not None and not self._fe._driver_alive():
                # no driver thread: the consumer IS the scheduler
                self._fe.step()

    def __repr__(self) -> str:
        return (f"RequestHandle(id={self.req_id}, "
                f"state={self._state.value}, "
                f"streamed={len(self._tokens)})")

    # -- frontend-internal ----------------------------------------------
    def _raise_if_aborted(self, st: RequestState) -> None:
        if st is RequestState.REJECTED:
            raise RequestRejected(self.reason or "rejected")
        if st in (RequestState.CANCELLED, RequestState.TIMED_OUT):
            raise RequestAborted(st, self.reason)

    def _deliver_tokens(self, toks: List[int], *, block: bool,
                        timeout: float) -> float:
        """Append tokens to the stream in order.  When ``block`` (a
        driver thread is delivering), a full bounded buffer makes the
        producer WAIT for the consumer — backpressure, never dropping:
        on timeout the token is appended anyway (the buffer degrades to
        elastic rather than losing data).  Returns seconds waited."""
        waited = 0.0
        delivered: List[int] = []
        with self._cond:
            if self._state is RequestState.QUEUED:
                self._state = RequestState.RUNNING
            for t in toks:
                if block and self._cap is not None:
                    t0 = time.monotonic()
                    while (len(self._tokens) - self._cursor >= self._cap
                           and self._state is RequestState.RUNNING
                           and time.monotonic() - t0 < timeout):
                        self._cond.wait(0.02)
                    waited += time.monotonic() - t0
                if self._state is not RequestState.RUNNING:
                    break          # aborted mid-delivery: stop streaming
                self._tokens.append(t)
                delivered.append(t)
                self._cond.notify_all()
            # accumulate inside the cond: metrics_snapshot reads this
            # from whatever thread scrapes it, and += is two racy ops
            self.backpressure_wait_s += waited
        if self.on_token is not None:
            for t in delivered:
                self.on_token(self, t)
        return waited

    def _finish(self, state: RequestState, *,
                result: Optional[np.ndarray] = None,
                reason: Optional[str] = None,
                now: Optional[float] = None) -> bool:
        with self._cond:
            if self._state in _TERMINAL:
                return False
            self._state = state
            self._result = result
            self.reason = reason
            self.finish_t = now
            self._cond.notify_all()
        return True


@dataclass
class _Record:
    """Frontend-side bookkeeping for one live (non-terminal) request."""

    handle: RequestHandle
    req: object                       # engine GenRequest
    blocks: int                       # projected page demand
    deadline_t: Optional[float]
    queue_deadline_t: Optional[float]
    delivered: int = 0
    last_token_t: Optional[float] = None
    done: bool = False


@dataclass
class _Delivery:
    """Deferred handle mutation, applied OUTSIDE the scheduler lock so a
    backpressured (blocking) push can never deadlock against submit()/
    cancel() calls from consumer threads."""

    rec: _Record
    toks: List[int] = field(default_factory=list)
    state: Optional[RequestState] = None
    result: Optional[np.ndarray] = None
    reason: Optional[str] = None
    now: float = 0.0


_UNSET = object()


class ServingFrontend:
    """Request-lifecycle front door over a ``ContinuousBatchingEngine``.

    Args:
      engine: the continuous-batching engine (owned by this frontend —
        calling ``engine.step()`` elsewhere while a frontend is live
        would race the scheduler).
      admission: :class:`AdmissionConfig` load-shedding knobs.
      clock: monotonic-seconds source for deadlines/TTFT.  Injectable so
        tests and simulations control time; stream-buffer waits always
        use real ``time.monotonic``.
      default_deadline_s: deadline applied when submit passes none.
      stream_capacity: default per-handle stream buffer bound (None =
        sized by ``max_new_tokens``, i.e. no backpressure).
      backpressure_timeout_s: longest a delivery blocks on a full buffer
        before degrading to elastic buffering.
      registry: metrics registry (defaults to the process ``REGISTRY``).

    Drive it one of two ways: call :meth:`step` / :meth:`run_until_drained`
    from your own loop (deterministic, test-friendly), or
    :meth:`start` a background driver thread and consume handles from
    other threads (streaming with backpressure).
    """

    def __init__(self, engine, *, admission: Optional[AdmissionConfig] = None,
                 clock: Callable[[], float] = time.monotonic,
                 default_deadline_s: Optional[float] = None,
                 stream_capacity: Optional[int] = None,
                 backpressure_timeout_s: float = 60.0,
                 registry=None):
        self.engine = engine
        self.admission = admission or AdmissionConfig()
        self.metrics = ServeMetrics(registry)
        self.error: Optional[BaseException] = None
        self._clock = clock
        self._default_deadline = default_deadline_s
        self._cap = stream_capacity
        self._bp_timeout = backpressure_timeout_s
        self._lock = threading.RLock()
        self._recs: "collections.OrderedDict[int, _Record]" = \
            collections.OrderedDict()
        self._driver: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------------
    # submit / cancel
    # ------------------------------------------------------------------
    def submit(self, prompt_ids, max_new_tokens: int, *,
               eos_token_id: Optional[int] = None,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None, seed: int = 0, n: int = 1,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               max_queue_time_s: Optional[float] = None,
               stream_capacity=_UNSET,
               on_token: Optional[Callable] = None) -> RequestHandle:
        """Admit one request.  Never raises for load reasons — an
        over-capacity submit returns a handle already in REJECTED (the
        caller's fast-fail signal); genuinely malformed requests
        (empty prompt, zero budget) still raise ``ValueError``.

        ``n > 1`` (ROADMAP 5(b)) fans the request out to n parallel
        samples sharing ONE prompt KV: every sample is an ordinary
        engine request whose prompt pages are refcount-shared through
        the cross-request prefix cache (the first sample prefills and
        registers, the rest claim the cached pages — zero new compiled
        programs, the sampler is already padded per geometry), and each
        streams on its own PRNG stream keyed (seed, sample_idx,
        absolute position) via
        :func:`~paddle_tpu.inference.serving.derive_sample_seed`.
        Returns a LIST of n handles (bit-identical to n independent
        submits carrying the derived seeds — pinned by
        tests/test_prefix_cache.py)."""
        if n < 1:
            raise ValueError("n must be >= 1")
        if n > 1:
            if temperature is None or temperature <= 0.0:
                raise ValueError(
                    "n > 1 parallel sampling needs temperature > 0 — "
                    "n greedy samples of one prompt are n identical "
                    "streams")
            from ..inference.serving import derive_sample_seed
            return [self.submit(
                prompt_ids, max_new_tokens, eos_token_id=eos_token_id,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=derive_sample_seed(seed, i), priority=priority,
                deadline_s=deadline_s, max_queue_time_s=max_queue_time_s,
                stream_capacity=stream_capacity, on_token=on_token)
                for i in range(n)]
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        cap = self._cap if stream_capacity is _UNSET else stream_capacity
        with self._lock:
            now = self._clock()
            handle = RequestHandle(self, prompt, max_new_tokens, cap,
                                   now, on_token)
            # request tracing (ISSUE 20): open the trace here — the
            # outermost serve layer below the wire — and activate it
            # around add_request so router/supervisor/engine spans land
            # on it with no signature changes
            tr = TRACER.begin(prompt_tokens=int(len(prompt)),
                              max_new_tokens=int(max_new_tokens),
                              priority=int(priority)) \
                if TRACER.enabled else None
            reason = self._admission_reason(prompt, max_new_tokens)
            rid = None
            if reason is None:
                try:
                    with TRACER.activating(tr):
                        rid = self.engine.add_request(
                            prompt, max_new_tokens, eos_token_id,
                            temperature=temperature, top_k=top_k,
                            top_p=top_p, seed=seed, priority=priority)
                except ValueError as e:
                    if len(prompt) < 1 or max_new_tokens < 1:
                        raise                      # malformed, not load
                    reason = str(e)                # could never admit
            if reason is not None:
                handle._finish(RequestState.REJECTED, reason=reason,
                               now=now)
                self.metrics.on_reject(reason)
                if tr is not None:
                    TRACER.finish(tr, "REJECTED", reason=reason,
                                  registry=self.metrics.registry)
                return handle
            handle.req_id = rid
            if tr is not None:
                TRACER.bind(tr, rid)
                handle.trace = tr
            req = next(r for r in reversed(self.engine.queue)
                       if r.req_id == rid)
            ddl = deadline_s if deadline_s is not None \
                else self._default_deadline
            mqt = max_queue_time_s if max_queue_time_s is not None \
                else self.admission.max_queue_time_s
            self._recs[rid] = _Record(
                handle=handle, req=req,
                blocks=self.engine._blocks_needed(
                    len(prompt) + max_new_tokens),
                deadline_t=None if ddl is None else now + ddl,
                queue_deadline_t=None if mqt is None else now + mqt)
            self.metrics.on_submit(rid, len(prompt), max_new_tokens)
            self._publish()
            return handle

    def cancel(self, handle: RequestHandle,
               reason: str = "cancelled by client") -> bool:
        """Abort a live request in either phase; frees its engine slot
        and refcounted KV pages immediately.  False when already
        terminal (idempotent)."""
        with self._lock:
            rid = handle.req_id
            rec = None if rid is None else self._recs.get(rid)
            if rec is None or rec.done or handle.state.terminal:
                return False
            self.engine.cancel(rid)
            rec.done = True
            del self._recs[rid]
            now = self._clock()
            self.metrics.on_cancel(rid)
            self._publish()
        handle._finish(RequestState.CANCELLED, reason=reason, now=now)
        self._finish_trace(handle.trace, "CANCELLED", handle.n_streamed,
                           reason=reason)
        return True

    # ------------------------------------------------------------------
    # scheduler pump
    # ------------------------------------------------------------------
    def step(self) -> bool:
        """One scheduler iteration: expire deadlines, advance the
        engine, stream newly produced tokens, publish gauges.  Returns
        True while live requests remain."""
        deliveries: List[_Delivery] = []
        with self._lock:
            now = self._clock()
            self._expire(now, deliveries)
            try:
                # The scheduler lock IS the engine serialization point:
                # step() mutates engine batch state, and every other
                # engine touch (submit's admission, drain) already goes
                # through _lock.  Callers never block on _lock for the
                # step duration — they use the handle condvars.
                finished = self.engine.step()  # locklint: disable=LK002
            except BaseException as e:
                self._crash(e)
                raise
            now = self._clock()
            for rid, rec in list(self._recs.items()):
                out = rec.req.out
                n = len(out)
                d = _Delivery(rec, now=now)
                if n > rec.delivered:
                    d.toks = list(out[rec.delivered:n])
                    if rec.delivered == 0:
                        rec.handle.first_token_t = now
                        self.metrics.on_first_token(
                            rid, now - rec.handle.submit_t)
                        tr = rec.handle.trace
                        if tr is not None:
                            # trace-relative TTFT: the window split
                            # attribution() cuts the timeline at
                            tr.meta["ttft_s"] = tr.now()
                            tr.event("first_token")
                        if len(d.toks) > 1:
                            self.metrics.on_tokens(len(d.toks) - 1, 0.0)
                    else:
                        self.metrics.on_tokens(
                            len(d.toks),
                            (now - rec.last_token_t) / len(d.toks))
                    rec.last_token_t = now
                    rec.delivered = n
                if rid in finished:
                    rec.done = True
                    del self._recs[rid]
                    d.state = RequestState.FINISHED
                    d.result = finished[rid]
                    self.metrics.on_finish(
                        rid, now - rec.handle.submit_t, n)
                    self._finish_trace(rec.handle.trace, "FINISHED", n)
                if d.toks or d.state is not None:
                    deliveries.append(d)
            self._publish()
            pending = bool(self._recs)
        self._apply(deliveries)
        return pending

    def run_until_drained(self, timeout_s: Optional[float] = None) -> None:
        """Pump (or wait on the driver) until no live requests remain."""
        t0 = time.monotonic()
        while True:
            with self._lock:
                pending = bool(self._recs)
            if not pending:
                return
            if timeout_s is not None \
                    and time.monotonic() - t0 > timeout_s:
                raise TimeoutError(
                    f"requests still live after {timeout_s}s")
            if self._driver_alive():
                time.sleep(0.01)
            else:
                self.step()

    # ------------------------------------------------------------------
    # background driver
    # ------------------------------------------------------------------
    def start(self) -> "ServingFrontend":
        """Run the scheduler on a daemon thread; handles then stream
        with real backpressure.  Idempotent."""
        with self._lock:
            if self._driver is not None and self._driver.is_alive():
                return self
            self._stop.clear()
            self._driver = threading.Thread(
                target=self._drive, name="serving-frontend", daemon=True)
            self._driver.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t = self._driver
        if t is not None and t is not threading.current_thread():
            t.join(timeout=30.0)
        self._driver = None

    def close(self, cancel_pending: bool = True) -> None:
        """Stop the driver and (by default) abort anything still live,
        so no consumer blocks forever on a dead frontend."""
        self.stop()
        if cancel_pending:
            with self._lock:
                handles = [r.handle for r in self._recs.values()]
            for h in handles:
                self.cancel(h, reason="frontend closed")

    def _driver_alive(self) -> bool:
        t = self._driver
        return (t is not None and t.is_alive()
                and t is not threading.current_thread())

    def _drive(self) -> None:
        try:
            while not self._stop.is_set():
                with self._lock:
                    pending = bool(self._recs)
                if pending:
                    self.step()
                else:
                    self._stop.wait(0.002)
        except BaseException as e:
            # engine failures already ran _crash() inside step(); any
            # other failure (delivery callback, expiry logic) must
            # still abort live streams so consumers don't hang
            if self.error is None:
                self._crash(e)
            return

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _admission_reason(self, prompt: np.ndarray,
                          max_new_tokens: int) -> Optional[str]:
        adm = self.admission
        if adm.max_queue_len is not None:
            waiting = sum(1 for r in self._recs.values()
                          if len(r.req.out) == 0)
            if waiting >= adm.max_queue_len:
                return (f"queue full: {waiting} waiting >= "
                        f"max_queue_len={adm.max_queue_len}")
        if adm.kv_demand_factor is not None:
            need = self.engine._blocks_needed(
                len(prompt) + max_new_tokens)
            outstanding = sum(r.blocks for r in self._recs.values())
            cap = adm.kv_demand_factor * self.engine.alloc.num_blocks
            if outstanding + need > cap:
                return (f"kv pool saturated: demand {outstanding}+{need} "
                        f"blocks > {adm.kv_demand_factor:g}x pool "
                        f"({self.engine.alloc.num_blocks})")
        return None

    def _expire(self, now: float, deliveries: List[_Delivery]) -> None:
        """Shed queue-time and deadline violators BEFORE the engine
        step, so an expired request never occupies (or takes) a slot
        this iteration — expiry-to-free latency is bounded by one
        scheduler iteration."""
        for rid, rec in list(self._recs.items()):
            phase = None
            if rec.deadline_t is not None and now >= rec.deadline_t:
                phase = "deadline"
            elif (rec.queue_deadline_t is not None
                  and now >= rec.queue_deadline_t
                  and len(rec.req.out) == 0):
                phase = "max_queue_time"
            if phase is None:
                continue
            self.engine.cancel(rid)
            rec.done = True
            del self._recs[rid]
            toks = list(rec.req.out[rec.delivered:])
            rec.delivered = len(rec.req.out)
            deliveries.append(_Delivery(
                rec, toks=toks, state=RequestState.TIMED_OUT,
                reason=phase, now=now))
            self.metrics.on_timeout(rid, phase)
            self._finish_trace(rec.handle.trace, "TIMED_OUT",
                               len(rec.req.out), reason=phase)

    def _apply(self, deliveries: List[_Delivery]) -> None:
        block = threading.current_thread() is self._driver
        for d in deliveries:
            h = d.rec.handle
            if d.toks:
                waited = h._deliver_tokens(d.toks, block=block,
                                           timeout=self._bp_timeout)
                if waited > 0.0:
                    self.metrics.on_backpressure(waited)
            if d.state is not None:
                h._finish(d.state, result=d.result, reason=d.reason,
                          now=d.now)

    def _publish(self) -> None:
        self.metrics.publish_engine(self.engine)

    def _finish_trace(self, tr, state: str, n_tokens: int = 0, *,
                      reason: Optional[str] = None, **meta) -> None:
        """Close a request trace on its terminal state: stamp the token
        count and derived TPOT (decode seconds per post-first token),
        then hand it to the tracer — which emits the span tree as a
        ``trace`` exemplar event (FlightRecorder-visible) when the
        request missed its SLO or ended abnormally."""
        if tr is None:
            return
        ttft = tr.meta.get("ttft_s")
        if ttft is not None and n_tokens > 1:
            meta.setdefault("tpot_s",
                            (tr.now() - ttft) / (n_tokens - 1))
        if reason is not None:
            meta.setdefault("reason", reason)
        meta.setdefault("n_tokens", int(n_tokens))
        TRACER.finish(tr, state, registry=self.metrics.registry, **meta)

    def _crash(self, exc: BaseException) -> None:
        """Engine-step failure: record, dump the serve ring for
        post-mortem, and abort every live stream so consumers get a
        terminal state instead of hanging."""
        with self._lock:       # re-entrant from step(); health_snapshot
            self.error = exc   # reads error from other threads
        self.metrics.event("crash",
                           error=f"{type(exc).__name__}: {exc}")
        with self._lock:
            recs = list(self._recs.values())
            self._recs.clear()
        # close live traces FIRST: their span trees ride the ring as
        # ``trace`` exemplar events, so the dump below is a post-mortem
        # with timelines, not just counters
        for rec in recs:
            self._finish_trace(
                rec.handle.trace, "CANCELLED", len(rec.req.out),
                reason=f"frontend crashed: {type(exc).__name__}: {exc}",
                crash=True)
        try:
            from ..observability.flight_recorder import FlightRecorder
            for sink in self.metrics.registry.sinks:
                if isinstance(sink, FlightRecorder) \
                        and sink.directory is not None:
                    sink.dump(f"serving-frontend crash: "
                              f"{type(exc).__name__}: {exc}")
        except Exception as dump_err:   # the dump must not mask exc
            self.metrics.event("crash_dump_failed", error=str(dump_err))
        now = self._clock()
        for rec in recs:
            rec.done = True
            rec.handle._finish(
                RequestState.CANCELLED,
                reason=f"frontend crashed: {type(exc).__name__}: {exc}",
                now=now)

    # -- introspection --------------------------------------------------
    @property
    def live_requests(self) -> int:
        return len(self._recs)

    def __enter__(self) -> "ServingFrontend":
        return self

    def __exit__(self, *exc_info) -> None:
        self.close()
