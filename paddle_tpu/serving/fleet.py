"""Fleet-level serving (ISSUE 12): a health-checked multi-replica
router over N data-parallel supervised engines.

One :class:`~paddle_tpu.serving.resilience.SupervisedEngine` behind a
front-end is still a single point of failure: an exhausted circuit
breaker aborts every live stream, and one saturated engine sheds load
the fleet could absorb.  :class:`EngineRouter` fronts N replicas behind
ONE admission view and duck-types the engine surface
``ServingFrontend`` drives, so the whole existing front-end / loadgen /
resilience stack works unchanged at fleet scale::

    factory = aot.serve.warm_engine_factory(cfg, params, aot_dir=root,
                                            max_batch=4, num_blocks=256)
    router = EngineRouter([factory] * 4)          # 4 warm replicas
    fe = ServingFrontend(router)                  # unchanged

* **Placement** is prefix-affine, KV-aware least-loaded: a request
  whose prompt shares a cached prefix routes to the replica whose
  radix tree already holds it (deepest match wins, bounded by an
  anti-herd cap — ISSUE 14); otherwise, among replicas whose health
  admits traffic, the one with the least (queue + running) work wins,
  KV-pool utilization breaking ties.  The router-level
  :class:`~paddle_tpu.serving.frontend.AdmissionConfig` rejects only
  when NO healthy replica can admit.
* **Health states** per replica::

      HEALTHY ──crash/transient──► DEGRADED ──clean steps──► HEALTHY
         │                            │
         ├────────── drain() ─────────┤──────► DRAINING ──► DEAD
         │                            │                      ▲
         └── RecoveryExhaustedError ──┴──────────────────────┘

  DEGRADED replicas keep serving but receive new work only when no
  HEALTHY replica can admit.  A replica whose supervisor escalates
  (:class:`RecoveryExhaustedError` — circuit breaker open or a rebuild
  factory failure) is DEAD: every live request on it is **re-placed**
  onto a healthy replica and replayed from its committed token prefix,
  so consumers see one gap-free bit-identical stream (greedy, sampled,
  and mid-speculation — pinned by tests/test_serving_fleet.py).  Only
  when the LAST replica dies does the router raise
  :class:`FleetExhaustedError`, landing in the front-end's existing
  typed abort-all path.
* **Graceful drain** (:meth:`EngineRouter.drain`) for rolling
  restarts: placement stops, live requests are spilled (their
  CRC-checked KV page bytes are replica-agnostic, so the target
  restores them into fresh blocks without recompute) or run out, the
  spilled ones are re-placed, and only then is the replica torn down —
  with its final KV-leak report recorded (must be zero).
* **Rebalancing**: a request waiting (queued or preempted-and-spilled)
  on a replica that cannot admit it migrates to a replica that can —
  cross-replica re-placement of preempted/spilled requests (ROADMAP
  2(b)), snapshot transplanted when present.
* **Zero compiles at fleet scale**: build every replica from the same
  AOT artifact generation via ``aot.serve.warm_engine_factory`` —
  fleet cold-start, crash rebuilds, AND re-placement prefills all run
  deserialized programs (the ``fleet_warm`` COMPILE_BUDGET.md row pins
  this at ZERO backend compiles).
* **Telemetry**: the ``serve.fleet.*`` family rolls per-replica
  ``serve.*`` state into fleet gauges plus re-placement / drain /
  death counters, all riding the flight ring (docs/serving.md).

Drive the router from one thread (or behind ``ServingFrontend``, whose
lock serializes submit/cancel/step) — like the engine it wraps, it is
a scheduler, not a server.
"""

from __future__ import annotations

import collections
import enum
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..inference.serving import GenRequest
from ..observability import REGISTRY
from ..observability.tracing import TRACER
from .frontend import AdmissionConfig
from .resilience import (PortableRequest, RecoveryExhaustedError,
                         ResilienceError, RetryPolicy, SupervisedEngine)

__all__ = ["EngineRouter", "FleetExhaustedError", "ReplicaState"]


class FleetExhaustedError(ResilienceError):
    """Every replica in the fleet is DEAD while live requests remain.
    Escalates to the front-end's typed abort-all path — the fleet
    analogue of a single supervisor's circuit breaker opening."""


class ReplicaState(enum.Enum):
    HEALTHY = "HEALTHY"
    DEGRADED = "DEGRADED"
    DRAINING = "DRAINING"
    DEAD = "DEAD"


@dataclass
class _Replica:
    idx: int
    sup: Optional[SupervisedEngine]
    state: ReplicaState = ReplicaState.HEALTHY
    reason: Optional[str] = None
    clean_steps: int = 0
    last_crashes: int = 0            # sup crash+transient counter snapshot
    final_leak: Optional[Dict[str, int]] = None

    @property
    def live(self) -> bool:
        return self.state is not ReplicaState.DEAD and self.sup is not None


@dataclass
class _Placement:
    """Router bookkeeping for one live request.  ``req`` is the
    router-owned outer ``GenRequest`` — the object the front-end
    streams from; it survives every re-placement.  ``obj`` is the
    current replica's tracked request, ``base`` the length offset
    between the two token lists (``req.out == req.out[:base] +
    obj.out`` at all times)."""

    req: GenRequest
    kwargs: Dict[str, object]
    max_new: int
    priority: int
    blocks: int
    replica: int
    sid: int
    obj: GenRequest
    base: int
    moves: int = 0


class EngineRouter:
    """N data-parallel supervised replicas behind one admission view.

    Args:
      factories: zero-arg engine factories, one per replica (pass the
        same ``warm_engine_factory`` N times for a homogeneous fleet —
        replicas must share pool geometry for snapshot re-placement).
        Each is wrapped in a :class:`SupervisedEngine`, so intra-replica
        faults (transient retries, crash rebuild + replay) never reach
        the router; only an exhausted replica escalates here.
      policy: per-replica :class:`RetryPolicy`.
      admission: router-level :class:`AdmissionConfig`, applied PER
        replica — a submit is rejected only when NO healthy (then
        degraded) replica passes it.
      heal_after_steps: consecutive clean supervised steps before a
        DEGRADED replica is HEALTHY again.
      prefix_affinity: route a request sharing a cached prefix to the
        replica already holding it (ISSUE 14): placement consults each
        candidate's radix-tree summary (``prefix_match_blocks`` over
        the request's chained block digests) and the deepest match
        wins, least-loaded as tiebreak — a cache hit skips the shared
        prefix's prefill entirely, so affinity beats raw load balance
        whenever a prefix is actually cached.
      affinity_load_slack: the anti-herd cap — the affinity replica is
        taken only while its outstanding work (queue + running) exceeds
        the least-loaded candidate's by at most this many requests;
        past the cap the load balancer wins (counter
        ``affinity_capped``), so a popular system prompt can never
        starve the fleet onto one replica.
      registry / clock / sleep: forwarded to each supervisor.
    """

    def __init__(self, factories: Sequence[Callable[[], object]], *,
                 policy: Optional[RetryPolicy] = None,
                 admission: Optional[AdmissionConfig] = None,
                 heal_after_steps: int = 8, prefix_affinity: bool = True,
                 affinity_load_slack: int = 2, registry=None,
                 clock=None, sleep=None):
        if not factories:
            raise ValueError("EngineRouter needs at least one replica "
                             "factory")
        self.policy = policy
        self.admission = admission or AdmissionConfig()
        self.heal_after_steps = int(heal_after_steps)
        self.prefix_affinity = bool(prefix_affinity)
        self.affinity_load_slack = int(affinity_load_slack)
        self._reg = REGISTRY if registry is None else registry
        self._sup_kwargs = {}
        if clock is not None:
            self._sup_kwargs["clock"] = clock
        if sleep is not None:
            self._sup_kwargs["sleep"] = sleep
        self._replicas: List[_Replica] = []
        for f in factories:
            self._add_replica(f)
        # one fleet, one geometry: page math must keep working even
        # with every replica dead (re-placement decides typed-abort vs
        # strand based on it)
        self._block_size = int(self._replicas[0].sup.engine.BS)
        self._next_id = 0
        self._placements: "collections.OrderedDict[int, _Placement]" = \
            collections.OrderedDict()
        self._by_sid: Dict[tuple, int] = {}      # (replica, sid) -> rid
        self._pending_finished: Dict[int, np.ndarray] = {}
        self._final_replica: Dict[int, int] = {}
        self.stats: Dict[str, int] = {
            "placements": 0, "replacements": 0, "rebalanced": 0,
            "snapshot_migrations": 0, "deaths": 0, "drains": 0,
            "synthesized": 0, "affinity_hits": 0, "affinity_capped": 0,
        }

    # ------------------------------------------------------------------
    # replica lifecycle
    # ------------------------------------------------------------------
    def _add_replica(self, factory: Callable[[], object]) -> _Replica:
        sup = SupervisedEngine(factory, policy=self.policy,
                               registry=self._reg, **self._sup_kwargs)
        rep = _Replica(idx=len(self._replicas), sup=sup)
        self._replicas.append(rep)
        return rep

    def add_replica(self, factory: Callable[[], object]) -> int:
        """Grow the fleet by one replica (the second half of a rolling
        restart: drain the old, add the new).  Returns its index."""
        rep = self._add_replica(factory)
        self._event("replica_added", replica=rep.idx)
        return rep.idx

    @property
    def replicas(self) -> List[_Replica]:
        return list(self._replicas)

    def replica_state(self, idx: int) -> ReplicaState:
        return self._replicas[idx].state

    def _live(self) -> List[_Replica]:
        return [r for r in self._replicas if r.live]

    def _placeable(self) -> List[_Replica]:
        """Replicas that may receive NEW work, healthiest tier first."""
        healthy = [r for r in self._replicas
                   if r.live and r.state is ReplicaState.HEALTHY]
        degraded = [r for r in self._replicas
                    if r.live and r.state is ReplicaState.DEGRADED]
        return healthy + degraded

    def placeable(self) -> bool:
        """Readiness predicate: can the fleet accept NEW work right now
        — is at least one replica HEALTHY or DEGRADED?  DRAINING and
        DEAD replicas keep existing streams alive but take no new
        placements, so a fleet of only those is not ready.  This is the
        load-balancer answer ``GET /readyz`` (serving/http.py) serves."""
        return bool(self._placeable())

    def health_census(self) -> Dict[str, int]:
        """Structured replica-health counts, one key per
        :class:`ReplicaState` value (``HEALTHY`` / ``DEGRADED`` /
        ``DRAINING`` / ``DEAD``) plus ``total`` — the readiness and
        metrics endpoints read fleet state through this instead of
        poking ``_replicas``."""
        census = {s.value: 0 for s in ReplicaState}
        for r in self._replicas:
            census[r.state.value] += 1
        census["total"] = len(self._replicas)
        return census

    # ------------------------------------------------------------------
    # placement
    # ------------------------------------------------------------------
    def _outstanding_blocks(self, idx: int) -> int:
        return sum(p.blocks for p in self._placements.values()
                   if p.replica == idx)

    def _replica_admits(self, rep: _Replica, need: int) -> bool:
        """The router-level admission view, applied to ONE replica: the
        fleet rejects only when this fails for every placeable
        replica."""
        eng = rep.sup
        if need > eng.alloc.num_blocks:
            return False                       # could never admit here
        adm = self.admission
        if adm.max_queue_len is not None \
                and eng.queue_depth >= adm.max_queue_len:
            return False
        if adm.kv_demand_factor is not None:
            cap = adm.kv_demand_factor * eng.alloc.num_blocks
            if self._outstanding_blocks(rep.idx) + need > cap:
                return False
        return True

    def _load_key(self, rep: _Replica):
        """KV-aware least-loaded order: outstanding work first, pool
        pressure second, index for determinism."""
        eng = rep.sup
        return (eng.queue_depth + eng.active_requests,
                round(eng.kv_utilization(), 6), rep.idx)

    def _prefix_keys(self, prompt: np.ndarray) -> Optional[List[bytes]]:
        """The request's chained block digests (computed ONCE per
        placement; every replica summary is consulted with the same
        list), or None when affinity is off / the prompt spans no full
        block."""
        if not self.prefix_affinity:
            return None
        from .prefix_cache import block_keys
        full = len(prompt) // self._block_size
        lookup = full - 1 if full and len(prompt) % self._block_size == 0 \
            else full
        if lookup <= 0:
            return None
        return block_keys(prompt, lookup, self._block_size)

    def _pick_replica(self, need: int, exclude: Optional[int] = None,
                      prefix_keys: Optional[List[bytes]] = None
                      ) -> Optional[_Replica]:
        """Least-loaded admitting replica, HEALTHY tier strictly before
        DEGRADED — degraded replicas take new work only as overflow.
        With ``prefix_keys``, prefix affinity runs first within the
        tier: the deepest cached-chain match wins (least-loaded
        tiebreak) unless the anti-herd cap says the affinity target is
        already ``affinity_load_slack`` requests busier than the
        least-loaded candidate — then load balance wins."""
        for state in (ReplicaState.HEALTHY, ReplicaState.DEGRADED):
            cands = [r for r in self._replicas
                     if r.live and r.state is state and r.idx != exclude
                     and self._replica_admits(r, need)]
            if not cands:
                continue
            best = min(cands, key=self._load_key)
            why, chosen, depth = "least_loaded", best, 0
            if prefix_keys:
                matched = [(r.sup.prefix_match_blocks(prefix_keys), r)
                           for r in cands]
                aff = [(m, r) for m, r in matched if m > 0]
                if aff:
                    m, target = min(
                        aff, key=lambda t: (-t[0],) + self._load_key(t[1]))
                    t_load = (target.sup.queue_depth
                              + target.sup.active_requests)
                    b_load = best.sup.queue_depth + best.sup.active_requests
                    if target is best or \
                            t_load <= b_load + self.affinity_load_slack:
                        self.stats["affinity_hits"] += 1
                        if self._reg.enabled:
                            self._reg.counter(
                                "serve.fleet.affinity_hits_total").inc()
                        why, chosen, depth = "affinity_hit", target, m
                    else:
                        self.stats["affinity_capped"] += 1
                        if self._reg.enabled:
                            self._reg.counter(
                                "serve.fleet.affinity_capped_total").inc()
                        why, depth = "affinity_capped", m
            if TRACER.enabled:
                # request tracing (ISSUE 20): the placement decision —
                # replica chosen and WHY — as an instant on the ambient
                # trace (active during submit and re-placement)
                tr = TRACER.current()
                if tr is not None:
                    tr.event("placement", replica=chosen.idx, why=why,
                             tier=state.value, matched_blocks=depth)
            return chosen
        return None

    def add_request(self, prompt_ids, max_new_tokens: int,
                    eos_token_id: Optional[int] = None, *,
                    temperature: float = 0.0,
                    top_k: Optional[int] = None,
                    top_p: Optional[float] = None,
                    seed: int = 0, priority: int = 0) -> int:
        """Place one request on the least-loaded admitting replica.
        Raises ``ValueError`` when no healthy replica can admit (the
        front-end turns that into a typed REJECTED handle), or for a
        genuinely malformed request."""
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if not self._live():
            raise ValueError("no live replica in the fleet")
        need = self._blocks_needed(len(prompt) + max_new_tokens)
        rep = self._pick_replica(need,
                                 prefix_keys=self._prefix_keys(prompt))
        if rep is None:
            raise ValueError(
                f"no healthy replica can admit: demand {need} blocks "
                f"across {len(self._placeable())} placeable replica(s) "
                f"(fleet admission {self.admission})")
        kwargs = {"eos_token_id": eos_token_id, "temperature": temperature,
                  "top_k": top_k, "top_p": top_p, "seed": seed}
        sid = rep.sup.add_request(
            prompt, max_new_tokens, eos_token_id,
            temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed, priority=priority)
        obj = rep.sup.tracked_request(sid)
        rid = self._next_id
        self._next_id += 1
        outer = GenRequest(rid, prompt, max_new_tokens, eos_token_id,
                           temperature=temperature, top_k=top_k,
                           top_p=top_p, seed=seed, priority=int(priority))
        self._placements[rid] = _Placement(
            req=outer, kwargs=kwargs, max_new=int(max_new_tokens),
            priority=int(priority), blocks=need, replica=rep.idx,
            sid=sid, obj=obj, base=0)
        self._by_sid[(rep.idx, sid)] = rid
        self.stats["placements"] += 1
        if self._reg.enabled:
            self._reg.counter("serve.fleet.placements_total").inc()
        return rid

    def cancel(self, req_id: int) -> bool:
        if self._pending_finished.pop(req_id, None) is not None:
            return True
        p = self._placements.pop(req_id, None)
        if p is None:
            return False
        del self._by_sid[(p.replica, p.sid)]
        self._final_replica[req_id] = p.replica
        rep = self._replicas[p.replica]
        if rep.live:
            rep.sup.cancel(p.sid)
        return True

    # ------------------------------------------------------------------
    # scheduling
    # ------------------------------------------------------------------
    def step(self) -> Dict[int, np.ndarray]:
        """One fleet iteration: step every live replica (a replica
        whose supervisor escalates dies here, its requests re-placed),
        bridge fresh tokens into the outer request objects, finish
        drains whose replica ran dry, and rebalance one stuck waiter.
        Returns newly finished ``{router_id: full ids}``."""
        out: Dict[int, np.ndarray] = {}
        for rep in list(self._replicas):
            if not rep.live:
                continue
            try:
                fin = rep.sup.step()
            except (KeyboardInterrupt, SystemExit):
                raise
            except RecoveryExhaustedError as e:
                self._on_death(rep, e)
                continue
            self._absorb_replica(rep, fin, out)
            self._update_health(rep)
        for rep in self._replicas:
            if rep.state is ReplicaState.DRAINING and rep.live \
                    and not any(p.replica == rep.idx
                                for p in self._placements.values()):
                self._teardown(rep, "drained")
        self._rebalance_one()
        if self._pending_finished:
            out.update(self._pending_finished)
            self._pending_finished = {}
        if self._placements and not self._live():
            raise FleetExhaustedError(
                "every replica in the fleet is dead; "
                f"{len(self._placements)} live request(s) cannot be "
                "re-placed")
        return out

    def run_to_completion(self) -> Dict[int, np.ndarray]:
        results: Dict[int, np.ndarray] = {}
        while self._placements or self._pending_finished:
            results.update(self.step())
        return results

    def _absorb_replica(self, rep: _Replica, fin: Dict[int, np.ndarray],
                        out: Dict[int, np.ndarray]) -> None:
        """Bridge new tokens into outer requests and translate this
        replica's finished ids to router ids."""
        for p in self._placements.values():
            if p.replica != rep.idx or p.sid in fin:
                continue
            new = p.obj.out[len(p.req.out) - p.base:]
            if new:
                p.req.out.extend(int(x) for x in new)
            if p.obj.eos_pos is not None and p.req.eos_pos is None:
                p.req.eos_pos = p.base + p.obj.eos_pos
        for sid, arr in fin.items():
            rid = self._by_sid.pop((rep.idx, sid), None)
            if rid is None:
                continue                        # cancelled passthrough
            p = self._placements.pop(rid)
            p.req.out = p.req.out[:p.base] + [int(x) for x in p.obj.out]
            if p.obj.eos_pos is not None:
                p.req.eos_pos = p.base + p.obj.eos_pos
            self._final_replica[rid] = rep.idx
            out[rid] = np.concatenate(
                [p.req.prompt, np.asarray(p.req.out, np.int32)])

    def _update_health(self, rep: _Replica) -> None:
        faults = rep.sup.stats["crashes"] + rep.sup.stats["transient_retries"]
        if faults > rep.last_crashes:
            rep.last_crashes = faults
            rep.clean_steps = 0
            if rep.state is ReplicaState.HEALTHY:
                rep.state = ReplicaState.DEGRADED
                self._event("replica_degraded", replica=rep.idx)
        elif rep.state is ReplicaState.DEGRADED:
            rep.clean_steps += 1
            if rep.clean_steps >= self.heal_after_steps:
                rep.state = ReplicaState.HEALTHY
                rep.clean_steps = 0
                self._event("replica_healed", replica=rep.idx)

    # ------------------------------------------------------------------
    # death + re-placement
    # ------------------------------------------------------------------
    def kill_replica(self, idx: int, reason: str = "killed") -> None:
        """Declare a replica dead NOW (the chaos/ops entry point — the
        organic path is its supervisor raising
        :class:`RecoveryExhaustedError` inside :meth:`step`).  Live
        requests re-place onto surviving replicas and replay from their
        committed prefixes."""
        rep = self._replicas[idx]
        if not rep.live:
            raise ValueError(f"replica {idx} is already dead")
        self._on_death(rep, RecoveryExhaustedError(reason))

    def _on_death(self, rep: _Replica, exc: BaseException) -> None:
        rep.state = ReplicaState.DEAD
        rep.reason = f"{type(exc).__name__}: {exc}"
        rep.sup = None                        # drop pools with the wrapper
        self.stats["deaths"] += 1
        if self._reg.enabled:
            self._reg.counter("serve.fleet.replica_deaths_total").inc()
        self._event("replica_dead", replica=rep.idx,
                    error=rep.reason[:300])
        victims = [(rid, p) for rid, p in self._placements.items()
                   if p.replica == rep.idx]
        for rid, p in victims:
            del self._placements[rid]
            self._by_sid.pop((p.replica, p.sid), None)
            req = p.req
            if req.eos_pos is not None or len(req.out) >= p.max_new:
                # died between the final token and its delivery:
                # synthesize the terminal result from the committed
                # prefix, exactly like a supervisor-internal recovery
                if req.eos_pos is not None:
                    req.out = req.out[:req.eos_pos + 1]
                self._pending_finished[rid] = np.concatenate(
                    [req.prompt, np.asarray(req.out, np.int32)])
                self._final_replica[rid] = rep.idx
                self.stats["synthesized"] += 1
                continue
            portable = PortableRequest(
                prompt=req.prompt, out=list(req.out),
                kwargs=dict(p.kwargs), max_new=p.max_new,
                priority=p.priority)
            self._re_place(rid, p, portable)

    def _re_place(self, rid: int, p: _Placement,
                  portable: PortableRequest) -> None:
        """Adopt a portable request on the least-loaded live replica
        and splice the placement so the outer stream continues."""
        # the portable is the source of truth — extraction bridges
        # tokens the router has not absorbed yet
        out = [int(x) for x in portable.out]
        eos = portable.kwargs.get("eos_token_id")
        if eos is not None and eos in out:
            out = out[:out.index(eos) + 1]
            done = True
        else:
            done = len(out) >= portable.max_new
        if done:
            # extracted between the final token and its retire (the
            # engine retires at the START of the next step): nothing
            # left to run — synthesize the terminal result; the outer
            # object is synced so the handle streams the tail first
            p.req.out = out
            self._pending_finished[rid] = np.concatenate(
                [portable.prompt, np.asarray(out, np.int32)])
            self._final_replica[rid] = p.replica
            self.stats["synthesized"] += 1
            return
        need = portable.snapshot.num_blocks \
            if portable.snapshot is not None \
            else self._blocks_needed(
                len(portable.prompt) + portable.max_new)
        # request tracing (ISSUE 20): re-place under the ORIGINAL trace
        # (the router rid IS the frontend rid the tracer indexed), so a
        # mid-stream replica kill keeps one trace_id across the move
        tr = TRACER.lookup(rid=rid) if TRACER.enabled else None
        t_mv = tr.now() if tr is not None else 0.0
        src = p.replica
        with TRACER.activating(tr):
            target = self._pick_replica(
                need, exclude=p.replica,
                prefix_keys=self._prefix_keys(portable.prompt))
            if target is None:
                # admission knobs must not strand an ALREADY-admitted
                # request: fall back to any live replica, least loaded
                cands = [r for r in self._live() if r.idx != p.replica] \
                    or self._live()
                if not cands:
                    # keep the placement so the next step() still sees a
                    # live request on a dead fleet and escalates typed —
                    # the stream must abort, never silently vanish
                    self._placements[rid] = p
                    raise FleetExhaustedError(
                        "every replica in the fleet is dead; request "
                        f"{rid} cannot be re-placed")
                target = min(cands, key=self._load_key)
            sid = target.sup.adopt_request(portable)
        obj = target.sup.tracked_request(sid)
        if tr is not None:
            tr.add("re_place", t_mv, tr.now(), from_replica=src,
                   to_replica=target.idx, committed=len(out),
                   snapshot=portable.snapshot is not None)
            tr.meta["replayed"] = True
        p.replica = target.idx
        p.sid = sid
        p.obj = obj
        p.base = len(p.req.out) - len(obj.out)
        p.moves += 1
        self._placements[rid] = p
        self._by_sid[(target.idx, sid)] = rid
        self.stats["replacements"] += 1
        if portable.snapshot is not None:
            self.stats["snapshot_migrations"] += 1
        if self._reg.enabled:
            self._reg.counter("serve.fleet.replacements_total").inc()
            if portable.snapshot is not None:
                self._reg.counter(
                    "serve.fleet.snapshot_migrations_total").inc()
        self._event("re_place", req_id=rid, replica=target.idx,
                    committed=len(p.req.out),
                    snapshot=portable.snapshot is not None)

    # ------------------------------------------------------------------
    # graceful drain
    # ------------------------------------------------------------------
    def drain(self, idx: int, *, mode: str = "replace") -> None:
        """Gracefully remove a replica (rolling restart): placement
        stops immediately; live requests are spilled and re-placed
        (``mode="replace"`` — KV snapshots transplant, streams resume
        bit-identically on the target) or allowed to run out
        (``mode="run_out"`` — teardown happens in :meth:`step` once the
        replica runs dry).  Teardown records the replica's final
        KV-leak report (must be zero) before dropping it."""
        if mode not in ("replace", "run_out"):
            raise ValueError(f"unknown drain mode {mode!r}")
        rep = self._replicas[idx]
        if not rep.live:
            raise ValueError(f"replica {idx} is already dead")
        others = [r for r in self._live()
                  if r.idx != idx and r.state is not ReplicaState.DRAINING]
        if not others:
            raise ValueError("cannot drain the last live replica — add "
                             "a replacement first (add_replica)")
        rep.state = ReplicaState.DRAINING
        self.stats["drains"] += 1
        if self._reg.enabled:
            self._reg.counter("serve.fleet.drains_total").inc()
        self._event("drain_start", replica=idx, mode=mode)
        if mode == "run_out":
            return
        for rid, p in [(r, q) for r, q in self._placements.items()
                       if q.replica == idx]:
            arr = rep.sup.take_pending_result(p.sid)
            if arr is not None:
                del self._placements[rid]
                self._by_sid.pop((idx, p.sid), None)
                self._pending_finished[rid] = arr
                continue
            portable = rep.sup.extract_request(p.sid)
            if portable is None:
                continue                   # finished this very step
            self._by_sid.pop((idx, p.sid), None)
            del self._placements[rid]
            self._re_place(rid, p, portable)
        self._teardown(rep, "drained")

    def _teardown(self, rep: _Replica, reason: str) -> None:
        rep.final_leak = rep.sup.kv_leak_report()
        rep.state = ReplicaState.DEAD
        rep.reason = reason
        rep.sup = None
        self._event("drain_done", replica=rep.idx,
                    leaked=rep.final_leak["leaked"]
                    + rep.final_leak["unaccounted"])

    # ------------------------------------------------------------------
    # rebalancing: cross-replica re-placement of waiting/spilled work
    # ------------------------------------------------------------------
    def _rebalance_one(self) -> None:
        """Migrate ONE stuck waiter per fleet step: a request queued
        (often preempted-and-spilled) on a replica that cannot seat it
        now moves to a replica with a free slot and pages — bounded
        work per step, monotonic progress, no thrashing."""
        for rep in self._live():
            eng = rep.sup
            if eng.queue_depth == 0:
                continue
            src_slot_free = any(s is None for s in eng.slots)
            for waiting in list(eng.queue):
                rid = self._by_sid.get((rep.idx, waiting.req_id))
                if rid is None:
                    continue
                p = self._placements[rid]
                snap = eng._spill.get(waiting.req_id)
                need = snap.num_blocks if snap is not None else \
                    self._blocks_needed(len(waiting.prompt)
                                        + waiting.max_new_tokens)
                if src_slot_free and eng.alloc.free_blocks >= need:
                    continue               # source can seat it itself
                target = self._target_with_room(need, exclude=rep.idx)
                if target is None:
                    continue
                portable = eng.extract_request(p.sid)
                if portable is None:
                    continue
                del self._placements[rid]
                self._by_sid.pop((rep.idx, p.sid), None)
                self._re_place(rid, p, portable)
                self.stats["rebalanced"] += 1
                if self._reg.enabled:
                    self._reg.counter(
                        "serve.fleet.rebalanced_total").inc()
                return
        return

    def _target_with_room(self, need: int,
                          exclude: int) -> Optional[_Replica]:
        """A replica that could seat the request THIS step: a free
        decode slot and enough free pool pages right now."""
        cands = [r for r in self._placeable()
                 if r.idx != exclude
                 and any(s is None for s in r.sup.slots)
                 and r.sup.alloc.free_blocks >= need]
        if not cands:
            return None
        return min(cands, key=self._load_key)

    # ------------------------------------------------------------------
    # engine-surface duck typing (ServingFrontend / loadgen / bench)
    # ------------------------------------------------------------------
    @property
    def queue(self) -> List[GenRequest]:
        """Outer request objects of every live request (newest last) —
        the front-end's post-submit lookup reads this."""
        return [p.req for p in self._placements.values()]

    @property
    def queue_depth(self) -> int:
        return sum(r.sup.queue_depth for r in self._live())

    @property
    def active_requests(self) -> int:
        return sum(r.sup.active_requests for r in self._live())

    @property
    def live_requests(self) -> int:
        return len(self._placements)

    @property
    def cfg(self):
        live = self._live()
        if not live:
            raise FleetExhaustedError("no live replica in the fleet")
        return live[0].sup.cfg

    class _FleetPool:
        """Aggregate KV-pool view over live replicas (the front-end's
        admission math and gauges read ``num_blocks``/``free_blocks``)."""

        def __init__(self, router: "EngineRouter"):
            self._router = router

        @property
        def num_blocks(self) -> int:
            return sum(r.sup.alloc.num_blocks
                       for r in self._router._live())

        @property
        def free_blocks(self) -> int:
            return sum(r.sup.alloc.free_blocks
                       for r in self._router._live())

    @property
    def alloc(self) -> "_FleetPool":
        return EngineRouter._FleetPool(self)

    def _blocks_needed(self, n_tokens: int) -> int:
        return -(-n_tokens // self._block_size)

    def batch_occupancy(self) -> float:
        live = self._live()
        if not live:
            return 0.0
        return sum(r.sup.batch_occupancy() for r in live) / len(live)

    def kv_utilization(self) -> float:
        pool = self.alloc
        n = pool.num_blocks
        return 0.0 if n == 0 else 1.0 - pool.free_blocks / float(n)

    def kv_leak_report(self) -> Dict[str, int]:
        """Component-wise sum over live replicas (drained replicas'
        final reports are checked at teardown and kept in
        ``fleet_stats()['drain_reports']``)."""
        total = {"free_blocks": 0, "index_blocks": 0, "slot_blocks": 0,
                 "leaked": 0, "unaccounted": 0}
        for r in self._live():
            for k, v in r.sup.kv_leak_report().items():
                total[k] += v
        return total

    def resilience_stats(self) -> Dict[str, object]:
        """Summed per-replica resilience counters plus the fleet's own
        re-placement counters (the gauge publisher and bench rows read
        one dict)."""
        keys: Dict[str, object] = {}
        for r in self._live():
            for k, v in r.sup.resilience_stats().items():
                if isinstance(v, (int, float)):
                    keys[k] = keys.get(k, 0) + v
        for k, v in self.stats.items():
            keys[f"fleet_{k}"] = v
        keys.setdefault("spilled_bytes", 0)
        keys.setdefault("spilled_requests", 0)
        return keys

    def prefix_stats(self) -> Dict[str, object]:
        """Fleet-wide prefix-cache rollup: summed per-replica counters
        plus the router's own affinity counters (``hit_rate`` is
        recomputed over the summed lookups, never averaged)."""
        total: Dict[str, object] = {}
        for r in self._live():
            for k, v in r.sup.prefix_stats().items():
                if k == "hit_rate" or isinstance(v, bool):
                    continue
                if isinstance(v, (int, float)):
                    total[k] = total.get(k, 0) + v
        lk = total.get("lookups", 0)
        total["hit_rate"] = (total.get("hits", 0) / lk) if lk else None
        total["affinity_hits"] = self.stats["affinity_hits"]
        total["affinity_capped"] = self.stats["affinity_capped"]
        return total

    def aot_stats(self) -> Dict[str, object]:
        return {f"replica{r.idx}": r.sup.aot_stats()
                for r in self._live()}

    def fleet_stats(self) -> Dict[str, object]:
        """The ``serve.fleet.*`` rollup: health census, aggregate load,
        re-placement / drain / death counters, per-replica breakdown,
        and drained replicas' final leak reports."""
        by_state = self.health_census()
        per_replica = []
        for r in self._replicas:
            row: Dict[str, object] = {"replica": r.idx,
                                      "state": r.state.value}
            if r.live:
                row.update(
                    queue_depth=r.sup.queue_depth,
                    active=r.sup.active_requests,
                    batch_occupancy=round(r.sup.batch_occupancy(), 4),
                    kv_utilization=round(r.sup.kv_utilization(), 4),
                    crashes=r.sup.stats["crashes"],
                    recoveries=r.sup.stats["recoveries"])
            elif r.reason is not None:
                row["reason"] = r.reason
            per_replica.append(row)
        return {
            "replicas": len(self._replicas),
            **{st.value.lower(): by_state[st.value]
               for st in ReplicaState},
            "live_requests": len(self._placements),
            "queue_depth": self.queue_depth,
            "batch_occupancy": round(self.batch_occupancy(), 4),
            "kv_utilization": round(self.kv_utilization(), 4),
            **self.stats,
            "per_replica": per_replica,
            "drain_reports": {r.idx: r.final_leak
                              for r in self._replicas
                              if r.final_leak is not None},
        }

    def replica_of(self, req_id: int) -> Optional[int]:
        """Current (live) or final replica of a request — the loadgen
        per-replica breakdown reads this."""
        p = self._placements.get(req_id)
        if p is not None:
            return p.replica
        return self._final_replica.get(req_id)

    def _event(self, action: str, **fields) -> None:
        if self._reg.enabled:
            self._reg.event("serve", action=f"fleet_{action}", **fields)
