"""Open-loop Poisson load generator for the serving front-end.

Open loop is the honest serving benchmark: arrivals follow a seeded
Poisson process whose times do NOT depend on how fast the server
responds (a closed loop — next request after the previous reply — lets
a slow server throttle its own offered load and flatters every latency
percentile).  The generator submits at the planned arrival times,
pumps the front-end between arrivals, and reports the SLO-facing
numbers the bench row carries: p50/p95/p99 TTFT, per-output-token
latency, tokens/s, and goodput-under-SLO.

Everything here is seeded host code: request content, budgets, which
requests sample, and which get cancelled are all deterministic
functions of ``LoadGenConfig.seed``, so token outputs are reproducible
run-to-run (the engine pins per-request results independent of batch
composition).  Wall-clock only feeds TIMINGS, never traced code.

Usage::

    eng = ContinuousBatchingEngine(cfg, params, ...)
    fe = ServingFrontend(eng)
    report = PoissonLoadGenerator(fe, LoadGenConfig(
        n_requests=64, rate_rps=32.0, seed=0)).run()
    print(report.to_dict())

After the drain the generator cross-checks the engine's KV pool
(``kv_leak_report``) — a run with cancellations and timeouts must end
with zero leaked blocks.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import numpy as np

from .frontend import RequestHandle, RequestState, ServingFrontend

__all__ = ["LoadGenConfig", "LoadReport", "PoissonLoadGenerator"]


def _span(v: Union[int, Tuple[int, int]]) -> Tuple[int, int]:
    if isinstance(v, int):
        return (v, v)
    lo, hi = int(v[0]), int(v[1])
    if not 1 <= lo <= hi:
        raise ValueError(f"bad range {v!r}: need 1 <= lo <= hi")
    return (lo, hi)


@dataclass(frozen=True)
class LoadGenConfig:
    """Workload shape + SLOs.  ``prompt_len`` / ``max_new_tokens`` take
    an int or an inclusive ``(lo, hi)`` range."""

    n_requests: int = 32
    rate_rps: float = 16.0             # Poisson arrival rate
    seed: int = 0
    prompt_len: Union[int, Tuple[int, int]] = (4, 12)
    max_new_tokens: Union[int, Tuple[int, int]] = (4, 16)
    sampled_fraction: float = 0.0      # fraction using temperature>0
    temperature: float = 0.8
    top_k: Optional[int] = 20
    eos_token_id: Optional[int] = None
    slo_ttft_s: float = 2.0
    slo_tpot_s: float = 0.5
    deadline_s: Optional[float] = None
    max_queue_time_s: Optional[float] = None
    cancel_fraction: float = 0.0       # fraction cancelled mid-stream
    cancel_after_tokens: int = 2
    # mixed-priority traffic (ISSUE 11): each request draws its
    # priority class from ``priorities`` (seeded; ``priority_weights``
    # biases the draw).  With the default single class the engine's
    # preemption machinery is inert and reports carry no breakdown.
    priorities: Tuple[int, ...] = (0,)
    priority_weights: Optional[Tuple[float, ...]] = None
    # bursty arrivals (ISSUE 12): each inter-arrival gap is drawn at
    # ``burst_rate_rps`` with probability ``burst_fraction`` (seeded) —
    # a two-state modulated Poisson process whose bursts stress
    # admission and fleet placement without changing the mean shape of
    # calm traffic.  Disabled by default (identical draw sequence to
    # the pre-ISSUE plan, so existing seeds reproduce unchanged).
    burst_rate_rps: Optional[float] = None
    burst_fraction: float = 0.0
    # scripted replica kill (ISSUE 12): once ``kill_after_requests``
    # requests have been SUBMITTED (a deterministic trigger — wall
    # clock never decides), the generator kills fleet replica
    # ``kill_replica`` via ``router.kill_replica``.  Requires the
    # frontend to drive an ``EngineRouter``.
    kill_replica: Optional[int] = None
    kill_after_requests: int = 0
    # multi-tenant shared-prefix traffic (ISSUE 14): a seeded pool of
    # ``tenants`` system prompts (each ``tenant_prefix_len`` tokens);
    # every planned request draws a tenant and, with probability
    # ``tenant_reuse_prob``, PREPENDS that tenant's shared prompt to
    # its random user suffix — the workload shape the cross-request
    # prefix cache exists for.  With ``tenants=0`` (the default) no
    # extra RNG draws happen, so pre-ISSUE-14 seeds reproduce their
    # exact request sequences.  The tenant pool is part of the plan (a
    # pure function of the seed), so in-process and HTTP-transport runs
    # offer identical sequences (the PR 13 pin).
    tenants: int = 0
    tenant_prefix_len: Union[int, Tuple[int, int]] = 16
    tenant_reuse_prob: float = 1.0


@dataclass
class _Planned:
    at: float                          # arrival offset from run start
    prompt: np.ndarray
    max_new: int
    sampled: bool
    seed: int
    cancel: bool
    priority: int = 0
    tenant: Optional[int] = None       # set when a shared prefix applied


@dataclass
class LoadReport:
    """Aggregate + per-request results of one loadgen run.

    ``ttft`` / ``tpot`` dicts carry ``p50/p95/p99/mean`` over FINISHED
    requests (None when nothing finished); ``goodput_rps`` counts only
    finished requests that met BOTH SLOs."""

    n_requests: int
    finished: int
    rejected: int
    cancelled: int
    timed_out: int
    duration_s: float
    total_streamed_tokens: int
    tokens_per_s: float
    ttft_s: Optional[Dict[str, float]]
    tpot_s: Optional[Dict[str, float]]
    goodput_rps: float
    goodput_tokens_per_s: float
    slo: Dict[str, float]
    kv_leaks: Dict[str, int]
    per_request: List[Dict[str, Any]] = field(default_factory=list)
    # per-priority-class breakdown (ISSUE 11), only for mixed-priority
    # runs: the chaos invariant is that the HIGH class keeps its
    # goodput while the low class is shed/preempted
    by_priority: Optional[Dict[int, Dict[str, Any]]] = None
    # per-replica breakdown (ISSUE 12), only when the frontend drives
    # an EngineRouter: each request is attributed to the replica that
    # FINISHED it (its final placement after any re-placement)
    by_replica: Optional[Dict[int, Dict[str, Any]]] = None
    # prefix-cache effectiveness over THIS run (ISSUE 14): counter
    # deltas from the engine's prefix_stats(), only when the serving
    # stack exposes them
    prefix: Optional[Dict[str, Any]] = None
    # per-tenant goodput-under-SLO (ISSUE 14), only for multi-tenant
    # runs: the fairness invariant is that a shared system prompt buys
    # its tenant TTFT, not the fleet a hot spot
    by_tenant: Optional[Dict[int, Dict[str, Any]]] = None
    # per-phase latency-budget attribution (ISSUE 20), only when the
    # span tracer is enabled: p50/p95 contribution of each engine
    # phase (queue_wait, prefill, decode_step, ...) to TTFT and TPOT,
    # so a p99 miss names the phase that ate the budget
    attribution: Optional[Dict[str, Any]] = None

    def to_dict(self, include_requests: bool = False) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "n_requests": self.n_requests, "finished": self.finished,
            "rejected": self.rejected, "cancelled": self.cancelled,
            "timed_out": self.timed_out,
            "duration_s": round(self.duration_s, 4),
            "total_streamed_tokens": self.total_streamed_tokens,
            "tokens_per_s": round(self.tokens_per_s, 2),
            "ttft_s": self.ttft_s, "tpot_s": self.tpot_s,
            "goodput_rps": round(self.goodput_rps, 3),
            "goodput_tokens_per_s": round(self.goodput_tokens_per_s, 2),
            "slo": self.slo,
            "kv_leaked_blocks": (self.kv_leaks["leaked"]
                                 + self.kv_leaks["unaccounted"]),
        }
        if self.by_priority is not None:
            d["by_priority"] = self.by_priority
        if self.by_replica is not None:
            d["by_replica"] = self.by_replica
        if self.prefix is not None:
            d["prefix"] = self.prefix
        if self.by_tenant is not None:
            d["by_tenant"] = self.by_tenant
        if self.attribution is not None:
            d["attribution"] = self.attribution
        if include_requests:
            d["per_request"] = self.per_request
        return d


def _pcts(vals: List[float]) -> Optional[Dict[str, float]]:
    if not vals:
        return None
    a = np.asarray(vals, np.float64)
    return {"p50": round(float(np.percentile(a, 50)), 6),
            "p95": round(float(np.percentile(a, 95)), 6),
            "p99": round(float(np.percentile(a, 99)), 6),
            "mean": round(float(a.mean()), 6)}


class PoissonLoadGenerator:
    """Drives a :class:`ServingFrontend` with a seeded open-loop Poisson
    arrival process and reports latency/goodput percentiles.

    ``transport=`` (ISSUE 13) swaps the submission path: instead of
    calling ``frontend.submit`` in-process, every planned request goes
    through the transport (``serving.http.HttpTransport`` — the real
    HTTP/SSE wire).  The PLAN is identical either way (a pure function
    of the seed and vocab, consumed through one kwargs builder), so a
    wire run offers the exact same request sequence — content, budgets,
    sampling, cancels — as the in-process run with the same seed;
    pinned by tests/test_serving_http.py."""

    def __init__(self, frontend: Optional[ServingFrontend],
                 config: Optional[LoadGenConfig] = None, *,
                 transport=None,
                 clock: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        if frontend is None and transport is None:
            raise ValueError("need a frontend or a transport")
        self.frontend = frontend
        self.transport = transport
        self.config = config or LoadGenConfig()
        self._clock = clock
        self._sleep = sleep
        # handles of the most recent run() — chaos tests assert stream
        # invariants (no drop/dup/reorder) directly on them
        self.last_handles: List[Optional[RequestHandle]] = []

    def _vocab_size(self) -> int:
        if self.transport is not None:
            return int(self.transport.vocab_size)
        return int(self.frontend.engine.cfg.vocab_size)

    def request_kwargs(self, p: _Planned) -> dict:
        """The ONE planned-request → submit-kwargs mapping, shared by
        the in-process and wire transports (the reproducibility pin
        compares exactly this)."""
        cfg = self.config
        return dict(
            prompt_ids=p.prompt, max_new_tokens=p.max_new,
            eos_token_id=cfg.eos_token_id,
            temperature=cfg.temperature if p.sampled else 0.0,
            top_k=cfg.top_k if p.sampled else None, seed=p.seed,
            priority=p.priority, deadline_s=cfg.deadline_s,
            max_queue_time_s=cfg.max_queue_time_s)

    def plan(self) -> List[_Planned]:
        """The run's deterministic request schedule (pure function of
        the config seed and the engine's vocab size)."""
        cfg = self.config
        rng = np.random.default_rng(cfg.seed)
        gaps = rng.exponential(1.0 / cfg.rate_rps, cfg.n_requests)
        if cfg.burst_rate_rps is not None and cfg.burst_fraction > 0.0:
            bursty = rng.random(cfg.n_requests) < cfg.burst_fraction
            gaps = np.where(
                bursty,
                rng.exponential(1.0 / cfg.burst_rate_rps,
                                cfg.n_requests), gaps)
        arrivals = np.cumsum(gaps)
        vocab = self._vocab_size()
        plo, phi = _span(cfg.prompt_len)
        nlo, nhi = _span(cfg.max_new_tokens)
        prios = list(cfg.priorities)
        weights = None
        if cfg.priority_weights is not None:
            w = np.asarray(cfg.priority_weights, np.float64)
            weights = w / w.sum()
        # multi-tenant shared prefixes (ISSUE 14): the seeded tenant
        # pool is drawn FIRST, then per-request tenancy — all inside
        # the ``tenants`` gate so tenantless configs keep their exact
        # pre-ISSUE-14 draw sequence
        tenant_prompts: List[np.ndarray] = []
        if cfg.tenants > 0:
            tlo, thi = _span(cfg.tenant_prefix_len)
            for _ in range(cfg.tenants):
                tl = int(rng.integers(tlo, thi + 1))
                tenant_prompts.append(
                    rng.integers(0, vocab, (tl,)).astype(np.int32))
        out: List[_Planned] = []
        for i in range(cfg.n_requests):
            t0 = int(rng.integers(plo, phi + 1))
            prompt = rng.integers(0, vocab, (t0,)).astype(np.int32)
            tenant: Optional[int] = None
            if cfg.tenants > 0:
                t = int(rng.integers(0, cfg.tenants))
                if bool(rng.random() < cfg.tenant_reuse_prob):
                    tenant = t
                    prompt = np.concatenate([tenant_prompts[t], prompt])
            out.append(_Planned(
                at=float(arrivals[i]), prompt=prompt,
                max_new=int(rng.integers(nlo, nhi + 1)),
                sampled=bool(rng.random() < cfg.sampled_fraction),
                seed=int(rng.integers(0, 2 ** 31 - 1)),
                cancel=bool(rng.random() < cfg.cancel_fraction),
                priority=int(rng.choice(prios, p=weights)),
                tenant=tenant))
        return out

    def _submit(self, p: _Planned) -> RequestHandle:
        kwargs = self.request_kwargs(p)
        if self.transport is not None:
            return self.transport.submit(**kwargs)
        return self.frontend.submit(
            kwargs.pop("prompt_ids"), kwargs.pop("max_new_tokens"),
            **kwargs)

    def run(self) -> LoadReport:
        cfg = self.config
        from ..observability.tracing import TRACER
        if TRACER.enabled:
            # traced runs grade exemplars against THIS run's SLOs
            TRACER.configure(slo_ttft_s=cfg.slo_ttft_s,
                             slo_tpot_s=cfg.slo_tpot_s)
        if cfg.kill_replica is not None \
                and (self.frontend is None
                     or not hasattr(self.frontend.engine,
                                    "kill_replica")):
            raise ValueError(
                "kill_replica is a fleet scenario — the frontend must "
                "drive an EngineRouter")
        plan = self.plan()
        handles: List[Optional[RequestHandle]] = [None] * len(plan)
        ps0 = self._prefix_stats()
        t0 = self._clock()
        next_up = 0
        killed = False
        while True:
            now = self._clock() - t0
            while next_up < len(plan) and plan[next_up].at <= now:
                handles[next_up] = self._submit(plan[next_up])
                next_up += 1
            if (cfg.kill_replica is not None and not killed
                    and next_up >= cfg.kill_after_requests):
                # deterministic chaos: the kill fires at a submission
                # count, never at a wall-clock time
                self.frontend.engine.kill_replica(
                    cfg.kill_replica, reason="loadgen scripted kill")
                killed = True
            # deterministic mid-stream cancellations: fire once the
            # request has streamed cancel_after_tokens tokens
            for h, p in zip(handles, plan):
                if (h is not None and p.cancel
                        and not h.state.terminal
                        and h.n_streamed >= cfg.cancel_after_tokens):
                    h.cancel()
            live = any(h is not None and not h.state.terminal
                       for h in handles)
            if live:
                if self.transport is not None:
                    self.transport.pump(self._sleep)
                else:
                    self.frontend.step()
            elif next_up < len(plan):
                gap = plan[next_up].at - (self._clock() - t0)
                if gap > 0:
                    self._sleep(min(gap, 0.005))
            else:
                break
        if self.transport is not None:
            self.transport.drain()
        duration = max(self._clock() - t0, 1e-9)
        self.last_handles = handles
        return self._report(handles, duration, plan,
                            prefix=self._prefix_delta(ps0))

    def _prefix_stats(self) -> Optional[Dict[str, Any]]:
        """The serving stack's prefix-cache counters (engine, router,
        or co-located HTTP server), or None when unavailable (remote
        wire without a co-located server)."""
        src: Any = self.transport if self.transport is not None \
            else self.frontend.engine
        fn = getattr(src, "prefix_stats", None)
        return fn() if callable(fn) else None

    def _prefix_delta(self,
                      before: Optional[Dict[str, Any]]
                      ) -> Optional[Dict[str, Any]]:
        """Counter deltas over this run (the report must not attribute
        a warm engine's lifetime hits to one scenario)."""
        after = self._prefix_stats()
        if after is None:
            return None
        before = before or {}
        delta: Dict[str, Any] = {}
        for k in ("lookups", "hits", "hit_tokens", "inserts",
                  "evictions", "offloads", "restores",
                  "restore_failures", "prefill_tokens_computed"):
            if k in after:
                delta[k] = int(after[k]) - int(before.get(k, 0))
        lk = delta.get("lookups", 0)
        delta["hit_rate"] = round(delta["hits"] / lk, 4) if lk else None
        for k in ("cached_blocks", "offloaded_blocks",
                  "offloaded_bytes"):
            if k in after:
                delta[k] = after[k]          # point-in-time, not delta
        return delta

    def _report(self, handles: List[Optional[RequestHandle]],
                duration: float,
                plan: Optional[List[_Planned]] = None,
                prefix: Optional[Dict[str, Any]] = None) -> LoadReport:
        cfg = self.config
        ttfts: List[float] = []
        tpots: List[float] = []
        counts = {s: 0 for s in RequestState}
        total_tokens = 0
        good = 0
        good_tokens = 0
        per_req: List[Dict[str, Any]] = []
        prio_of = {} if plan is None else {
            id(h): p.priority for h, p in zip(handles, plan)
            if h is not None}
        tenant_of = {} if plan is None else {
            id(h): p.tenant for h, p in zip(handles, plan)
            if h is not None and p.tenant is not None}
        by_prio: Dict[int, Dict[str, Any]] = {}
        by_ten: Dict[int, Dict[str, Any]] = {}
        eng = None if self.frontend is None else self.frontend.engine
        replica_of = getattr(eng, "replica_of", None)
        by_rep: Dict[int, Dict[str, Any]] = {}
        for h in handles:
            if h is None:
                continue
            counts[h.state] += 1
            k = h.n_streamed
            total_tokens += k
            if replica_of is not None and h.req_id is not None:
                ridx = replica_of(h.req_id)
                if ridx is not None:
                    rc = by_rep.setdefault(ridx, {
                        "n": 0, "finished": 0, "cancelled": 0,
                        "timed_out": 0, "tokens": 0})
                    rc["n"] += 1
                    rc["tokens"] += k
                    for st, key in (
                            (RequestState.FINISHED, "finished"),
                            (RequestState.CANCELLED, "cancelled"),
                            (RequestState.TIMED_OUT, "timed_out")):
                        if h.state is st:
                            rc[key] += 1
            prio = prio_of.get(id(h), 0)
            pc = by_prio.setdefault(prio, {
                "n": 0, "finished": 0, "rejected": 0, "cancelled": 0,
                "timed_out": 0, "good": 0, "good_tokens": 0})
            pc["n"] += 1
            for st, key in ((RequestState.FINISHED, "finished"),
                            (RequestState.REJECTED, "rejected"),
                            (RequestState.CANCELLED, "cancelled"),
                            (RequestState.TIMED_OUT, "timed_out")):
                if h.state is st:
                    pc[key] += 1
            tenant = tenant_of.get(id(h))
            tc = None
            if tenant is not None:
                tc = by_ten.setdefault(tenant, {
                    "n": 0, "finished": 0, "good": 0, "good_tokens": 0,
                    "ttfts": []})
                tc["n"] += 1
                if h.state is RequestState.FINISHED:
                    tc["finished"] += 1
            rec: Dict[str, Any] = {"req_id": h.req_id,
                                   "state": h.state.value,
                                   "n_tokens": k, "priority": prio}
            if tenant is not None:
                rec["tenant"] = tenant
            if h.ttft_s is not None:
                rec["ttft_s"] = round(h.ttft_s, 6)
            if h.state is RequestState.FINISHED:
                ttfts.append(h.ttft_s)
                if tc is not None:
                    tc["ttfts"].append(h.ttft_s)
                tpot = 0.0
                if k > 1:
                    tpot = (h.finish_t - h.first_token_t) / (k - 1)
                    tpots.append(tpot)
                rec["tpot_s"] = round(tpot, 6)
                if h.ttft_s <= cfg.slo_ttft_s and tpot <= cfg.slo_tpot_s:
                    good += 1
                    good_tokens += k
                    pc["good"] += 1
                    pc["good_tokens"] += k
                    if tc is not None:
                        tc["good"] += 1
                        tc["good_tokens"] += k
            per_req.append(rec)
        by_priority = None
        if len(by_prio) > 1:
            by_priority = {}
            for prio, pc in sorted(by_prio.items()):
                by_priority[prio] = {
                    "n": pc["n"], "finished": pc["finished"],
                    "rejected": pc["rejected"],
                    "cancelled": pc["cancelled"],
                    "timed_out": pc["timed_out"],
                    "goodput_rps": round(pc["good"] / duration, 3),
                    "goodput_tokens_per_s": round(
                        pc["good_tokens"] / duration, 2),
                }
        by_tenant = None
        if by_ten:
            by_tenant = {}
            for t, tc in sorted(by_ten.items()):
                by_tenant[t] = {
                    "n": tc["n"], "finished": tc["finished"],
                    "goodput_rps": round(tc["good"] / duration, 3),
                    "goodput_tokens_per_s": round(
                        tc["good_tokens"] / duration, 2),
                    "ttft_s": _pcts(tc["ttfts"]),
                }
        attrib = None
        from ..observability.tracing import TRACER, attribution
        if TRACER.enabled:
            traces = [t for t in (getattr(h, "trace", None)
                                  for h in handles if h is not None)
                      if t is not None]
            if traces:
                attrib = attribution(traces)
        return LoadReport(
            n_requests=cfg.n_requests,
            finished=counts[RequestState.FINISHED],
            rejected=counts[RequestState.REJECTED],
            cancelled=counts[RequestState.CANCELLED],
            timed_out=counts[RequestState.TIMED_OUT],
            duration_s=duration,
            total_streamed_tokens=total_tokens,
            tokens_per_s=total_tokens / duration,
            ttft_s=_pcts(ttfts), tpot_s=_pcts(tpots),
            goodput_rps=good / duration,
            goodput_tokens_per_s=good_tokens / duration,
            slo={"ttft_s": cfg.slo_ttft_s, "tpot_s": cfg.slo_tpot_s},
            kv_leaks=(self.transport.kv_leak_report()
                      if self.transport is not None
                      else self.frontend.engine.kv_leak_report()),
            per_request=per_req, by_priority=by_priority,
            by_replica={k: by_rep[k] for k in sorted(by_rep)}
            if by_rep else None,
            prefix=prefix, by_tenant=by_tenant, attribution=attrib)
