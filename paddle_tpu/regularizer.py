"""paddle.regularizer parity (reference python/paddle/regularizer.py).

The optimizers consume these via ``weight_decay=`` (Optimizer._wd_coeff
reads ``_coeff``); ``__call__`` also computes the penalty directly for
manual-loss use."""

from __future__ import annotations

__all__ = ["L1Decay", "L2Decay"]


class _Decay:
    def __init__(self, coeff: float = 0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self) -> float:
        return self._coeff

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L2Decay(_Decay):
    """coeff/2 * sum(w^2) — the decoupled form the optimizers apply as
    weight decay (reference L2DecayRegularizer)."""

    def __call__(self, param):
        from .ops import api
        return api.sum(api.square(param)) * (self._coeff * 0.5)


class L1Decay(_Decay):
    """coeff * sum(|w|) (reference L1DecayRegularizer).  NOTE: the
    built-in optimizers apply ``weight_decay`` as L2-style decay; pass
    an L1Decay penalty into the loss directly for true L1."""

    def __call__(self, param):
        from .ops import api
        return api.sum(api.abs(param)) * self._coeff
