"""Metrics (reference: python/paddle/metric/metrics.py —
Accuracy/Precision/Recall/Auc)."""

from __future__ import annotations

import numpy as np

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        pred = np.asarray(pred)
        label = np.asarray(label)
        idx = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = idx == label[..., None]
        return correct

    def update(self, correct, *args):
        correct = np.asarray(correct)
        accs = []
        for i, k in enumerate(self.topk):
            c = correct[..., :k].any(-1).astype(np.float64)
            self.total[i] += c.sum()
            self.count[i] += c.size
            accs.append(c.mean())
        return accs[0] if len(accs) == 1 else accs

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "precision"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fp += int(((preds == 1) & (labels == 0)).sum())

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__()
        self._name = name or "recall"
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = (np.asarray(preds) > 0.5).astype(np.int64).reshape(-1)
        labels = np.asarray(labels).reshape(-1)
        self.tp += int(((preds == 1) & (labels == 1)).sum())
        self.fn += int(((preds == 0) & (labels == 1)).sum())

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name or "auc"
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        labels = np.asarray(labels).reshape(-1)
        pos_prob = preds[:, 1] if preds.ndim == 2 else preds.reshape(-1)
        bins = (pos_prob * self.num_thresholds).astype(np.int64)
        bins = np.clip(bins, 0, self.num_thresholds)
        for b, l in zip(bins, labels):
            if l:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoid over thresholds, descending
        pos = np.cumsum(self._stat_pos[::-1])
        neg = np.cumsum(self._stat_neg[::-1])
        tpr = pos / tot_pos
        fpr = neg / tot_neg
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1):
    """Functional accuracy (reference: paddle.metric.accuracy)."""
    from ..core.tensor import Tensor
    pred = np.asarray(input._value if isinstance(input, Tensor) else input)
    lab = np.asarray(label._value if isinstance(label, Tensor) else label)
    idx = np.argsort(-pred, axis=-1)[..., :k]
    if lab.ndim == pred.ndim:
        lab = lab.squeeze(-1)
    correct = (idx == lab[..., None]).any(-1)
    return Tensor(np.asarray(correct.mean(), np.float32))
