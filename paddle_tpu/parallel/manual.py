"""Manual-SPMD building blocks for fully-compiled hybrid parallel steps.

Everything here is meant to run INSIDE a ``jax.shard_map`` whose mesh makes
ALL hybrid axes (pp, dp, sharding, sep, mp) manual.  Round-1 mixed GSPMD
tensor-parallel sharding with a partial-manual shard_map pipeline, which
blew up SPMD partitioning / compile time on mp×pp meshes; the cure is to
express tensor parallelism the Megatron way — local shards + explicit
collectives — so XLA never has to propagate shardings through the pipeline.

Reference semantics being matched (cited per function):
* ``mp_copy``   — the Megatron "f" operator ``_c_identity``
  (/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_ops.py:91):
  identity forward, all-reduce backward.
* ``vocab_parallel_embedding`` — masked local lookup + all-reduce
  (mp_layers.py:47 ``VocabParallelEmbedding`` / ``c_embedding`` op).
* ``vocab_parallel_nll`` — ``ParallelCrossEntropy`` (mp_layers.py:742,
  ``c_softmax_with_cross_entropy`` kernel): max/psum over the vocab-sharded
  logits, never materializing the full softmax.
* ``zero_adam_leaf_update`` — sharding stage-1/2 semantics
  (fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:44,
  sharding/group_sharded_stage2.py:46): grads reduce-scattered to the owner
  shard, optimizer moments stored 1/shard per device, updated params
  all-gathered — expressed per-leaf on a flattened (padded) vector.
"""

from __future__ import annotations

import functools
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .topology import (DP_AXIS, MP_AXIS, PP_AXIS, SEP_AXIS, SHARDING_AXIS,
                       HybridTopology)

__all__ = ["mp_copy", "fwd_psum", "vocab_parallel_embedding",
           "vocab_parallel_nll",
           "zero_adam_leaf_update", "local_shape", "moment_shape",
           "MOMENT_SPEC", "tree_map_with_spec"]

# Flat optimizer-moment layout: [pp, mp, shard * chunk] — one fp32 chunk per
# (pp, mp, sharding) mesh coordinate, replicated over dp/sep.
MOMENT_SPEC = P(PP_AXIS, MP_AXIS, SHARDING_AXIS)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_copy(x, axis_name: str = MP_AXIS):
    """Identity forward / psum backward over the tensor-parallel axis.

    Insert before every column-parallel matmul whose input is replicated
    over mp: each rank's backward contribution through its weight shard is
    partial, and this operator's VJP all-reduces them (Megatron "f",
    reference mp_ops.py:91 ``_c_identity``)."""
    return x


def _mp_copy_fwd(x, axis_name):
    return x, None


def _mp_copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


mp_copy.defvjp(_mp_copy_fwd, _mp_copy_bwd)


def vocab_parallel_embedding(ids, wte_local, axis_name: str = MP_AXIS):
    """Vocab-parallel embedding lookup (reference mp_layers.py:47).

    ``wte_local``: [vocab/mp, h] local shard; ``ids``: global token ids.
    Masked local gather + psum over mp.  Returns [..., h].
    """
    vpr = wte_local.shape[0]
    off = lax.axis_index(axis_name) * vpr
    mask = (ids >= off) & (ids < off + vpr)
    x = jnp.take(wte_local, jnp.where(mask, ids - off, 0), axis=0)
    x = jnp.where(mask[..., None], x, jnp.zeros((), x.dtype))
    return fwd_psum(x, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def fwd_psum(x, axis_name):
    """All-reduce forward / IDENTITY backward (the Megatron "g" operator,
    reference mp_ops.py:293 ``_mp_allreduce``).

    Use this — not raw ``lax.psum`` — for every forward-path all-reduce
    that autodiff will flow through inside a ``check_vma=False`` shard_map:
    there JAX transposes ``psum`` to another ``psum``, which multiplies the
    (replicated) cotangent by the axis size and silently scales gradients.
    Each device's summand has unit Jacobian w.r.t. the replicated output,
    so the correct VJP is the identity."""
    return lax.psum(x, axis_name)


fwd_psum.defvjp(lambda x, a: (lax.psum(x, a), None),
                lambda a, _, g: (g,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pmax_stop(x, axis_name):
    """pmax with zero gradient (lax.pmax has no differentiation rule;
    the softmax max-subtraction is a constant shift mathematically)."""
    return lax.pmax(x, axis_name)


_pmax_stop.defvjp(lambda x, a: (lax.pmax(x, a), None),
                  lambda a, _, g: (jnp.zeros_like(g),))


def vocab_parallel_nll(logits_local, labels, axis_name: str = MP_AXIS):
    """Per-token negative log-likelihood over vocab-sharded logits.

    ``logits_local``: [..., vocab/mp] (fp32 recommended); ``labels``: global
    ids with the same leading shape.  Equivalent to the reference's
    ``ParallelCrossEntropy`` (mp_layers.py:742): global max via pmax, global
    sum-exp and label logit via psum — no full-vocab materialization.
    """
    vpr = logits_local.shape[-1]
    off = lax.axis_index(axis_name) * vpr
    lmax = _pmax_stop(jnp.max(lax.stop_gradient(logits_local), axis=-1),
                      axis_name)
    z = logits_local - lmax[..., None]
    sumexp = fwd_psum(jnp.sum(jnp.exp(z), axis=-1), axis_name)
    lse = jnp.log(sumexp)
    mask = (labels >= off) & (labels < off + vpr)
    li = jnp.where(mask, labels - off, 0)
    lab = jnp.take_along_axis(z, li[..., None], axis=-1)[..., 0]
    lab = fwd_psum(jnp.where(mask, lab, jnp.zeros((), z.dtype)), axis_name)
    return lse - lab


def zero_adam_leaf_update(p, g, m_flat, v_flat, tf, *, lr, b1=0.9, b2=0.95,
                          eps=1e-8, weight_decay=0.0,
                          axis_name: str = SHARDING_AXIS):
    """ZeRO-sharded Adam step for one (local) parameter leaf.

    ``p``/``g``: the device-local shard of the param and its grad (grads
    must already be reduced over data axes; the sharding-axis reduction
    happens HERE via psum_scatter).  ``m_flat``/``v_flat``: fp32 moment
    chunks of size ceil(p.size/shard) — each device owns 1/shard of the
    optimizer state (stage-1/2 memory behavior,
    reference group_sharded_stage2.py:46).  Returns (p_new, m_new, v_new).
    """
    shard = lax.axis_size(axis_name)
    shape, n = p.shape, p.size
    chunk = m_flat.size
    pad = shard * chunk - n
    g32 = jnp.pad(g.astype(jnp.float32).reshape(-1), (0, pad))
    g32 = g32.reshape(shard, chunk)
    # reduce-scatter: sum over the sharding axis, keep only our chunk
    g_loc = lax.psum_scatter(g32, axis_name, scatter_dimension=0,
                             tiled=False)
    idx = lax.axis_index(axis_name)
    p32 = jnp.pad(p.astype(jnp.float32).reshape(-1), (0, pad))
    p_loc = lax.dynamic_index_in_dim(p32.reshape(shard, chunk), idx, 0,
                                     keepdims=False)
    m2 = b1 * m_flat + (1 - b1) * g_loc
    v2 = b2 * v_flat + (1 - b2) * g_loc * g_loc
    mh = m2 / (1 - b1 ** tf)
    vh = v2 / (1 - b2 ** tf)
    upd = mh / (jnp.sqrt(vh) + eps)
    if weight_decay:
        upd = upd + weight_decay * p_loc
    p_loc = p_loc - lr * upd
    p_new = lax.all_gather(p_loc, axis_name, tiled=False).reshape(-1)
    p_new = p_new[:n].reshape(shape).astype(p.dtype)
    return p_new, m2, v2


def local_shape(shape: Tuple[int, ...], spec: P,
                topo: HybridTopology) -> Tuple[int, ...]:
    """Device-local shape of a global array laid out with ``spec``."""
    out = list(shape)
    for i, ax in enumerate(tuple(spec)[:len(out)]):
        if ax is None:
            continue
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            size = topo.axis_size(a)
            if out[i] % size != 0:
                raise ValueError(
                    f"dim {i} of {shape} not divisible by {a}={size}")
            out[i] //= size
    return tuple(out)


def moment_shape(param_shape: Tuple[int, ...], spec: P,
                 topo: HybridTopology) -> Tuple[int, int, int]:
    """Global shape of the flat ZeRO moment buffer for one param leaf:
    [pp, mp, shard*chunk] with chunk = ceil(local_numel/shard)."""
    n = int(np.prod(local_shape(param_shape, spec, topo))) or 1
    shard = topo.axis_size(SHARDING_AXIS)
    chunk = -(-n // shard)
    return (topo.axis_size(PP_AXIS), topo.axis_size(MP_AXIS), shard * chunk)


def tree_map_with_spec(fn, tree, specs):
    """tree_map over a nested dict whose spec tree has PartitionSpec leaves
    (PartitionSpec is tuple-like, so jax.tree.map can't be trusted here)."""
    if isinstance(tree, dict):
        return {k: tree_map_with_spec(fn, tree[k], specs[k]) for k in tree}
    return fn(tree, specs)
