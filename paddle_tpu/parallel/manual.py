"""Manual-SPMD building blocks for fully-compiled hybrid parallel steps.

Everything here is meant to run INSIDE a ``jax.shard_map`` whose mesh makes
ALL hybrid axes (pp, dp, sharding, sep, mp) manual.  Round-1 mixed GSPMD
tensor-parallel sharding with a partial-manual shard_map pipeline, which
blew up SPMD partitioning / compile time on mp×pp meshes; the cure is to
express tensor parallelism the Megatron way — local shards + explicit
collectives — so XLA never has to propagate shardings through the pipeline.

Reference semantics being matched (cited per function):
* ``mp_copy``   — the Megatron "f" operator ``_c_identity``
  (/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_ops.py:91):
  identity forward, all-reduce backward.
* ``vocab_parallel_embedding`` — masked local lookup + all-reduce
  (mp_layers.py:47 ``VocabParallelEmbedding`` / ``c_embedding`` op).
* ``vocab_parallel_nll`` — ``ParallelCrossEntropy`` (mp_layers.py:742,
  ``c_softmax_with_cross_entropy`` kernel): max/psum over the vocab-sharded
  logits, never materializing the full softmax.
* ``zero_adam_leaf_update`` — sharding stage-1/2 semantics
  (fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:44,
  sharding/group_sharded_stage2.py:46): grads reduce-scattered to the owner
  shard, optimizer moments stored 1/shard per device, updated params
  all-gathered — expressed per-leaf on a flattened (padded) vector.
"""

from __future__ import annotations

import functools
import math
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .remat import remat_wrap
from .topology import (DP_AXIS, MP_AXIS, PP_AXIS, SEP_AXIS, SHARDING_AXIS,
                       HybridTopology)

__all__ = ["mp_copy", "fwd_psum", "vocab_parallel_embedding",
           "vocab_parallel_nll", "vocab_parallel_linear_nll",
           "zero_adam_leaf_update", "local_shape", "moment_shape",
           "MOMENT_SPEC", "tree_map_with_spec"]

# Flat optimizer-moment layout: [pp, mp, shard * chunk] — one fp32 chunk per
# (pp, mp, sharding) mesh coordinate, replicated over dp/sep.
MOMENT_SPEC = P(PP_AXIS, MP_AXIS, SHARDING_AXIS)
# Expert-parallel leaves (param spec carries the dp axis — MoE expert
# banks): every (dp, sharding) coordinate owns distinct state, so the
# flat dim is sharded over both and NOT replicated over dp.
MOMENT_SPEC_EP = P(PP_AXIS, MP_AXIS, (DP_AXIS, SHARDING_AXIS))


def spec_has_axis(spec: P, axis: str) -> bool:
    """True if the PartitionSpec mentions ``axis`` (incl. tuple entries)."""
    for ax in tuple(spec):
        if ax is None:
            continue
        if axis in (ax if isinstance(ax, tuple) else (ax,)):
            return True
    return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def mp_copy(x, axis_name: str = MP_AXIS):
    """Identity forward / psum backward over the tensor-parallel axis.

    Insert before every column-parallel matmul whose input is replicated
    over mp: each rank's backward contribution through its weight shard is
    partial, and this operator's VJP all-reduces them (Megatron "f",
    reference mp_ops.py:91 ``_c_identity``)."""
    return x


def _mp_copy_fwd(x, axis_name):
    return x, None


def _mp_copy_bwd(axis_name, _, g):
    return (lax.psum(g, axis_name),)


mp_copy.defvjp(_mp_copy_fwd, _mp_copy_bwd)


def vocab_parallel_embedding(ids, wte_local, axis_name: str = MP_AXIS):
    """Vocab-parallel embedding lookup (reference mp_layers.py:47).

    ``wte_local``: [vocab/mp, h] local shard; ``ids``: global token ids.
    Masked local gather + psum over mp.  Returns [..., h].
    """
    vpr = wte_local.shape[0]
    off = lax.axis_index(axis_name) * vpr
    mask = (ids >= off) & (ids < off + vpr)
    x = jnp.take(wte_local, jnp.where(mask, ids - off, 0), axis=0)
    x = jnp.where(mask[..., None], x, jnp.zeros((), x.dtype))
    return fwd_psum(x, axis_name)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def fwd_psum(x, axis_name):
    """All-reduce forward / IDENTITY backward (the Megatron "g" operator,
    reference mp_ops.py:293 ``_mp_allreduce``).

    Use this — not raw ``lax.psum`` — for every forward-path all-reduce
    that autodiff will flow through inside a ``check_vma=False`` shard_map:
    there JAX transposes ``psum`` to another ``psum``, which multiplies the
    (replicated) cotangent by the axis size and silently scales gradients.
    Each device's summand has unit Jacobian w.r.t. the replicated output,
    so the correct VJP is the identity."""
    return lax.psum(x, axis_name)


fwd_psum.defvjp(lambda x, a: (lax.psum(x, a), None),
                lambda a, _, g: (g,))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _pmax_stop(x, axis_name):
    """pmax with zero gradient (lax.pmax has no differentiation rule;
    the softmax max-subtraction is a constant shift mathematically)."""
    return lax.pmax(x, axis_name)


_pmax_stop.defvjp(lambda x, a: (lax.pmax(x, a), None),
                  lambda a, _, g: (jnp.zeros_like(g),))


def vocab_parallel_nll(logits_local, labels, axis_name: str = MP_AXIS):
    """Per-token negative log-likelihood over vocab-sharded logits.

    ``logits_local``: [..., vocab/mp] (fp32 recommended); ``labels``: global
    ids with the same leading shape.  Equivalent to the reference's
    ``ParallelCrossEntropy`` (mp_layers.py:742): global max via pmax, global
    sum-exp and label logit via psum — no full-vocab materialization.
    """
    vpr = logits_local.shape[-1]
    off = lax.axis_index(axis_name) * vpr
    lmax = _pmax_stop(jnp.max(lax.stop_gradient(logits_local), axis=-1),
                      axis_name)
    z = logits_local - lmax[..., None]
    sumexp = fwd_psum(jnp.sum(jnp.exp(z), axis=-1), axis_name)
    lse = jnp.log(sumexp)
    mask = (labels >= off) & (labels < off + vpr)
    li = jnp.where(mask, labels - off, 0)
    lab = jnp.take_along_axis(z, li[..., None], axis=-1)[..., 0]
    lab = fwd_psum(jnp.where(mask, lab, jnp.zeros((), z.dtype)), axis_name)
    return lse - lab


def vocab_parallel_linear_nll(x, w_local, labels, *, w_layout: str = "vh",
                              chunk=None, axis_name: str = MP_AXIS,
                              ignore_index=None, label_smoothing: float = 0.0):
    """Logits-free fused head for mp-sharded vocab: per-token NLL of the
    column-parallel ``x @ head`` computed by streaming vocab chunks —
    replaces the ``mp_copy`` → full-logits einsum → :func:`vocab_parallel_nll`
    pipeline.  The reference's two all-reduce passes (max, then sum-exp +
    label pick) fuse into one pmax + one stacked psum inside the chunk
    loop, and the backward's dx psum subsumes ``mp_copy``'s VJP.

    ``w_local``: [V/mp, h] (``w_layout="vh"``, tied-embedding layout) or
    [h, V/mp] (``"hv"``, Linear layout).  Must run inside the all-manual
    ``shard_map`` (``axis_name`` collectives); grads are meant to be taken
    INSIDE the shard_map (the ``fwd_psum`` convention).
    """
    from ..ops.fused_cross_entropy import linear_cross_entropy
    return linear_cross_entropy(
        x, w_local, labels, w_layout=w_layout, chunk=chunk,
        ignore_index=ignore_index, label_smoothing=label_smoothing,
        axis_name=axis_name, backend="xla")


def zero_adam_leaf_update(p, g, m_flat, v_flat, tf, *, lr, b1=0.9, b2=0.95,
                          eps=1e-8, weight_decay=0.0,
                          axis_name: str = SHARDING_AXIS):
    """ZeRO-sharded Adam step for one (local) parameter leaf.

    ``p``/``g``: the device-local shard of the param and its grad (grads
    must already be reduced over data axes; the sharding-axis reduction
    happens HERE via psum_scatter).  ``m_flat``/``v_flat``: fp32 moment
    chunks of size ceil(p.size/shard) — each device owns 1/shard of the
    optimizer state (stage-1/2 memory behavior,
    reference group_sharded_stage2.py:46).  Returns (p_new, m_new, v_new).
    """
    shard = lax.axis_size(axis_name)
    shape, n = p.shape, p.size
    chunk = m_flat.size
    pad = shard * chunk - n
    g32 = jnp.pad(g.astype(jnp.float32).reshape(-1), (0, pad))
    g32 = g32.reshape(shard, chunk)
    # reduce-scatter: sum over the sharding axis, keep only our chunk
    g_loc = lax.psum_scatter(g32, axis_name, scatter_dimension=0,
                             tiled=False)
    idx = lax.axis_index(axis_name)
    p32 = jnp.pad(p.astype(jnp.float32).reshape(-1), (0, pad))
    p_loc = lax.dynamic_index_in_dim(p32.reshape(shard, chunk), idx, 0,
                                     keepdims=False)
    m2 = b1 * m_flat + (1 - b1) * g_loc
    v2 = b2 * v_flat + (1 - b2) * g_loc * g_loc
    mh = m2 / (1 - b1 ** tf)
    vh = v2 / (1 - b2 ** tf)
    upd = mh / (jnp.sqrt(vh) + eps)
    if weight_decay:
        upd = upd + weight_decay * p_loc
    p_loc = p_loc - lr * upd
    p_new = lax.all_gather(p_loc, axis_name, tiled=False).reshape(-1)
    p_new = p_new[:n].reshape(shape).astype(p.dtype)
    return p_new, m2, v2


def vpp_block_layout(blk_specs, S: int, vpp: int, num_layers: int):
    """Interleaved-schedule block layout shared by the model builders:
    validates divisibility, inserts the chunk axis into each block spec
    ([S, v, per_v, ...]), and returns a restacker mapping a
    [S*v, per_v, ...] vs-major stack to [S, v, per_v, ...] where element
    [s, c] holds virtual stage s + S*c (the layout
    spmd_pipeline_interleaved expects)."""
    if vpp <= 1:
        return blk_specs, None
    if num_layers % (S * vpp) != 0:
        raise ValueError(
            f"num_layers {num_layers} not divisible by pp*chunks "
            f"{S}*{vpp}")
    specs = {k: P(*(tuple(sp)[:1] + (None,) + tuple(sp)[1:]))
             for k, sp in blk_specs.items()}

    def restack(stacked):
        return {n: jnp.transpose(
                    val.reshape((vpp, S) + val.shape[1:]),
                    (1, 0) + tuple(range(2, val.ndim + 1)))
                for n, val in stacked.items()}

    return specs, restack


def pack_leaf(p_local, chunk: int, axis_name: str = SHARDING_AXIS):
    """Flat-shard a device-local param leaf over the sharding axis:
    keep only this device's ``chunk`` of the padded flat view (ZeRO
    stage-3 at-rest layout, reference group_sharded_stage3.py:85
    _param_storage)."""
    shard = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    flat = jnp.pad(p_local.reshape(-1), (0, shard * chunk - p_local.size))
    return lax.dynamic_index_in_dim(flat.reshape(shard, chunk), idx, 0,
                                    keepdims=False)


def unpack_leaf(p_flat, shape, dtype=None, axis_name: str = SHARDING_AXIS):
    """Gather-at-use: reassemble the full local leaf from the per-device
    flat shards (stage-3 ``_gather`` before forward use).  Differentiating
    through this all_gather transposes into exactly the stage-3
    reduce-scatter of the gradient — no separate grad plumbing."""
    full = lax.all_gather(p_flat, axis_name, tiled=False).reshape(-1)
    n = int(np.prod(shape))
    out = full[:n].reshape(shape)
    return out.astype(dtype) if dtype is not None else out


def zero3_adam_leaf_update(p_flat, g_flat, m, v, tf, *, lr, b1=0.9, b2=0.95,
                           eps=1e-8, weight_decay=0.0):
    """Adam on the flat-sharded stage-3 layout: everything device-local
    elementwise (the sharding-axis grad reduction already happened in the
    all_gather transpose), params stay sharded — no post-update gather."""
    g32 = g_flat.astype(jnp.float32)
    p32 = p_flat.astype(jnp.float32)
    m2 = b1 * m + (1 - b1) * g32
    v2 = b2 * v + (1 - b2) * g32 * g32
    mh = m2 / (1 - b1 ** tf)
    vh = v2 / (1 - b2 ** tf)
    upd = mh / (jnp.sqrt(vh) + eps)
    if weight_decay:
        upd = upd + weight_decay * p32
    return (p32 - lr * upd).astype(p_flat.dtype), m2, v2


def build_hybrid_train_step(*, topo: HybridTopology, param_specs,
                            init_params_fn, embed_fn, block_fn, head_nll_fn,
                            step_ctx_fn=None,
                            num_microbatches: int = 1,
                            learning_rate: float = 1e-4,
                            adam_betas=(0.9, 0.95), adam_eps: float = 1e-8,
                            weight_decay: float = 0.0, remat: bool = True,
                            remat_policy=None,
                            schedule: str = "1f1b",
                            num_model_chunks: int = 1,
                            sharding_stage: int = 2,
                            offload_optimizer: bool = False,
                            mp_reduce_block_leaves=frozenset()):
    """Generic fully-manual hybrid dp×mp×pp×sharding×sep train step.

    The caller provides the model as three per-device closures (all called
    INSIDE the all-axes-manual shard_map, so they may use mp/sep
    collectives from this module):

    * ``init_params_fn(seed) -> params`` — global arrays placed per
      ``param_specs``; structure must be ``{"blocks": {...stacked
      [pp, per, ...] leaves...}, <other leaves replicated over pp>}``.
    * ``embed_fn(params_local, ids_local) -> x [b_l, s_l, h]``
    * ``block_fn(layer_params_local, x, ctx) -> x`` — one transformer block
      (tensor-parallel via mp_copy/fwd_psum, cp attention inside).
    * ``head_nll_fn(params_local, x, labels_local) -> nll [b_l, s_l]`` —
      model builders pass the logits-free fused head here
      (:func:`vocab_parallel_linear_nll` /
      ``ops.fused_cross_entropy.linear_cross_entropy``); being a
      ``custom_vjp`` closure it flows unchanged through every schedule
      (gpipe scan, 1f1b/zbh1, interleave) and under remat, so no
      pipeline path ever materializes ``[b, s, V]`` logits.
    * ``step_ctx_fn(s_l) -> ctx`` (optional) — per-step loop invariants
      (e.g. rope cos/sin tables) computed ONCE outside the layer scan and
      passed to every ``block_fn`` call; ``ctx`` is None when omitted.

    The step runs the block stack through the pipeline over ``pp``
    (parallel/pipeline.py), reduces the masked last-stage loss over
    (pp, dp, sharding, sep), reduces grads over the data axes (plus pp for
    the non-block leaves, never mp — Megatron invariant), and applies
    ZeRO stage-2 Adam over the ``sharding`` axis
    (:func:`zero_adam_leaf_update`).

    ``schedule`` (pp>1 only): ``"1f1b"`` (default), ``"gpipe"``,
    ``"interleave"`` (virtual-pipeline chunks via ``num_model_chunks``),
    or ``"zbh1"`` (zero-bubble: weight-grad deferred into the drain
    bubble).  ``"1f1b"`` interleaves forward and
    recompute-backward per tick with O(pp) activation memory
    (:func:`~paddle_tpu.parallel.pipeline.spmd_pipeline_1f1b`, matching the
    reference's production 1F1B pipeline_parallel.py:547); ``"gpipe"`` is
    the fill-drain scan differentiated end-to-end (O(M) memory,
    reference FThenB).

    ``mp_reduce_block_leaves``: block-param leaf names whose grads are
    PARTIAL over mp and need a psum — used by Megatron sequence
    parallelism, where LayerNorms/biases run on the mp-sharded sequence
    (the compiled-step analog of the reference's
    register_sequence_parallel_allreduce_hooks).

    Returns ``(step_fn, init_fn)`` with
    ``step_fn(state, ids, labels) -> (state, loss)``.
    """
    import jax.numpy as _jnp
    from jax.sharding import NamedSharding
    from .pipeline import (spmd_pipeline, spmd_pipeline_1f1b,
                           spmd_pipeline_zbh1)

    if schedule not in ("1f1b", "gpipe", "interleave", "zbh1"):
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if sharding_stage not in (2, 3):
        raise ValueError(f"sharding_stage must be 2 or 3, got "
                         f"{sharding_stage}")
    mesh = topo.mesh
    S = topo.axis_size(PP_AXIS)
    dp = topo.axis_size(DP_AXIS)
    shard = topo.axis_size(SHARDING_AXIS)
    sep = topo.axis_size(SEP_AXIS)
    mp_deg = topo.axis_size(MP_AXIS)
    b1, b2 = adam_betas
    data_spec = P((DP_AXIS, SHARDING_AXIS), SEP_AXIS)

    # stage-3: params live flat-sharded at rest (same chunk layout as the
    # moments) and are all_gather'ed AT USE — per layer inside the scan,
    # so off-layer weights cost 1/shard of their size.  The AD transpose
    # of that gather is the stage-3 grad reduce-scatter for free.
    # Under the interleaved schedule blocks carry an extra chunk axis
    # ([S, v, per, ...] — vpp_block_layout), so the flat at-rest layout
    # keeps ALL leading axes between pp and the layer dims ("lead").
    vpp_deg = num_model_chunks if schedule == "interleave" else 1
    n_lead = 2 if vpp_deg > 1 else 1
    BLOCK_FLAT_SPEC = P(PP_AXIS, *((None,) * n_lead + (MP_AXIS,)),
                        SHARDING_AXIS)
    BLOCK_FLAT_SPEC_EP = P(PP_AXIS, *((None,) * n_lead + (MP_AXIS,)),
                           (DP_AXIS, SHARDING_AXIS))
    # expert-parallel leaves: the param spec shards them over dp, so their
    # grads are NOT reduced over dp (each data rank owns distinct experts)
    # and their moments/flat storage carry a dp dimension
    ep_leaves = {k for k, s in param_specs.get("blocks", {}).items()
                 if spec_has_axis(s, DP_AXIS)}
    stage3 = sharding_stage == 3
    if stage3:
        p_abs = jax.eval_shape(init_params_fn, 0)

        def _leaf_info(leaf, spec, is_block):
            ls = local_shape(leaf.shape, spec, topo)
            if is_block:
                layer = tuple(ls[1 + n_lead:])
                n = int(np.prod(layer)) or 1
                return {"local": layer, "lead": tuple(ls[1:1 + n_lead]),
                        "chunk": -(-n // shard), "dtype": leaf.dtype}
            n = int(np.prod(ls)) or 1
            return {"local": tuple(ls), "chunk": -(-n // shard),
                    "dtype": leaf.dtype}

        info = {k: _leaf_info(p_abs[k], param_specs[k], False)
                for k in p_abs if k != "blocks"}
        info["blocks"] = {k: _leaf_info(p_abs["blocks"][k],
                                        param_specs["blocks"][k], True)
                          for k in p_abs["blocks"]}
        flat_specs = {k: MOMENT_SPEC for k in p_abs if k != "blocks"}
        flat_specs["blocks"] = {k: BLOCK_FLAT_SPEC_EP if k in ep_leaves
                                else BLOCK_FLAT_SPEC
                                for k in p_abs["blocks"]}
        store_specs = flat_specs
        mom_specs = flat_specs
    else:
        store_specs = param_specs
        mom_specs = tree_map_with_spec(
            lambda _p, s: (MOMENT_SPEC_EP if spec_has_axis(s, DP_AXIS)
                           else MOMENT_SPEC),
            param_specs, param_specs)

    def sh(spec):
        return NamedSharding(mesh, spec)

    def _flat_shape(k, k2=None):
        if k2 is None:
            return (S, mp_deg, shard * info[k]["chunk"])
        dpf = dp if k2 in ep_leaves else 1
        return (S,) + info["blocks"][k2]["lead"] + (
            mp_deg, dpf * shard * info["blocks"][k2]["chunk"])

    def init_fn(seed: int = 0):
        params = init_params_fn(seed)
        if stage3:
            def pack_local(prm):
                out = {"blocks": {}}
                for k in prm:
                    if k == "blocks":
                        continue
                    out[k] = pack_leaf(prm[k], info[k]["chunk"])[None, None]
                for k, val in prm["blocks"].items():
                    inf = info["blocks"][k]
                    c = inf["chunk"]
                    lv = val[0].reshape((-1,) + inf["local"])
                    packed = jax.vmap(lambda l, c=c: pack_leaf(l, c))(lv)
                    out["blocks"][k] = packed.reshape(
                        (1,) + inf["lead"] + (1, c))
                return out

            pack = jax.jit(jax.shard_map(
                pack_local, mesh=mesh, in_specs=(param_specs,),
                out_specs=flat_specs, check_vma=False))
            params = pack(params)
            mom_shapes = {k: _flat_shape(k) for k in info if k != "blocks"}
            mom_shapes["blocks"] = {k: _flat_shape("blocks", k)
                                    for k in info["blocks"]}
        else:
            mom_shapes = tree_map_with_spec(
                lambda p, spec: moment_shape(p.shape, spec, topo),
                params, param_specs)
        zinit = jax.jit(
            lambda: tree_map_with_spec(
                lambda shp, _: _jnp.zeros(shp, _jnp.float32),
                mom_shapes, mom_specs),
            out_shardings=tree_map_with_spec(
                lambda _s, sp: sh(sp), mom_shapes, mom_specs))
        m0, v0 = zinit(), zinit()
        # t is committed to the mesh (replicated) like every other leaf:
        # a checkpoint load preserves leaf shardings, and a state whose
        # leaves mix mesh-committed and single-device-committed arrays is
        # rejected by jit
        t0 = jax.device_put(_jnp.zeros((), _jnp.int32), sh(P()))
        return {"params": params,
                "opt": {"m": m0, "v": v0, "t": t0}}

    def local_step(params, m, v, t, ids, labels):
        b_l, s_l = ids.shape
        # per-step loop invariants + the one-layer scan body, shared by
        # both schedules (ctx never depends on params, so it can live
        # outside the differentiated region)
        ctx = step_ctx_fn(s_l) if step_ctx_fn is not None else None

        def _unpack_other(prm):
            return {k: unpack_leaf(v[0, 0], info[k]["local"],
                                   info[k]["dtype"])
                    for k, v in prm.items() if k != "blocks"}

        def body(carry, layer_params):
            if stage3:
                layer_params = {
                    k: unpack_leaf(v.reshape(-1),
                                   info["blocks"][k]["local"],
                                   info["blocks"][k]["dtype"])
                    for k, v in layer_params.items()}
            return block_fn(layer_params, carry, ctx), None

        def run_stack(x, blk, use_remat):
            """The per-stage layer stack.  Stage 2 scans (one traced
            block); stage 3 UNROLLS so each layer's weight all_gather is a
            distinct collective — a scanned gather is one HLO op executed
            per iteration with no cross-iteration data dependence, which
            XLA overlaps: on TPU that just prefetches weights early, but
            XLA:CPU's in-process rendezvous aborts on the repeated joins.
            Unrolling also lets the TPU scheduler hide each gather behind
            the previous layer's compute (the stage-3 prefetch pattern,
            reference group_sharded_stage3 _prefetch)."""
            if stage3:
                def one(c, lp):
                    return body(c, lp)[0]

                fn = remat_wrap(one, use_remat, remat_policy)
                per = next(iter(blk.values())).shape[0]
                for i in range(per):
                    x = fn(x, {k: lax.index_in_dim(v, i, 0, keepdims=False)
                               for k, v in blk.items()})
                return x
            sbody = remat_wrap(body, use_remat, remat_policy)
            x, _ = lax.scan(sbody, x, blk)
            return x

        def loss_fn(params):
            if stage3:
                params = dict(_unpack_other(params),
                              blocks=params["blocks"])
            x = embed_fn(params, ids)
            hdim = x.shape[-1]
            blk = {k: val[0] for k, val in params["blocks"].items()}

            if S > 1:
                M = num_microbatches
                mbs = x.reshape(M, b_l // M, s_l, hdim)

                def stage_fn(blk_local, hcarry):
                    # spmd_pipeline applies its own remat around the stage
                    return run_stack(hcarry, blk_local,
                                     use_remat=stage3 and remat)

                outs = spmd_pipeline(stage_fn, blk, mbs, S, remat=remat,
                                     remat_policy=remat_policy)
                x = outs.reshape(b_l, s_l, hdim)
            else:
                x = run_stack(x, blk, use_remat=remat)

            nll = head_nll_fn(params, x, labels)
            # loss lives on the LAST pp stage only (other stages computed
            # the head on zeros); psum with the mask so grads flow to
            # exactly one stage's head and the scalar is replicated.
            is_last = (lax.axis_index(PP_AXIS) == S - 1)
            total = fwd_psum(
                jnp.sum(nll) * is_last.astype(nll.dtype),
                (PP_AXIS, DP_AXIS, SHARDING_AXIS, SEP_AXIS))
            return total / (b_l * s_l * dp * shard * sep)

        norm = b_l * s_l * dp * shard * sep
        if S > 1 and schedule == "interleave":
            from .pipeline import spmd_pipeline_interleaved
            M = num_microbatches
            n_chunks = num_model_chunks
            other = {k: val for k, val in params.items() if k != "blocks"}
            blk = {k: val[0] for k, val in params["blocks"].items()}
            ids_mb = ids.reshape(M, b_l // M, s_l)
            labels_mb = labels.reshape(M, b_l // M, s_l)

            def mb_fn_v(other_p, blk_c, x_in, ids1, labels1, first, last):
                if stage3:
                    other_p = _unpack_other(other_p)
                p = dict(other_p, blocks=None)
                x0 = embed_fn(p, ids1)
                x = jnp.where(first, x0, x_in)
                y = run_stack(x, blk_c, use_remat=remat)
                nll = head_nll_fn(p, y, labels1)
                return y, jnp.sum(nll) * last.astype(nll.dtype)

            def _embed_probe_v(o, i):
                if stage3:
                    o = _unpack_other(o)
                return embed_fn(dict(o, blocks=None), i)

            xa = jax.eval_shape(_embed_probe_v, other, ids_mb[0])
            nll_sum, d_other, d_blk = spmd_pipeline_interleaved(
                mb_fn_v, other, blk, ids_mb, labels_mb, xa.shape, xa.dtype,
                S, n_chunks)
            loss = fwd_psum(nll_sum,
                            (PP_AXIS, DP_AXIS, SHARDING_AXIS, SEP_AXIS))                 / norm
            grads = {k: g / norm for k, g in d_other.items()}
            grads["blocks"] = {k: g[None] / norm for k, g in d_blk.items()}
        elif S > 1 and schedule in ("1f1b", "zbh1"):
            M = num_microbatches
            other = {k: v for k, v in params.items() if k != "blocks"}
            blk = {k: v[0] for k, v in params["blocks"].items()}
            ids_mb = ids.reshape(M, b_l // M, s_l)
            labels_mb = labels.reshape(M, b_l // M, s_l)

            def mb_fn(other_p, blk_p, x_in, ids1, labels1):
                if stage3:
                    other_p = _unpack_other(other_p)
                p = dict(other_p, blocks=None)
                x0 = embed_fn(p, ids1)
                x = jnp.where(lax.axis_index(PP_AXIS) == 0, x0, x_in)
                y = run_stack(x, blk_p, use_remat=remat)
                nll = head_nll_fn(p, y, labels1)
                last = (lax.axis_index(PP_AXIS) == S - 1)
                return y, jnp.sum(nll) * last.astype(nll.dtype)

            def _embed_probe(o, i):
                if stage3:
                    o = _unpack_other(o)
                return embed_fn(dict(o, blocks=None), i)

            xa = jax.eval_shape(_embed_probe, other, ids_mb[0])
            sched_fn = spmd_pipeline_1f1b if schedule == "1f1b" \
                else spmd_pipeline_zbh1
            nll_sum, d_other, d_blk = sched_fn(
                mb_fn, other, blk, ids_mb, labels_mb,
                xa.shape, xa.dtype, S)
            loss = fwd_psum(nll_sum,
                            (PP_AXIS, DP_AXIS, SHARDING_AXIS, SEP_AXIS)) \
                / norm
            grads = {k: v / norm for k, v in d_other.items()}
            grads["blocks"] = {k: v[None] / norm for k, v in d_blk.items()}
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params)
        t2 = t + 1
        tf = t2.astype(_jnp.float32)

        def upd(is_blocks, p, g, m_leaf, v_leaf, mp_partial=False,
                ep=False):
            # data-axis grad reduction; non-block leaves are replicated
            # over pp (stage0 embeds, last stage heads) so sum over pp
            # too.  NEVER over mp (mp-replicated params get full grads
            # via mp_copy's bwd psum, mp-sharded ones are local) — except
            # sequence-parallel leaves, whose activations were mp-sharded
            # along seq so each rank saw only its tokens.  Expert leaves
            # (``ep``) skip the dp reduction: each data rank's expert
            # grads are complete after the all_to_all routing round-trip.
            red = ((SEP_AXIS,) if ep else (DP_AXIS, SEP_AXIS)) \
                if is_blocks else (PP_AXIS, DP_AXIS, SEP_AXIS)
            if mp_partial:
                red = red + (MP_AXIS,)
            g = lax.psum(g, red)
            if stage3:
                # flat layout end to end: the sharding-axis reduction
                # already happened in the unpack_leaf transpose
                return zero3_adam_leaf_update(
                    p, g, m_leaf, v_leaf, tf, lr=learning_rate, b1=b1,
                    b2=b2, eps=adam_eps, weight_decay=weight_decay)
            p2, m2, v2 = zero_adam_leaf_update(
                p, g, m_leaf.reshape(-1), v_leaf.reshape(-1), tf,
                lr=learning_rate, b1=b1, b2=b2, eps=adam_eps,
                weight_decay=weight_decay)
            return p2, m2.reshape(m_leaf.shape), v2.reshape(v_leaf.shape)

        new_p = dict(blocks={})
        new_m = dict(blocks={})
        new_v = dict(blocks={})
        for k in params:
            if k == "blocks":
                continue
            new_p[k], new_m[k], new_v[k] = upd(
                False, params[k], grads[k], m[k], v[k])
        for k in params["blocks"]:
            (new_p["blocks"][k], new_m["blocks"][k],
             new_v["blocks"][k]) = upd(
                True, params["blocks"][k], grads["blocks"][k],
                m["blocks"][k], v["blocks"][k],
                mp_partial=k in mp_reduce_block_leaves,
                ep=k in ep_leaves)
        return new_p, new_m, new_v, t2, loss

    shd = jax.shard_map(
        local_step, mesh=mesh,
        in_specs=(store_specs, mom_specs, mom_specs, P(), data_spec,
                  data_spec),
        out_specs=(store_specs, mom_specs, mom_specs, P(), P()),
        check_vma=False)

    def step(state, ids, labels):
        p2, m2, v2, t2, loss = shd(state["params"], state["opt"]["m"],
                                   state["opt"]["v"], state["opt"]["t"],
                                   ids, labels)
        return {"params": p2, "opt": {"m": m2, "v": v2, "t": t2}}, loss

    step_fn = jax.jit(step, donate_argnums=(0,))
    if offload_optimizer:
        mom_shardings = tree_map_with_spec(lambda _s, sp: sh(sp),
                                           mom_specs, mom_specs)
        return _offload_opt_state(step_fn, init_fn,
                                  {"m": mom_shardings, "v": mom_shardings})
    return step_fn, init_fn


def _offload_opt_state(step_fn, init_fn, mom_shardings):
    """Optimizer-state host offload (reference group_sharded offload=True /
    sharding_offload: fp32 moments live in HOST RAM between steps and are
    shipped to the device around each update).  The explicit
    device_put/device_get pair outside jit is the backend-portable form of
    the reference's pinned-memory optimizer; the per-step transfer is the
    price of the HBM savings, exactly as in the reference."""
    import numpy as _np

    def init2(seed: int = 0):
        state = init_fn(seed)
        opt = state["opt"]
        host = {"m": jax.tree.map(lambda a: _np.asarray(a), opt["m"]),
                "v": jax.tree.map(lambda a: _np.asarray(a), opt["v"])}
        state["opt"] = {"m": host["m"], "v": host["v"], "t": opt["t"]}
        return state

    def step2(state, ids, labels):
        # shardings come from the builder's moment specs, so a state
        # restored from a checkpoint (no init_fn call) steps fine
        sh = mom_shardings
        dev_state = {
            "params": state["params"],
            "opt": {"m": jax.tree.map(jax.device_put, state["opt"]["m"],
                                      sh["m"]),
                    "v": jax.tree.map(jax.device_put, state["opt"]["v"],
                                      sh["v"]),
                    "t": state["opt"]["t"]},
        }
        new_state, loss = step_fn(dev_state, ids, labels)
        new_state["opt"] = {
            "m": jax.tree.map(lambda a: _np.asarray(a),
                              new_state["opt"]["m"]),
            "v": jax.tree.map(lambda a: _np.asarray(a),
                              new_state["opt"]["v"]),
            "t": new_state["opt"]["t"]}
        return new_state, loss

    return step2, init2


def local_shape(shape: Tuple[int, ...], spec: P,
                topo: HybridTopology) -> Tuple[int, ...]:
    """Device-local shape of a global array laid out with ``spec``."""
    out = list(shape)
    for i, ax in enumerate(tuple(spec)[:len(out)]):
        if ax is None:
            continue
        for a in (ax if isinstance(ax, tuple) else (ax,)):
            size = topo.axis_size(a)
            if out[i] % size != 0:
                raise ValueError(
                    f"dim {i} of {shape} not divisible by {a}={size}")
            out[i] //= size
    return tuple(out)


def moment_shape(param_shape: Tuple[int, ...], spec: P,
                 topo: HybridTopology) -> Tuple[int, int, int]:
    """Global shape of the flat ZeRO moment buffer for one param leaf:
    [pp, mp, shard*chunk] with chunk = ceil(local_numel/shard).  Expert
    (dp-sharded) leaves get a dp factor on the flat dim to match
    MOMENT_SPEC_EP — each data rank's experts carry their own moments."""
    n = int(np.prod(local_shape(param_shape, spec, topo))) or 1
    shard = topo.axis_size(SHARDING_AXIS)
    chunk = -(-n // shard)
    dpf = topo.axis_size(DP_AXIS) if spec_has_axis(spec, DP_AXIS) else 1
    return (topo.axis_size(PP_AXIS), topo.axis_size(MP_AXIS),
            dpf * shard * chunk)


def tree_map_with_spec(fn, tree, specs):
    """tree_map over a nested dict whose spec tree has PartitionSpec leaves
    (PartitionSpec is tuple-like, so jax.tree.map can't be trusted here)."""
    if isinstance(tree, dict):
        return {k: tree_map_with_spec(fn, tree[k], specs[k]) for k in tree}
    return fn(tree, specs)
