"""Hybrid-parallel topology over a device mesh.

Analog of the reference's ``CommunicateTopology`` / ``HybridCommunicateGroup``
(/root/reference/python/paddle/distributed/fleet/base/topology.py:65,178) —
a cartesian rank grid over axes ["data","pipe","sharding","sep","model"] with
nesting order pp→mp→sep→sharding→dp (topology.py:290).

TPU-native: the rank grid IS a ``jax.sharding.Mesh`` with named axes
("pp","mp","sep","sharding","dp"); each reference "comm group" becomes a mesh
axis name usable in PartitionSpecs / shard_map collectives — no process
groups, no NCCL rings, no TCPStore.  Axis order follows the reference's
nesting so that mp lives on the innermost (fastest ICI) dimension.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["HybridTopology", "get_topology", "set_topology", "init_topology",
           "DP_AXIS", "SHARDING_AXIS", "SEP_AXIS", "MP_AXIS", "PP_AXIS"]

PP_AXIS = "pp"
MP_AXIS = "mp"
SEP_AXIS = "sep"
SHARDING_AXIS = "sharding"
DP_AXIS = "dp"

# Nesting order mirrors the reference (pp outermost … dp innermost is the
# reference's order reversed: reference nests pp→mp→sep→sharding→dp with dp
# slowest-varying; mesh-wise, we put pp on the outermost (DCN-friendly) axis
# and mp innermost (ICI-adjacent chips).
AXIS_ORDER = (PP_AXIS, DP_AXIS, SHARDING_AXIS, SEP_AXIS, MP_AXIS)


class HybridTopology:
    """Device mesh with the five hybrid-parallel axes.

    degrees: dict axis→size; missing axes default to 1.  Total must divide
    the available device count (or equal it).
    """

    def __init__(self, dp: int = 1, mp: int = 1, pp: int = 1, sep: int = 1,
                 sharding: int = 1, devices: Optional[Sequence] = None):
        self.degrees: Dict[str, int] = {
            PP_AXIS: pp, DP_AXIS: dp, SHARDING_AXIS: sharding,
            SEP_AXIS: sep, MP_AXIS: mp,
        }
        devices = list(devices) if devices is not None else jax.devices()
        total = int(np.prod([self.degrees[a] for a in AXIS_ORDER]))
        if total > len(devices):
            raise ValueError(
                f"topology needs {total} devices, only {len(devices)} present")
        devices = devices[:total]
        shape = tuple(self.degrees[a] for a in AXIS_ORDER)
        dev_array = np.asarray(devices).reshape(shape)
        self.mesh = Mesh(dev_array, AXIS_ORDER)

    # ------------------------------------------------------------------
    # reference-API parity (HybridCommunicateGroup)
    # ------------------------------------------------------------------
    def get_data_parallel_world_size(self) -> int:
        return self.degrees[DP_AXIS]

    def get_model_parallel_world_size(self) -> int:
        return self.degrees[MP_AXIS]

    def get_pipe_parallel_world_size(self) -> int:
        return self.degrees[PP_AXIS]

    def get_sharding_parallel_world_size(self) -> int:
        return self.degrees[SHARDING_AXIS]

    def get_sep_parallel_world_size(self) -> int:
        return self.degrees[SEP_AXIS]

    def axis_size(self, axis: str) -> int:
        return self.degrees[axis]

    @property
    def world_size(self) -> int:
        return int(np.prod(list(self.degrees.values())))

    def spec(self, *axes) -> PartitionSpec:
        return PartitionSpec(*axes)

    def sharding(self, *axes) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec(*axes))

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def data_axes(self) -> Tuple[str, ...]:
        """Axes over which the batch dim is split (dp + sharding, the
        reference's fused dp-sharding group for grad sync)."""
        axes = tuple(a for a in (DP_AXIS, SHARDING_AXIS)
                     if self.degrees[a] > 1)
        return axes or (DP_AXIS,)

    def active_axes(self) -> List[str]:
        return [a for a in AXIS_ORDER if self.degrees[a] > 1]

    def __repr__(self):
        d = {k: v for k, v in self.degrees.items() if v > 1}
        return f"HybridTopology({d or 'single-device'}, mesh={self.mesh.shape})"


_topology: Optional[HybridTopology] = None


def set_topology(topo: HybridTopology) -> HybridTopology:
    global _topology
    _topology = topo
    return topo


def get_topology() -> HybridTopology:
    global _topology
    if _topology is None:
        _topology = HybridTopology()
    return _topology


def init_topology(dp: int = 1, mp: int = 1, pp: int = 1, sep: int = 1,
                  sharding: int = 1, devices=None) -> HybridTopology:
    return set_topology(HybridTopology(dp=dp, mp=mp, pp=pp, sep=sep,
                                       sharding=sharding, devices=devices))
