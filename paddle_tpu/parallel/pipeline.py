"""Pipeline parallelism.

Reference: fleet/meta_parallel — ``PipelineLayer`` pp_layers.py:257
(``LayerDesc`` :56, ``SharedLayerDesc`` :76), runtime ``PipelineParallel``
pipeline_parallel.py:231 with 1F1B ``forward_backward_pipeline`` :547 and
interleaved VPP :1138, p2p via partial send/recv ops.

TPU-native design: the transformer block stack is *stacked* — one params
pytree with leading dim [num_stages, layers_per_stage, ...] sharded over the
``pp`` mesh axis — and the schedule is a ``lax.scan`` under ``shard_map``:
each scan step every stage applies its block to its current microbatch and
rotates activations to the next stage with ``lax.ppermute`` (the partial
send/recv ops dissolve into one ICI collective-permute per step).  Autodiff
through the scan gives the backward pipeline for free (ppermute's VJP is the
reverse permute), with per-stage rematerialization via ``jax.checkpoint``
bounding activation memory like 1F1B.  The reference needed an actor runtime
(fleet_executor) + five schedule passes for this; here it is ~100 lines that
XLA software-pipelines.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer, functional_call
from .topology import PP_AXIS, get_topology

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "spmd_pipeline",
           "spmd_pipeline_1f1b", "spmd_pipeline_interleaved", "spmd_pipeline_zbh1", "pipeline_stack_specs"]


class LayerDesc:
    """Deferred layer construction (reference pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer across stages (reference pp_layers.py:76, used for
    tied embeddings).  On TPU tying is a pytree aliasing decision: the tied
    weight lives outside the pipelined stack, replicated (or mp-sharded)
    across pp, so no gradient all-reduce between first/last stage is
    needed — XLA sums the contributions."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def spmd_pipeline(stage_fn: Callable, stage_params: Any, microbatches,
                  num_stages: int, axis_name: str = PP_AXIS,
                  remat: bool = True, remat_policy=None):
    """Run the scan-pipeline INSIDE a shard_map over ``axis_name``.

    stage_fn(params_local, x) -> y : one pipeline stage's computation
    stage_params: params pytree with leading stage dim already sliced to the
      local stage (shard_map does the slicing via in_specs)
    microbatches: [M, mb, ...] array, same on every stage (in_specs P(None))
    returns [M, mb, ...] outputs valid on the LAST stage (callers psum or
      ppermute them home).
    """
    M = microbatches.shape[0]
    S = num_stages
    stage = jax.lax.axis_index(axis_name)
    from .remat import remat_wrap
    fn = remat_wrap(stage_fn, remat, remat_policy)

    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def step(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others take the
        # rotated activation from the previous stage
        mb = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb, state)
        y = fn(stage_params, x)
        # last stage writes its finished microbatch t-(S-1)
        out_idx = t - (S - 1)
        write = (stage == S - 1) & (out_idx >= 0)
        outputs = jax.lax.cond(
            write,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), axis=0),
            lambda o: o, outputs)
        # rotate activations forward one stage
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(step, (state, outputs),
                                       jnp.arange(M + S - 1))
    return outputs


def spmd_pipeline_1f1b(mb_fn, other_params, blk_params, ids_mb, labels_mb,
                       x_shape, x_dtype, num_stages: int,
                       axis_name: str = PP_AXIS):
    """1F1B-class pipeline schedule with manually-interleaved backward.

    Matches the MEMORY behavior of the reference's 1F1B runtime
    (fleet/meta_parallel/pipeline_parallel.py:547): peak activation storage
    is O(num_stages) in-flight microbatch *stage inputs*, independent of the
    microbatch count M — unlike differentiating through the GPipe fill-drain
    scan (:func:`spmd_pipeline`), whose saved residuals grow O(M).

    Design (runs INSIDE an all-manual shard_map over ``axis_name``):
    one ``lax.scan`` over T = M + 2(S-1) combined ticks.  Each tick every
    stage
      1. runs one forward microbatch (F of mb ``m`` at tick ``s + m``),
         saving only its INPUT into a circular buffer of 2S slots,
      2. ppermutes the activation forward,
      3. runs one backward microbatch (B of mb ``m`` at tick
         ``2(S-1) - s + m``) by re-running the forward from the saved input
         under ``jax.vjp`` (recompute, like the reference's
         recompute+1F1B combination) and accumulating fp32 grads,
      4. ppermutes the input-cotangent backward.
    The tick scan itself is never differentiated, so NO scan residuals are
    kept — the only activation state is the 2S-slot buffer and the two
    message buffers.  Inactive (bubble) slots compute on zeros and their
    writes are masked out.

    ``mb_fn(other_params, blk_params, x_in, ids1, labels1) -> (y, nll_sum)``
    must: use ``x_in`` only when ``lax.axis_index(axis_name) > 0`` (stage 0
    embeds ``ids1`` itself), and mask ``nll_sum`` to the LAST stage.

    Returns ``(nll_total, d_other, d_blk)``: the summed (unnormalized) NLL
    — nonzero on the last stage only — and fp32 grad pytrees matching
    ``other_params`` / ``blk_params``.
    """
    M = ids_mb.shape[0]
    S = num_stages
    T = M + 2 * (S - 1)
    BUF = 2 * S
    stage = jax.lax.axis_index(axis_name)
    is_last = stage == S - 1
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_bwd = [(i, (i - 1) % S) for i in range(S)]

    f32 = functools.partial(jax.tree.map,
                            lambda p: jnp.zeros(p.shape, jnp.float32))
    x0 = jnp.zeros(x_shape, x_dtype)
    carry0 = (
        jnp.zeros((BUF,) + x_shape, x_dtype),       # saved stage inputs
        x0,                                         # fwd activation message
        x0,                                         # bwd cotangent message
        f32(other_params), f32(blk_params),         # grad accumulators
        jnp.zeros((), jnp.float32),                 # nll accumulator
    )

    def masked_add(acc, g, on):
        return jax.tree.map(
            lambda a, gg: a + jnp.where(on, gg.astype(jnp.float32), 0.0),
            acc, g)

    def tick(carry, t):
        x_save, y_msg, dx_msg, d_other, d_blk, nll_acc = carry

        # ---- forward phase: F(stage, m_f) at tick t = stage + m_f ----
        m_f = t - stage
        on_f = (m_f >= 0) & (m_f < M)
        m_fc = jnp.clip(m_f, 0, M - 1)
        ids_f = jax.lax.dynamic_index_in_dim(ids_mb, m_fc, 0, keepdims=False)
        lab_f = jax.lax.dynamic_index_in_dim(labels_mb, m_fc, 0,
                                             keepdims=False)
        y_f, nll_f = mb_fn(other_params, blk_params, y_msg, ids_f, lab_f)
        x_save = jnp.where(
            on_f,
            jax.lax.dynamic_update_index_in_dim(x_save, y_msg, m_fc % BUF, 0),
            x_save)
        nll_acc = nll_acc + jnp.where(on_f, nll_f.astype(jnp.float32), 0.0)
        y_msg = jax.lax.ppermute(y_f, axis_name, perm_fwd)

        # ---- backward phase: B(stage, m_b) at t = 2(S-1) - stage + m_b ----
        m_b = t - (2 * (S - 1) - stage)
        on_b = (m_b >= 0) & (m_b < M)
        m_bc = jnp.clip(m_b, 0, M - 1)
        ids_b = jax.lax.dynamic_index_in_dim(ids_mb, m_bc, 0, keepdims=False)
        lab_b = jax.lax.dynamic_index_in_dim(labels_mb, m_bc, 0,
                                             keepdims=False)
        x_b = jax.lax.dynamic_index_in_dim(x_save, m_bc % BUF, 0,
                                           keepdims=False)
        _, pull = jax.vjp(
            lambda o, b, x: mb_fn(o, b, x, ids_b, lab_b),
            other_params, blk_params, x_b)
        # last stage: y is not consumed downstream (the head ate x), so its
        # cotangent is zero; the loss cotangent is 1 (mb_fn masks nll_sum
        # to the last stage, so interior stages get zero head/embed grads
        # through the same pullback).
        dy = jnp.where(is_last, jnp.zeros_like(dx_msg), dx_msg)
        go, gb, dx = pull((dy, jnp.ones((), nll_f.dtype)))
        d_other = masked_add(d_other, go, on_b)
        d_blk = masked_add(d_blk, gb, on_b)
        dx_msg = jax.lax.ppermute(dx, axis_name, perm_bwd)

        return (x_save, y_msg, dx_msg, d_other, d_blk, nll_acc), None

    (x_save, y_msg, dx_msg, d_other, d_blk, nll_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(T))
    return nll_acc, d_other, d_blk


def pipeline_stack_specs(param_tree, axis_name: str = PP_AXIS):
    """PartitionSpec for a stacked stage-param pytree: leading dim over pp."""
    return jax.tree.map(
        lambda v: P(axis_name, *([None] * (np.ndim(v) - 1))), param_tree)


class PipelineLayer(Layer):
    """API-parity container (reference pp_layers.py:257).

    Built from LayerDescs, segmented into ``num_stages`` contiguous chunks
    (seg_method="uniform" — layer-count balanced, matching the reference's
    default :113).  Eager forward runs all stages sequentially (single
    program semantics); the DistributedEngine detects a PipelineLayer and
    can lower the homogeneous block stack through :func:`spmd_pipeline`.
    """

    def __init__(self, layers: List[LayerDesc], num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, name=None):
        super().__init__()
        topo = topology or get_topology()
        self.num_stages = num_stages or topo.get_pipe_parallel_world_size()
        self.descs = list(layers)
        from ..nn.layer.container import LayerList
        built = []
        self.shared_layers = {}
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key in self.shared_layers:
                    layer = self.shared_layers[d.key]
                else:
                    layer = d.build()
                    self.shared_layers[d.key] = layer
                built.append((layer, getattr(d, "forward_func", None)))
            elif isinstance(d, LayerDesc):
                built.append((d.build(), None))
            else:
                built.append((d, None))
        self.runs = built
        self.stack = LayerList([l for l, _ in built])
        # uniform segmentation with remainder spread over leading stages
        # (reference seg_method="uniform", pp_layers.py:113-134)
        n = len(built)
        base, rem = divmod(n, self.num_stages)
        bounds = [0]
        for i in range(self.num_stages):
            bounds.append(bounds[-1] + base + (1 if i < rem else 0))
        self.segments = list(zip(bounds[:-1], bounds[1:]))
        self.recompute_interval = recompute_interval

    def forward(self, x):
        for layer, ffn in self.runs:
            x = ffn(layer, x) if ffn is not None else layer(x)
        return x

    def get_stage_layers(self, stage: int):
        lo, hi = self.segments[stage]
        return [l for l, _ in self.runs[lo:hi]]


def spmd_pipeline_interleaved(mb_fn_v, other_params, blk_params, ids_mb,
                              labels_mb, x_shape, x_dtype, num_stages: int,
                              num_chunks: int, axis_name: str = PP_AXIS):
    """Interleaved (virtual-pipeline / VPP) 1F1B schedule.

    Reference: pipeline_parallel.py:1138 ``_forward_backward_pipeline``'s
    interleaved mode + pipeline_scheduler_pass VPP — each physical stage
    hosts ``num_chunks`` model chunks, so the virtual pipeline has
    ``Sv = S * v`` stages and the warmup/drain bubble shrinks ~1/v.

    Layout: virtual stage ``vs`` lives on device ``vs % S`` as chunk
    ``vs // S``; consecutive virtual stages are therefore ALWAYS on
    ring-adjacent devices, so each chunk's activations ride the same +1
    ppermute ring, with the device-(S-1) → device-0 hop also advancing the
    chunk index (handled by shifting the send stream below).

    ``mb_fn_v(other, blk_chunk, x_in, ids, labels, first, last)`` runs ONE
    chunk: ``first``/``last`` say whether this (device, chunk) is virtual
    stage 0 (embed instead of consuming ``x_in``) / Sv-1 (head + nll).
    ``blk_params`` leaves are stacked ``[v, per_chunk, ...]`` device-local.

    Same memory design as :func:`spmd_pipeline_1f1b`: the tick scan is not
    differentiated; backward recomputes each chunk-forward from its saved
    input (buffer of 2*Sv slots per chunk).
    """
    M = ids_mb.shape[0]
    S = num_stages
    v = num_chunks
    Sv = S * v
    T = M + 2 * (Sv - 1)
    BUF = 2 * Sv
    stage = jax.lax.axis_index(axis_name)
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_bwd = [(i, (i - 1) % S) for i in range(S)]
    is_last_dev = stage == S - 1
    is_first_dev = stage == 0

    f32 = functools.partial(jax.tree.map,
                            lambda p: jnp.zeros(p.shape, jnp.float32))
    x0 = jnp.zeros(x_shape, x_dtype)
    chunk_blk = [jax.tree.map(lambda l, c=c: l[c], blk_params)
                 for c in range(v)]

    carry0 = (
        jnp.zeros((v, BUF) + x_shape, x_dtype),    # saved chunk inputs
        jnp.zeros((v,) + x_shape, x_dtype),        # fwd messages per chunk
        jnp.zeros((v,) + x_shape, x_dtype),        # bwd messages per chunk
        f32(other_params), f32(blk_params),
        jnp.zeros((), jnp.float32),
    )

    def masked_add(acc, g, on):
        return jax.tree.map(
            lambda a, gg: a + jnp.where(on, gg.astype(jnp.float32), 0.0),
            acc, g)

    def tick(carry, t):
        x_save, y_msg, dx_msg, d_other, d_blk, nll_acc = carry

        new_y = []
        for c in range(v):
            vs = stage + S * c
            m_f = t - vs
            on_f = (m_f >= 0) & (m_f < M)
            m_fc = jnp.clip(m_f, 0, M - 1)
            ids_f = jax.lax.dynamic_index_in_dim(ids_mb, m_fc, 0,
                                                 keepdims=False)
            lab_f = jax.lax.dynamic_index_in_dim(labels_mb, m_fc, 0,
                                                 keepdims=False)
            first = is_first_dev & (c == 0)
            last = is_last_dev & (c == v - 1)
            y_c, nll_c = mb_fn_v(other_params, chunk_blk[c], y_msg[c],
                                 ids_f, lab_f, first, last)
            x_save = jnp.where(
                on_f,
                x_save.at[c].set(jax.lax.dynamic_update_index_in_dim(
                    x_save[c], y_msg[c], m_fc % BUF, 0)),
                x_save)
            nll_acc = nll_acc + jnp.where(on_f, nll_c.astype(jnp.float32),
                                          0.0)
            new_y.append(y_c)

        # device S-1's output on chunk c feeds device 0's chunk c+1: shift
        # the send stream down by one chunk there so every stream rides
        # the same +1 ring
        sends = [jnp.where(is_last_dev,
                           new_y[c - 1] if c > 0 else jnp.zeros_like(x0),
                           new_y[c]) for c in range(v)]
        y_msg = jnp.stack(
            [jax.lax.ppermute(s, axis_name, perm_fwd) for s in sends])

        new_dx = []
        for c in range(v):
            vs = stage + S * c
            m_b = t - (2 * (Sv - 1) - vs)
            on_b = (m_b >= 0) & (m_b < M)
            m_bc = jnp.clip(m_b, 0, M - 1)
            ids_b = jax.lax.dynamic_index_in_dim(ids_mb, m_bc, 0,
                                                 keepdims=False)
            lab_b = jax.lax.dynamic_index_in_dim(labels_mb, m_bc, 0,
                                                 keepdims=False)
            x_b = jax.lax.dynamic_index_in_dim(x_save[c], m_bc % BUF, 0,
                                               keepdims=False)
            first = is_first_dev & (c == 0)
            last = is_last_dev & (c == v - 1)
            _, pull = jax.vjp(
                lambda o, b, x: mb_fn_v(o, b, x, ids_b, lab_b, first,
                                        last),
                other_params, chunk_blk[c], x_b)
            # cotangent of this chunk's output: the final virtual stage's
            # head consumed its own activation (dy = 0); device S-1's
            # other chunks read the NEXT chunk stream from device 0
            dy_c = jnp.where(is_last_dev,
                             dx_msg[c + 1] if c < v - 1
                             else jnp.zeros_like(x0),
                             dx_msg[c])
            go, gb_c, dx = pull((dy_c, jnp.ones((), jnp.float32)))
            d_other = masked_add(d_other, go, on_b)
            d_blk = jax.tree.map(
                lambda a, gg, c=c, on=on_b: a.at[c].add(
                    jnp.where(on, gg.astype(jnp.float32), 0.0)),
                d_blk, gb_c)
            new_dx.append(dx)

        dx_msg = jnp.stack(
            [jax.lax.ppermute(d, axis_name, perm_bwd) for d in new_dx])

        return (x_save, y_msg, dx_msg, d_other, d_blk, nll_acc), None

    (x_save, y_msg, dx_msg, d_other, d_blk, nll_acc), _ = jax.lax.scan(
        tick, carry0, jnp.arange(T))
    return nll_acc, d_other, d_blk


def spmd_pipeline_zbh1(mb_fn, other_params, blk_params, ids_mb, labels_mb,
                       x_shape, x_dtype, num_stages: int,
                       axis_name: str = PP_AXIS):
    """ZBH1 zero-bubble-class schedule (reference
    pipeline_scheduler_pass ZBH1, Qi et al. arXiv:2401.10241): the
    backward splits into **B** (activation gradient — the only part on the
    pipeline's critical path, since dx must ppermute upstream) and **W**
    (weight gradient — no inter-stage dependence), and W is deferred S
    ticks to run inside what would otherwise be the drain bubble.

    Same recompute design as :func:`spmd_pipeline_1f1b` (tick scan never
    differentiated).  The compute split is real under XLA: the B phase
    pulls only the input cotangent, so dead-code elimination drops the
    wgrad outer-product matmuls from that executable; W pulls only the
    param cotangents.  Cost of the split in this remat design: the chunk
    forward is recomputed in both phases (+1 fwd per microbatch vs 1F1B) —
    the schedule buys bubble time with FLOPs, profitable when the bubble
    fraction (S-1)/M is large.

    Extra state vs 1F1B: the output-cotangent W-queue (``S+1`` slots —
    a cotangent lives exactly S ticks between its B and W) on top of the
    deeper ``3S``-slot input buffer (an input must survive from its F tick
    to its W tick, up to 3S-2 ticks on stage 0).
    """
    M = ids_mb.shape[0]
    S = num_stages
    T = M + 2 * (S - 1) + S          # +S ticks to drain the deferred Ws
    # a saved input must survive from its F tick (stage+m) to its W tick
    # (2(S-1)-stage+m+S): up to 3S-2 ticks on stage 0
    BUF = 3 * S
    DBUF = S + 1          # dy lives exactly S ticks (B tick -> W tick)
    stage = jax.lax.axis_index(axis_name)
    is_last = stage == S - 1
    perm_fwd = [(i, (i + 1) % S) for i in range(S)]
    perm_bwd = [(i, (i - 1) % S) for i in range(S)]

    f32 = functools.partial(jax.tree.map,
                            lambda p: jnp.zeros(p.shape, jnp.float32))
    x0 = jnp.zeros(x_shape, x_dtype)
    carry0 = (
        jnp.zeros((BUF,) + x_shape, x_dtype),   # saved stage inputs (fwd)
        jnp.zeros((DBUF,) + x_shape, x_dtype),  # W queue: dy per microbatch
        x0, x0,                                 # fwd / bwd messages
        f32(other_params), f32(blk_params),
        jnp.zeros((), jnp.float32),
    )

    def masked_add(acc, g, on):
        return jax.tree.map(
            lambda a, gg: a + jnp.where(on, gg.astype(jnp.float32), 0.0),
            acc, g)

    def tick(carry, t):
        x_save, dy_save, y_msg, dx_msg, d_other, d_blk, nll_acc = carry

        # ---- F(stage, m) at t = stage + m --------------------------------
        m_f = t - stage
        on_f = (m_f >= 0) & (m_f < M)
        m_fc = jnp.clip(m_f, 0, M - 1)
        ids_f = jax.lax.dynamic_index_in_dim(ids_mb, m_fc, 0, keepdims=False)
        lab_f = jax.lax.dynamic_index_in_dim(labels_mb, m_fc, 0,
                                             keepdims=False)
        y_f, nll_f = mb_fn(other_params, blk_params, y_msg, ids_f, lab_f)
        x_save = jnp.where(
            on_f,
            jax.lax.dynamic_update_index_in_dim(x_save, y_msg, m_fc % BUF,
                                                0),
            x_save)
        nll_acc = nll_acc + jnp.where(on_f, nll_f.astype(jnp.float32), 0.0)
        y_msg = jax.lax.ppermute(y_f, axis_name, perm_fwd)

        # ---- B(stage, m) at t = 2(S-1) - stage + m: dgrad only -----------
        m_b = t - (2 * (S - 1) - stage)
        on_b = (m_b >= 0) & (m_b < M)
        m_bc = jnp.clip(m_b, 0, M - 1)
        ids_b = jax.lax.dynamic_index_in_dim(ids_mb, m_bc, 0, keepdims=False)
        lab_b = jax.lax.dynamic_index_in_dim(labels_mb, m_bc, 0,
                                             keepdims=False)
        x_b = jax.lax.dynamic_index_in_dim(x_save, m_bc % BUF, 0,
                                           keepdims=False)
        dy = jnp.where(is_last, jnp.zeros_like(dx_msg), dx_msg)
        # params enter as CONSTANTS: the pullback computes dx only, and
        # XLA's DCE drops the wgrad outer products from this phase
        _, pull_x = jax.vjp(
            lambda x: mb_fn(other_params, blk_params, x, ids_b, lab_b), x_b)
        (dx,) = pull_x((dy, jnp.ones((), nll_f.dtype)))
        dy_save = jnp.where(
            on_b,
            jax.lax.dynamic_update_index_in_dim(dy_save, dy, m_bc % DBUF,
                                                0),
            dy_save)
        dx_msg = jax.lax.ppermute(dx, axis_name, perm_bwd)

        # ---- W(stage, m) at t = B-tick + S: wgrad in the bubble ----------
        m_w = t - (2 * (S - 1) - stage) - S
        on_w = (m_w >= 0) & (m_w < M)
        m_wc = jnp.clip(m_w, 0, M - 1)
        ids_w = jax.lax.dynamic_index_in_dim(ids_mb, m_wc, 0, keepdims=False)
        lab_w = jax.lax.dynamic_index_in_dim(labels_mb, m_wc, 0,
                                             keepdims=False)
        x_w = jax.lax.dynamic_index_in_dim(x_save, m_wc % BUF, 0,
                                           keepdims=False)
        dy_w = jax.lax.dynamic_index_in_dim(dy_save, m_wc % DBUF, 0,
                                            keepdims=False)
        _, pull_p = jax.vjp(
            lambda o, b: mb_fn(o, b, x_w, ids_w, lab_w),
            other_params, blk_params)
        go, gb = pull_p((dy_w, jnp.ones((), nll_f.dtype)))
        d_other = masked_add(d_other, go, on_w)
        d_blk = masked_add(d_blk, gb, on_w)

        return (x_save, dy_save, y_msg, dx_msg, d_other, d_blk,
                nll_acc), None

    (x_save, dy_save, y_msg, dx_msg, d_other, d_blk, nll_acc), _ = \
        jax.lax.scan(tick, carry0, jnp.arange(T))
    return nll_acc, d_other, d_blk
