"""Pipeline parallelism.

Reference: fleet/meta_parallel — ``PipelineLayer`` pp_layers.py:257
(``LayerDesc`` :56, ``SharedLayerDesc`` :76), runtime ``PipelineParallel``
pipeline_parallel.py:231 with 1F1B ``forward_backward_pipeline`` :547 and
interleaved VPP :1138, p2p via partial send/recv ops.

TPU-native design: the transformer block stack is *stacked* — one params
pytree with leading dim [num_stages, layers_per_stage, ...] sharded over the
``pp`` mesh axis — and the schedule is a ``lax.scan`` under ``shard_map``:
each scan step every stage applies its block to its current microbatch and
rotates activations to the next stage with ``lax.ppermute`` (the partial
send/recv ops dissolve into one ICI collective-permute per step).  Autodiff
through the scan gives the backward pipeline for free (ppermute's VJP is the
reverse permute), with per-stage rematerialization via ``jax.checkpoint``
bounding activation memory like 1F1B.  The reference needed an actor runtime
(fleet_executor) + five schedule passes for this; here it is ~100 lines that
XLA software-pipelines.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer, functional_call
from .topology import PP_AXIS, get_topology

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "spmd_pipeline",
           "pipeline_stack_specs"]


class LayerDesc:
    """Deferred layer construction (reference pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build(self) -> Layer:
        return self.layer_cls(*self.args, **self.kwargs)


class SharedLayerDesc(LayerDesc):
    """Weight-tied layer across stages (reference pp_layers.py:76, used for
    tied embeddings).  On TPU tying is a pytree aliasing decision: the tied
    weight lives outside the pipelined stack, replicated (or mp-sharded)
    across pp, so no gradient all-reduce between first/last stage is
    needed — XLA sums the contributions."""

    def __init__(self, key, layer_cls, forward_func=None, shared_weight_attr="weight",
                 *args, **kwargs):
        super().__init__(layer_cls, *args, **kwargs)
        self.key = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


def spmd_pipeline(stage_fn: Callable, stage_params: Any, microbatches,
                  num_stages: int, axis_name: str = PP_AXIS,
                  remat: bool = True):
    """Run the scan-pipeline INSIDE a shard_map over ``axis_name``.

    stage_fn(params_local, x) -> y : one pipeline stage's computation
    stage_params: params pytree with leading stage dim already sliced to the
      local stage (shard_map does the slicing via in_specs)
    microbatches: [M, mb, ...] array, same on every stage (in_specs P(None))
    returns [M, mb, ...] outputs valid on the LAST stage (callers psum or
      ppermute them home).
    """
    M = microbatches.shape[0]
    S = num_stages
    stage = jax.lax.axis_index(axis_name)
    fn = jax.checkpoint(stage_fn) if remat else stage_fn

    state = jnp.zeros_like(microbatches[0])
    outputs = jnp.zeros_like(microbatches)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def step(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (while t < M); others take the
        # rotated activation from the previous stage
        mb = jax.lax.dynamic_index_in_dim(
            microbatches, jnp.minimum(t, M - 1), axis=0, keepdims=False)
        x = jnp.where(stage == 0, mb, state)
        y = fn(stage_params, x)
        # last stage writes its finished microbatch t-(S-1)
        out_idx = t - (S - 1)
        write = (stage == S - 1) & (out_idx >= 0)
        outputs = jax.lax.cond(
            write,
            lambda o: jax.lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(out_idx, 0), axis=0),
            lambda o: o, outputs)
        # rotate activations forward one stage
        state = jax.lax.ppermute(y, axis_name, perm)
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(step, (state, outputs),
                                       jnp.arange(M + S - 1))
    return outputs


def pipeline_stack_specs(param_tree, axis_name: str = PP_AXIS):
    """PartitionSpec for a stacked stage-param pytree: leading dim over pp."""
    return jax.tree.map(
        lambda v: P(axis_name, *([None] * (np.ndim(v) - 1))), param_tree)


class PipelineLayer(Layer):
    """API-parity container (reference pp_layers.py:257).

    Built from LayerDescs, segmented into ``num_stages`` contiguous chunks
    (seg_method="uniform" — layer-count balanced, matching the reference's
    default :113).  Eager forward runs all stages sequentially (single
    program semantics); the DistributedEngine detects a PipelineLayer and
    can lower the homogeneous block stack through :func:`spmd_pipeline`.
    """

    def __init__(self, layers: List[LayerDesc], num_stages: Optional[int] = None,
                 topology=None, loss_fn=None, seg_method: str = "uniform",
                 recompute_interval: int = 0, name=None):
        super().__init__()
        topo = topology or get_topology()
        self.num_stages = num_stages or topo.get_pipe_parallel_world_size()
        self.descs = list(layers)
        from ..nn.layer.container import LayerList
        built = []
        self.shared_layers = {}
        for d in self.descs:
            if isinstance(d, SharedLayerDesc):
                if d.key in self.shared_layers:
                    layer = self.shared_layers[d.key]
                else:
                    layer = d.build()
                    self.shared_layers[d.key] = layer
                built.append((layer, getattr(d, "forward_func", None)))
            elif isinstance(d, LayerDesc):
                built.append((d.build(), None))
            else:
                built.append((d, None))
        self.runs = built
        self.stack = LayerList([l for l, _ in built])
        # uniform segmentation with remainder spread over leading stages
        # (reference seg_method="uniform", pp_layers.py:113-134)
        n = len(built)
        base, rem = divmod(n, self.num_stages)
        bounds = [0]
        for i in range(self.num_stages):
            bounds.append(bounds[-1] + base + (1 if i < rem else 0))
        self.segments = list(zip(bounds[:-1], bounds[1:]))
        self.recompute_interval = recompute_interval

    def forward(self, x):
        for layer, ffn in self.runs:
            x = ffn(layer, x) if ffn is not None else layer(x)
        return x

    def get_stage_layers(self, stage: int):
        lo, hi = self.segments[stage]
        return [l for l, _ in self.runs[lo:hi]]
