"""Semi-auto (DistTensor) API.

Reference: python/paddle/distributed/auto_parallel/api.py —
``shard_tensor`` :134, ``reshard`` :619, ``dtensor_from_local`` :539,
``shard_layer`` :718; C++ DistTensor/ProcessMesh/reshard functions
(phi/core/distributed/auto_parallel/).

TPU-native: a "DistTensor" is a Tensor whose value is a global
``jax.Array`` with a ``NamedSharding``; ``Placement`` types map onto
PartitionSpec entries; ``reshard`` is a sharded ``device_put`` — XLA
generates the same r_to_s / s_to_r / p_to_r transfer kernels the reference
hand-codes per placement pair (reshard/*.cc)."""

from __future__ import annotations

from typing import List, Optional, Sequence, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer.layers import Layer
from .topology import get_topology

__all__ = ["ProcessMesh", "Shard", "Replicate", "Partial", "shard_tensor",
           "dtensor_from_local", "reshard", "shard_layer", "get_placements"]


class Placement:
    pass


class Shard(Placement):
    """Shard(dim) — split tensor dim over a mesh axis."""

    def __init__(self, dim: int):
        self.dim = dim

    def __repr__(self):
        return f"Shard(dim={self.dim})"

    def __eq__(self, o):
        return isinstance(o, Shard) and o.dim == self.dim


class Replicate(Placement):
    def __repr__(self):
        return "Replicate()"

    def __eq__(self, o):
        return isinstance(o, Replicate)


class Partial(Placement):
    """Pending-reduction placement.  In the single-controller model a
    Partial tensor materializes as replicated-after-psum; kept for API
    parity (reference placement_types.h)."""

    def __init__(self, reduce_type: str = "sum"):
        self.reduce_type = reduce_type

    def __repr__(self):
        return f"Partial({self.reduce_type})"


class ProcessMesh:
    """N-d logical mesh over devices (reference process_mesh.h:34).  Wraps a
    jax Mesh; ``dim_names`` are the sharding axis names."""

    def __init__(self, mesh: Union[Sequence, np.ndarray, None] = None,
                 dim_names: Optional[List[str]] = None,
                 jax_mesh: Optional[Mesh] = None):
        if jax_mesh is not None:
            self.mesh = jax_mesh
            self.dim_names = list(jax_mesh.axis_names)
            return
        arr = np.asarray(mesh)
        dim_names = dim_names or [f"d{i}" for i in range(arr.ndim)]
        devs = np.asarray(jax.devices())[arr.reshape(-1)].reshape(arr.shape)
        self.mesh = Mesh(devs, tuple(dim_names))
        self.dim_names = list(dim_names)

    @property
    def shape(self):
        return [self.mesh.shape[n] for n in self.dim_names]

    @property
    def process_ids(self):
        return [d.id for d in self.mesh.devices.reshape(-1)]

    def __repr__(self):
        return f"ProcessMesh(shape={self.shape}, dim_names={self.dim_names})"


def _spec_from_placements(placements: Sequence[Placement], ndim: int,
                          dim_names: List[str]) -> P:
    entries: List[Optional[str]] = [None] * ndim
    for axis_name, pl in zip(dim_names, placements):
        if isinstance(pl, Shard):
            if entries[pl.dim] is not None:
                entries[pl.dim] = (*((entries[pl.dim],) if isinstance(
                    entries[pl.dim], str) else entries[pl.dim]), axis_name)
            else:
                entries[pl.dim] = axis_name
    return P(*entries)


def shard_tensor(data, mesh: Optional[ProcessMesh] = None,
                 placements: Optional[Sequence[Placement]] = None,
                 dtype=None, stop_gradient: Optional[bool] = None) -> Tensor:
    """Place a (global) tensor onto the mesh with the given placements
    (reference auto_parallel/api.py:134)."""
    t = data if isinstance(data, Tensor) else Tensor(np.asarray(data))
    mesh = mesh or ProcessMesh(jax_mesh=get_topology().mesh)
    placements = list(placements or [])
    if any(isinstance(p, Partial) for p in placements):
        raise ValueError(
            "shard_tensor cannot create a Partial tensor from global data "
            "(there is nothing to be partial over); build one with "
            "dtensor_from_local(partial_stack=...) or receive one from a "
            "sharded op")
    spec = _spec_from_placements(placements, t.ndim, mesh.dim_names)
    sharding = NamedSharding(mesh.mesh, spec)
    v = jax.device_put(t._value, sharding)
    out = Tensor(v, stop_gradient=(t.stop_gradient if stop_gradient is None
                                   else stop_gradient), name=t.name)
    out.process_mesh = mesh
    out.placements = placements
    return out


import functools


@functools.lru_cache(maxsize=128)
def _resolve_partial(reduce_type: str, dst_sharding):
    """Compiled-once Partial resolver: fold the hidden leading contribution
    dim with the placement's reduce op, pinned to the destination
    sharding (XLA lowers this to the all-reduce / reduce-scatter the
    reference's p_to_r / p_to_s emit).  lru-cached so a per-step reshard
    doesn't re-trace.

    The destination MUST be pinned via ``out_shardings``, not a
    ``with_sharding_constraint`` on the returned value: jit without
    ``out_shardings`` compiles with
    ``allow_spmd_sharding_propagation_to_output=true``, and under that
    flag XLA's partitioner may override (or gather+slice-elide) a
    root-position constraint — the dst placement silently doesn't
    happen (root cause of the ISSUE 11 reshard-matrix triage; jax
    0.4.37)."""
    import jax.numpy as jnp
    reducers = {"sum": jnp.sum, "avg": jnp.mean, "mean": jnp.mean,
                "max": jnp.max, "min": jnp.min}
    try:
        red = reducers[reduce_type]
    except KeyError:
        raise ValueError(f"unsupported Partial reduce_type {reduce_type!r}")

    @functools.partial(jax.jit, out_shardings=dst_sharding)
    def resolve(v):
        return red(v, axis=0)

    return resolve


def _partial_axes(placements, dim_names):
    return [ax for ax, pl in zip(dim_names, placements)
            if isinstance(pl, Partial)]


def dtensor_from_local(local_tensor, mesh: ProcessMesh,
                       placements: Sequence[Placement],
                       partial_stack=None) -> Tensor:
    """Assemble a global tensor from per-device local shards (reference
    api.py:539).  Single-controller: jax.make_array_from_single_device_arrays
    over the mesh's devices.

    Partial placements: pass ``partial_stack`` — an array of shape
    ``[axis_size, *logical_shape]`` holding each mesh-position's unreduced
    contribution (the per-rank partial values of the reference's Partial
    state).  The dtensor carries it sharded on the hidden leading dim;
    ``reshard`` to Replicate/Shard resolves it with the all-reduce /
    reduce-scatter the reference's p_to_r / p_to_s functions emit."""
    p_axes = _partial_axes(placements, mesh.dim_names)
    if p_axes:
        if partial_stack is None:
            raise ValueError("Partial placement needs partial_stack "
                             "[axis_size, *shape] of per-rank contributions")
        if len(p_axes) != 1:
            raise NotImplementedError("one Partial axis supported")
        data = np.asarray(partial_stack._value if isinstance(
            partial_stack, Tensor) else partial_stack)
        base = _spec_from_placements(placements, data.ndim - 1,
                                     mesh.dim_names)
        spec = P(p_axes[0], *base)
        v = jax.device_put(data, NamedSharding(mesh.mesh, spec))
        out = Tensor(v, stop_gradient=True)
        out.process_mesh = mesh
        out.placements = list(placements)   # Partial here marks the hidden
        return out                          # leading contribution dim
    t = local_tensor if isinstance(local_tensor, Tensor) else Tensor(
        np.asarray(local_tensor))
    spec = _spec_from_placements(placements, t.ndim, mesh.dim_names)
    sharding = NamedSharding(mesh.mesh, spec)
    # global shape: local shape scaled by shard counts
    gshape = list(t.shape)
    for axis_name, pl in zip(mesh.dim_names, placements):
        if isinstance(pl, Shard):
            gshape[pl.dim] *= mesh.mesh.shape[axis_name]
    v = jax.make_array_from_callback(
        tuple(gshape), sharding,
        lambda idx: np.asarray(t._value)[tuple(
            slice(0, s.stop - s.start) if isinstance(s, slice) else s
            for s in idx)])
    out = Tensor(v, stop_gradient=t.stop_gradient)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def reshard(dist_tensor: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Change placements (reference api.py:619; C++ reshard functions
    phi/core/distributed/auto_parallel/reshard/ r_to_s/s_to_r/p_to_r/
    p_to_s/s_to_s/nd_mesh).

    Placement-pair → collective mapping (asserted against the compiled
    HLO in tests/test_reshard_matrix.py):
      r_to_s  local slice (no collective)     s_to_r  all-gather
      s_to_s  all-to-all (dim move)           p_to_r  all-reduce
      p_to_s  reduce-scatter
    A Partial source resolves its hidden per-rank contribution dim by
    summation; XLA lowers sum-over-mesh-axis + output sharding to the
    all-reduce / reduce-scatter pair above."""
    src_partials = [p for p in (get_placements(dist_tensor) or [])
                    if isinstance(p, Partial)]
    if src_partials:
        dst_base = _spec_from_placements(placements, dist_tensor.ndim - 1,
                                         mesh.dim_names)
        dst_sharding = NamedSharding(mesh.mesh, dst_base)
        v = _resolve_partial(src_partials[0].reduce_type,
                             dst_sharding)(dist_tensor._value)
    else:
        spec = _spec_from_placements(placements, dist_tensor.ndim,
                                     mesh.dim_names)
        v = jax.device_put(dist_tensor._value,
                           NamedSharding(mesh.mesh, spec))
    out = Tensor(v, stop_gradient=dist_tensor.stop_gradient)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def shard_layer(layer: Layer, process_mesh: ProcessMesh,
                shard_fn=None, input_fn=None, output_fn=None) -> Layer:
    """Apply a shard_fn(name, layer, mesh) over sublayers to annotate/place
    parameters (reference api.py:718)."""
    def default_shard_fn(name, sublayer, mesh):
        for pname, p in sublayer._parameters.items():
            if p is not None:
                p._value = jax.device_put(
                    p._value, NamedSharding(mesh.mesh, P()))
    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    return layer


def get_placements(t: Tensor):
    return getattr(t, "placements", None)
