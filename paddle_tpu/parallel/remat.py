"""Selective rematerialization policies for the hybrid train step.

The reference's recompute offers per-layer granularity plus an "offload"
variant (fleet/recompute/recompute.py:124, recompute_hybrid.py); on TPU
the equivalent lever is ``jax.checkpoint``'s *policy*: instead of
recompute-everything (the round-3 default, which the v5e sweep priced at
~25% throughput — HFU 0.378 vs MFU 0.284 at GPT-1.3B-width), a policy can
save the cheap-to-store / expensive-to-recompute values and recompute
only the rest:

* ``"full"`` / ``None`` — save nothing, recompute the whole block (max
  memory savings, ~4/3 FLOP cost).
* ``"dots"`` — save non-batched matmul outputs (qkv/proj/fc1/fc2
  projections, each O(b*s*h)); recompute elementwise ops AND batched
  attention einsums (the O(b*h*s^2) logits stay unsaved).  The usual
  sweet spot: near-dense speed at a fraction of the memory.
* ``"dots_saveable"`` — additionally saves batched dots (attention
  logits); memory approaches the no-remat path.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

import jax

POLICIES = {
    None: None,
    "full": None,
    "dots": "dots_with_no_batch_dims_saveable",
    "dots_saveable": "dots_saveable",
    "everything": "everything_saveable",
}


def resolve_policy(policy: Union[str, Callable, None]):
    """Map a policy name to a jax.checkpoint policy callable (None =
    save-nothing).  Callables pass through for power users."""
    if callable(policy):
        return policy
    if policy not in POLICIES:
        raise ValueError(
            f"unknown remat policy {policy!r}; one of {sorted(k for k in POLICIES if k)} "
            "or a jax.checkpoint_policies callable")
    name = POLICIES[policy]
    return getattr(jax.checkpoint_policies, name) if name else None


def remat_wrap(fn: Callable, remat: bool,
               policy: Union[str, Callable, None] = None) -> Callable:
    """``jax.checkpoint`` ``fn`` under the named policy (no-op when
    ``remat`` is False)."""
    if not remat:
        return fn
    p = resolve_policy(policy)
    return jax.checkpoint(fn, policy=p) if p is not None else \
        jax.checkpoint(fn)
