"""``paddle_tpu.parallel`` — hybrid-parallel over TPU meshes (SURVEY §2.5).

Maps the reference's python/paddle/distributed surface onto jax.sharding:
process groups → mesh axes, NCCL → XLA collectives on ICI/DCN, TCPStore →
jax.distributed coordination.
"""

from . import collective  # noqa: F401
from . import spmd_rules  # noqa: F401
from . import completion  # noqa: F401
from .completion import CompletionPlan, complete_program  # noqa: F401
from . import fleet  # noqa: F401
from .api import (  # noqa: F401
    Partial, ProcessMesh, Replicate, Shard, dtensor_from_local, reshard,
    shard_layer, shard_tensor,
)
from .collective import (  # noqa: F401
    Group, ReduceOp, all_gather, all_reduce, all_to_all, barrier, broadcast,
    get_group, new_group, reduce, reduce_scatter, scatter,
)
from .context_parallel import ring_flash_attention, ulysses_attention  # noqa: F401
from .engine import DistributedEngine  # noqa: F401
from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized  # noqa: F401
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear, ParallelCrossEntropy, RowParallelLinear,
    VocabParallelEmbedding,
)
from .sequence_parallel import (  # noqa: F401
    ColumnSequenceParallelLinear, RowSequenceParallelLinear, all_gather_op,
    gather_op, mark_as_sequence_parallel_parameter, reduce_scatter_op,
    register_sequence_parallel_allreduce_hooks, scatter_op,
)
from . import overlap  # noqa: F401
from .overlap import (  # noqa: F401
    all_gather_matmul, matmul_all_reduce, matmul_reduce_scatter,
)
from .sharding import ShardingStage, group_sharded_parallel  # noqa: F401
from .topology import HybridTopology, get_topology, init_topology, set_topology  # noqa: F401
from .pipeline import LayerDesc, PipelineLayer, SharedLayerDesc, spmd_pipeline  # noqa: F401
from . import checkpoint  # noqa: F401
from .checkpoint import (  # noqa: F401
    TopologyMismatchError, load_state_dict, save_state_dict,
)
from . import elastic  # noqa: F401
from .elastic import (  # noqa: F401
    CollectiveTimeoutError, ElasticPolicy, ElasticTrainer, WorkerLostError,
)
