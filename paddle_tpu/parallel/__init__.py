from .env import ParallelEnv, get_rank, get_world_size, init_parallel_env, is_initialized  # noqa: F401
