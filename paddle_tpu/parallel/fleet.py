"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py:99 —
``fleet.init`` :166, ``distributed_model`` model.py:32,
``distributed_optimizer``; ``DistributedStrategy``
base/distributed_strategy.py:175 with ``hybrid_configs`` :1771)."""

from __future__ import annotations

from typing import Optional

from ..nn.layer.layers import Layer
from .engine import DistributedEngine
from .env import get_rank, get_world_size, init_parallel_env
from .topology import HybridTopology, get_topology, set_topology

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "Fleet", "UtilBase", "Role", "PaddleCloudRoleMaker",
           "UserDefinedRoleMaker", "CommunicateTopology",
           "HybridCommunicateGroup", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator",
           "distributed_optimizer", "get_hybrid_communicate_group"]


class DistributedStrategy:
    """Typed config replacing the protobuf-backed reference class."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sep_degree": 1, "sharding_degree": 1,
        }
        self.sharding_stage = 0
        self.amp = False
        self.amp_configs = {"level": "O1", "dtype": "bfloat16"}
        self.recompute = False
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.find_unused_parameters = False


_fleet_state = {"strategy": None, "topo": None, "initialized": False}


def init(is_collective: bool = True, role_maker=None,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    """fleet.init parity: reads strategy.hybrid_configs, builds the device
    mesh (the reference's HybridCommunicateGroup, topology.py:178)."""
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = HybridTopology(
        dp=hc.get("dp_degree", 1), mp=hc.get("mp_degree", 1),
        pp=hc.get("pp_degree", 1), sep=hc.get("sep_degree", 1),
        sharding=hc.get("sharding_degree", 1))
    set_topology(topo)
    _fleet_state.update(strategy=strategy, topo=topo, initialized=True)
    return topo


def get_hybrid_communicate_group() -> HybridTopology:
    return _fleet_state["topo"] or get_topology()


def distributed_model(model: Layer, optimizer=None, loss_fn=None
                      ) -> DistributedEngine:
    """Wrap a Layer for hybrid-parallel execution (reference fleet/model.py:32
    chooses Sharding/Segment/Tensor/Pipeline wrappers; here one engine
    handles all axes via sharding specs)."""
    strategy = _fleet_state["strategy"] or DistributedStrategy()
    topo = get_hybrid_communicate_group()
    eng = DistributedEngine(
        model, optimizer=optimizer, loss_fn=loss_fn, topology=topo,
        sharding_stage=strategy.sharding_stage,
        recompute=strategy.recompute,
        amp_dtype=(strategy.amp_configs.get("dtype")
                   if strategy.amp else None))
    return eng


def distributed_optimizer(optimizer, strategy=None):
    """The engine consumes the optimizer's functional API directly; global-
    norm clip already reduces across the whole mesh inside the compiled step
    (the reference needed HybridParallelOptimizer to patch this,
    hybrid_parallel_optimizer.py:255)."""
    return optimizer


worker_index = get_rank
worker_num = get_world_size


def is_first_worker() -> bool:
    return get_rank() == 0


# ---------------------------------------------------------------------------
# fleet __all__ tail (reference distributed/fleet/__init__.py)
# ---------------------------------------------------------------------------

# the reference's CommunicateTopology / HybridCommunicateGroup
# (fleet/base/topology.py:65/:178) are the rank-grid + per-axis comm-group
# objects — HybridTopology plays both roles here (mesh + axis groups)
CommunicateTopology = HybridTopology
HybridCommunicateGroup = HybridTopology


class Role:
    """Reference role_maker.Role enum."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4
    COORDINATOR = 5


class PaddleCloudRoleMaker:
    """Reference PaddleCloudRoleMaker: derives the rank/role from launcher
    environment variables (PADDLE_TRAINER_ID & co. — the same env our
    launcher sets)."""

    def __init__(self, is_collective: bool = True, **kwargs):
        import os
        self._is_collective = is_collective
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        self._size = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))

    def worker_index(self) -> int:
        return self._rank

    def worker_num(self) -> int:
        return self._size

    def is_worker(self) -> bool:
        return True

    def is_server(self) -> bool:
        return False

    def is_first_worker(self) -> bool:
        return self._rank == 0

    def role(self):
        return Role.WORKER


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    """Reference UserDefinedRoleMaker: explicit rank/size."""

    def __init__(self, is_collective=True, current_id=0, worker_num=1,
                 role=Role.WORKER, **kwargs):
        super().__init__(is_collective)
        self._rank = current_id
        self._size = worker_num
        self._role = role

    def role(self):
        return self._role


class UtilBase:
    """Reference UtilBase: small cross-rank helpers over the collective
    API."""

    def all_reduce(self, input, mode="sum", comm_world="worker"):
        import numpy as np

        from ..core.tensor import Tensor
        from . import collective as C
        t = input if isinstance(input, Tensor) else Tensor(np.asarray(input))
        C.all_reduce(t, op=mode)
        return np.asarray(t._value)

    def barrier(self, comm_world="worker"):
        from . import collective as C
        C.barrier()

    def all_gather(self, input, comm_world="worker"):
        import numpy as np

        from ..core.tensor import Tensor
        from . import collective as C
        out = []
        C.all_gather(out, Tensor(np.asarray(input)))
        return [np.asarray(o._value) for o in out]

    def get_file_shard(self, files):
        from .env import get_rank, get_world_size
        n, r = get_world_size(), get_rank()
        return files[r::n]

    def print_on_rank(self, message, rank_id=0):
        from .env import get_rank
        if get_rank() == rank_id:
            print(message)


class MultiSlotDataGenerator:
    """Reference fleet.MultiSlotDataGenerator (PS data-ingest protocol):
    subclass, implement generate_sample(line) yielding
    [(slot_name, [values]), ...]; run_from_stdin()/run_from_files()
    emit the multi-slot text protocol."""

    def __init__(self):
        self._proto_info = None

    def generate_sample(self, line):
        raise NotImplementedError(
            "implement generate_sample(line) -> iterator of "
            "[(slot, values), ...]")

    def _format(self, sample) -> str:
        parts = []
        for _slot, values in sample:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts)

    def run_from_stdin(self):
        import sys
        for line in sys.stdin:
            for sample in self.generate_sample(line):
                sys.stdout.write(self._format(sample) + "\n")

    def run_from_files(self, filelist):
        out = []
        for path in filelist:
            with open(path) as f:
                for line in f:
                    for sample in self.generate_sample(line):
                        out.append(self._format(sample))
        return out


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """String-slot variant (values emitted verbatim)."""


class Fleet:
    """Reference fleet.Fleet facade class (fleet/fleet.py:99).  The
    module-level functions (init/distributed_model/...) are the singleton
    instance's methods, matching how the reference exposes
    ``paddle.distributed.fleet`` as a Fleet() instance."""

    def __init__(self):
        self.util = UtilBase()

    def init(self, role_maker=None, is_collective=True, strategy=None):
        return init(is_collective=is_collective, role_maker=role_maker,
                    strategy=strategy)

    def distributed_model(self, model, **kw):
        return distributed_model(model, **kw)

    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def is_first_worker(self):
        return is_first_worker()

    @property
    def worker_index(self):
        from .env import get_rank
        return get_rank

    @property
    def worker_num(self):
        from .env import get_world_size
        return get_world_size

    def barrier_worker(self):
        from . import collective as C
        C.barrier()
