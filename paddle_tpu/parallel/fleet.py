"""Fleet facade (reference: python/paddle/distributed/fleet/fleet.py:99 —
``fleet.init`` :166, ``distributed_model`` model.py:32,
``distributed_optimizer``; ``DistributedStrategy``
base/distributed_strategy.py:175 with ``hybrid_configs`` :1771)."""

from __future__ import annotations

from typing import Optional

from ..nn.layer.layers import Layer
from .engine import DistributedEngine
from .env import get_rank, get_world_size, init_parallel_env
from .topology import HybridTopology, get_topology, set_topology

__all__ = ["DistributedStrategy", "init", "distributed_model",
           "distributed_optimizer", "get_hybrid_communicate_group"]


class DistributedStrategy:
    """Typed config replacing the protobuf-backed reference class."""

    def __init__(self):
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
            "sep_degree": 1, "sharding_degree": 1,
        }
        self.sharding_stage = 0
        self.amp = False
        self.amp_configs = {"level": "O1", "dtype": "bfloat16"}
        self.recompute = False
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1}
        self.pipeline_configs = {"accumulate_steps": 1,
                                 "micro_batch_size": 1}
        self.find_unused_parameters = False


_fleet_state = {"strategy": None, "topo": None, "initialized": False}


def init(is_collective: bool = True, role_maker=None,
         strategy: Optional[DistributedStrategy] = None, log_level=None):
    """fleet.init parity: reads strategy.hybrid_configs, builds the device
    mesh (the reference's HybridCommunicateGroup, topology.py:178)."""
    init_parallel_env()
    strategy = strategy or DistributedStrategy()
    hc = strategy.hybrid_configs
    topo = HybridTopology(
        dp=hc.get("dp_degree", 1), mp=hc.get("mp_degree", 1),
        pp=hc.get("pp_degree", 1), sep=hc.get("sep_degree", 1),
        sharding=hc.get("sharding_degree", 1))
    set_topology(topo)
    _fleet_state.update(strategy=strategy, topo=topo, initialized=True)
    return topo


def get_hybrid_communicate_group() -> HybridTopology:
    return _fleet_state["topo"] or get_topology()


def distributed_model(model: Layer, optimizer=None, loss_fn=None
                      ) -> DistributedEngine:
    """Wrap a Layer for hybrid-parallel execution (reference fleet/model.py:32
    chooses Sharding/Segment/Tensor/Pipeline wrappers; here one engine
    handles all axes via sharding specs)."""
    strategy = _fleet_state["strategy"] or DistributedStrategy()
    topo = get_hybrid_communicate_group()
    eng = DistributedEngine(
        model, optimizer=optimizer, loss_fn=loss_fn, topology=topo,
        sharding_stage=strategy.sharding_stage,
        recompute=strategy.recompute,
        amp_dtype=(strategy.amp_configs.get("dtype")
                   if strategy.amp else None))
    return eng


def distributed_optimizer(optimizer, strategy=None):
    """The engine consumes the optimizer's functional API directly; global-
    norm clip already reduces across the whole mesh inside the compiled step
    (the reference needed HybridParallelOptimizer to patch this,
    hybrid_parallel_optimizer.py:255)."""
    return optimizer


worker_index = get_rank
worker_num = get_world_size


def is_first_worker() -> bool:
    return get_rank() == 0
