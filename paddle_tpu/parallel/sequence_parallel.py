"""Megatron-style sequence parallelism over the tensor-parallel axis.

Reference: python/paddle/distributed/fleet/utils/sequence_parallel_utils.py
— ``ScatterOp``/``GatherOp``/``AllGatherOp``/``ReduceScatterOp`` PyLayers
(:85-:127) and ``ColumnSequenceParallelLinear`` (:427) /
``RowSequenceParallelLinear`` (:562).

Between transformer blocks the activation keeps its SEQUENCE dim sharded
over the ``mp`` axis (so LayerNorm/dropout activations cost 1/mp memory);
around each column-parallel matmul the sequence is all-gathered, and each
row-parallel matmul's all-reduce is replaced by a reduce-scatter back to
the sequence shard.  Everything here is manual-SPMD: call INSIDE
``shard_map`` with ``axis_name`` manual (the same style as
parallel/manual.py, which hosts the plain-mp operators).

Gradient caveat ported from the reference (register_sequence_parallel_
allreduce_hooks): parameters consumed on the SEQ-SHARDED activation
(LayerNorms, row-linear biases) see only their shard's tokens, so their
grads are partial over mp and must be summed — build_hybrid_train_step
takes ``mp_reduce_block_leaves`` for exactly this.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .topology import MP_AXIS

__all__ = ["scatter_op", "gather_op", "all_gather_op", "reduce_scatter_op",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "mark_as_sequence_parallel_parameter",
           "is_sequence_parallel_parameter",
           "register_sequence_parallel_allreduce_hooks"]


# ---------------------------------------------------------------------------
# functional ops (custom VJPs mirror the reference PyLayers)
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def scatter_op(x, axis_name: str = MP_AXIS, axis: int = 1):
    """Replicated full sequence -> local shard (reference ScatterOp :85:
    identity-split forward, all-gather backward)."""
    n = lax.axis_size(axis_name)
    if x.shape[axis] % n != 0:
        raise ValueError(f"scatter_op: dim {axis} ({x.shape[axis]}) not "
                         f"divisible by {axis_name} size {n}")
    idx = lax.axis_index(axis_name)
    size = x.shape[axis] // n
    return lax.dynamic_slice_in_dim(x, idx * size, size, axis)


scatter_op.defvjp(
    lambda x, a, ax: (scatter_op(x, a, ax), None),
    lambda a, ax, _, g: (lax.all_gather(g, a, axis=ax, tiled=True),))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def gather_op(x, axis_name: str = MP_AXIS, axis: int = 1):
    """Local shard -> replicated full sequence (reference GatherOp :106:
    all-gather forward, split backward)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def _split_bwd(axis_name, axis, _, g):
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    size = g.shape[axis] // n
    return (lax.dynamic_slice_in_dim(g, idx * size, size, axis),)


gather_op.defvjp(lambda x, a, ax: (gather_op(x, a, ax), None), _split_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def all_gather_op(x, axis_name: str = MP_AXIS, axis: int = 1):
    """All-gather whose backward is reduce-scatter (reference AllGatherOp
    :118) — the input-side operator of ColumnSequenceParallelLinear."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


all_gather_op.defvjp(
    lambda x, a, ax: (all_gather_op(x, a, ax), None),
    lambda a, ax, _, g: (lax.psum_scatter(g, a, scatter_dimension=ax,
                                          tiled=True),))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def reduce_scatter_op(x, axis_name: str = MP_AXIS, axis: int = 1):
    """Reduce-scatter whose backward is all-gather (reference
    ReduceScatterOp :127) — the output-side operator of
    RowSequenceParallelLinear, replacing the plain-mp all-reduce."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


reduce_scatter_op.defvjp(
    lambda x, a, ax: (reduce_scatter_op(x, a, ax), None),
    lambda a, ax, _, g: (lax.all_gather(g, a, axis=ax, tiled=True),))


# ---------------------------------------------------------------------------
# layers (manual-SPMD: forward must run inside shard_map)
# ---------------------------------------------------------------------------
def _sp_tag(tensor):
    tensor.__dict__["_sequence_parallel"] = True
    return tensor


def mark_as_sequence_parallel_parameter(parameter):
    """Tag a parameter whose gradient is partial over mp under SP
    (reference sequence_parallel_utils.py:mark_as_sequence_parallel_
    parameter) — consumed by register_sequence_parallel_allreduce_hooks /
    mp_reduce_block_leaves."""
    return _sp_tag(parameter)


def is_sequence_parallel_parameter(parameter) -> bool:
    return bool(getattr(parameter, "_sequence_parallel", False))


def register_sequence_parallel_allreduce_hooks(layer, accumulation_steps=1,
                                               fuse=False):
    """Reference parity (:sequence_parallel_utils.py:register_...): attach
    grad hooks that all-reduce marked params over mp after backward.  In
    the eager engine this is a Tensor grad hook calling the mp all-reduce;
    compiled steps instead list the leaves in mp_reduce_block_leaves."""
    from .collective import all_reduce
    from .topology import get_topology

    topo = get_topology()
    if topo.get_model_parallel_world_size() <= 1:
        return

    group = topo.get_model_parallel_group()
    for _, p in layer.named_parameters():
        if is_sequence_parallel_parameter(p):
            def hook(g, _group=group):
                return all_reduce(g, group=_group)
            p.register_hook(hook)


class ColumnSequenceParallelLinear:
    """y_local = all_gather_seq(x_shard) @ W[:, shard] (+ b[shard]).

    Weight layout identical to ColumnParallelLinear (column shard local);
    input/output sequence sharding per reference :427.  Pure-functional
    flavor: construct with the LOCAL weight shard and call inside
    shard_map.
    """

    def __init__(self, weight, bias=None, axis_name: str = MP_AXIS,
                 overlap: bool = False):
        self.weight = weight
        self.bias = bias
        self.axis_name = axis_name
        self.overlap = overlap

    def __call__(self, x):
        if self.overlap:
            # ring-decomposed gather+gemm (reference :255 overlap path);
            # see parallel/overlap.py
            from .overlap import all_gather_matmul
            y = all_gather_matmul(x, self.weight, self.axis_name)
        else:
            y = all_gather_op(x, self.axis_name) @ self.weight
        if self.bias is not None:
            y = y + self.bias
        return y


class RowSequenceParallelLinear:
    """y_shard = reduce_scatter_seq(x_local @ W[shard, :]) (+ b).

    The bias is added AFTER the reduce-scatter (on the sequence shard), so
    its gradient is partial over mp — mark it (reference :562 handles this
    with mark_as_sequence_parallel_parameter on the bias)."""

    def __init__(self, weight, bias=None, axis_name: str = MP_AXIS,
                 overlap: bool = False):
        self.weight = weight
        self.bias = bias
        self.axis_name = axis_name
        self.overlap = overlap

    def __call__(self, x):
        if self.overlap:
            from .overlap import matmul_reduce_scatter
            y = matmul_reduce_scatter(x, self.weight, self.axis_name)
        else:
            y = reduce_scatter_op(x @ self.weight, self.axis_name)
        if self.bias is not None:
            y = y + self.bias
        return y
