"""Per-op SPMD sharding-propagation rules for the semi-auto API.

Reference: paddle/phi/infermeta/spmd_rules/ (46 C++ rule files — e.g.
``MatmulInferSpmd`` matmul.h:25, embedding.cc, elementwise.cc,
softmax.cc, flash_attention.cc, reduction.cc) and the completion pass
(python/paddle/distributed/auto_parallel/static/completion.py).

TPU-native shape: a rule is a pure function over :class:`TensorDistAttr`
(dims_mapping + partial axes, same representation as the reference's
``TensorDistAttr``) that returns (a) the input attrs each operand must be
reshard-ed to and (b) the inferred output attr.  GSPMD does the actual
partitioning; the rule layer makes propagation *explicit and testable* —
each rule is pinned against GSPMD's observed behavior in
tests/test_spmd_rules.py, which is the analog of the reference's
spmd-rule unit suite (test/auto_parallel/spmd_rules).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set, Tuple

__all__ = ["TensorDistAttr", "matmul_rule", "elementwise_rule",
           "embedding_rule", "reduction_rule", "softmax_rule",
           "transpose_rule", "reshape_rule", "flash_attention_rule",
           "cross_entropy_rule", "layer_norm_rule"]


@dataclass
class TensorDistAttr:
    """dims_mapping[i] = mesh-axis name sharding tensor dim i (None =
    replicated on that dim); partial = mesh axes holding unreduced
    partial sums (reference: phi/core/distributed/auto_parallel/
    dist_attr.h TensorDistAttr)."""
    dims_mapping: List[Optional[str]]
    partial: Set[str] = field(default_factory=set)

    @property
    def ndim(self) -> int:
        return len(self.dims_mapping)

    def replicate(self) -> "TensorDistAttr":
        return TensorDistAttr([None] * self.ndim)

    def with_dim(self, dim: int, axis: Optional[str]) -> "TensorDistAttr":
        dm = list(self.dims_mapping)
        dm[dim] = axis
        return TensorDistAttr(dm, set(self.partial))

    def __repr__(self):
        p = f", partial={sorted(self.partial)}" if self.partial else ""
        return f"DistAttr({self.dims_mapping}{p})"


def _merge_dim(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Merge two proposals for one tensor dim: agreement wins, conflict
    (or one-sided) prefers the sharded proposal; hard conflict -> None
    (replicate), matching the reference's ShardingMergeForTensors."""
    if a == b:
        return a
    if a is None:
        return b
    if b is None:
        return a
    return None          # conflicting axes: fall back to replicated


def _used_axes(*attrs: TensorDistAttr) -> Set[str]:
    used = set()
    for at in attrs:
        used |= {a for a in at.dims_mapping if a is not None}
        used |= at.partial
    return used


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def matmul_rule(x: TensorDistAttr, y: TensorDistAttr,
                trans_x: bool = False, trans_y: bool = False
                ) -> Tuple[TensorDistAttr, TensorDistAttr, TensorDistAttr]:
    """[..., m, k] @ [..., k, n] (reference MatmulInferSpmd matmul.h:25).

    Returns (x_required, y_required, out).  Einsum-notation alignment:
    batch dims merge elementwise; m from x, n from y; a shared contracted
    axis makes the output PARTIAL on that mesh axis (the caller's reshard
    of the output inserts the all-reduce — reference partial semantics).
    """
    # vector operands: pad to rank 2 the way MatmulInferSpmd does —
    # x gains an m dim in front, y gains an n dim at the back
    xm = list(x.dims_mapping)
    ym = list(y.dims_mapping)
    x_vec, y_vec = len(xm) == 1, len(ym) == 1
    if x_vec:
        xm = [None] + xm
        trans_x = False  # reference resets trans flags for 1-D operands
    if y_vec:
        ym = ym + [None]
        trans_y = False
    if trans_x:
        xm[-1], xm[-2] = xm[-2], xm[-1]
    if trans_y:
        ym[-1], ym[-2] = ym[-2], ym[-1]
    nb = max(len(xm), len(ym)) - 2
    xb = [None] * (nb - (len(xm) - 2)) + xm[:-2]
    yb = [None] * (nb - (len(ym) - 2)) + ym[:-2]
    batch = [_merge_dim(a, b) for a, b in zip(xb, yb)]
    m, kx = xm[-2], xm[-1]
    ky, n = ym[-2], ym[-1]
    k = _merge_dim(kx, ky)
    # m/n may not reuse an axis already taken by k, a batch dim, or each
    # other (a mesh axis can appear at most once in a PartitionSpec —
    # reference ShardingMergeForTensors resolves the same conflicts)
    taken = {a for a in batch if a is not None}
    if k is not None:
        taken.add(k)
    m = None if m in taken else m
    taken.add(m)
    n = None if n in taken or n == m else n

    x_req = TensorDistAttr(batch[nb - (len(xm) - 2):] + [m, k])
    y_req = TensorDistAttr(batch[nb - (len(ym) - 2):] + [k, n])
    if trans_x:
        x_req.dims_mapping[-1], x_req.dims_mapping[-2] = \
            x_req.dims_mapping[-2], x_req.dims_mapping[-1]
    if trans_y:
        y_req.dims_mapping[-1], y_req.dims_mapping[-2] = \
            y_req.dims_mapping[-2], y_req.dims_mapping[-1]
    out_map = batch + [m, n]
    # strip the vector-padding dims back off (MatmulInferSpmd squeeze)
    if x_vec:
        x_req = TensorDistAttr(x_req.dims_mapping[-1:])
        out_map = [d for i, d in enumerate(out_map) if i != len(out_map) - 2]
    if y_vec:
        y_req = TensorDistAttr(y_req.dims_mapping[:-1])
        out_map = out_map[:-1]
    out = TensorDistAttr(out_map,
                         partial={k} if k is not None else set())
    return x_req, y_req, out


def elementwise_rule(*attrs: TensorDistAttr
                     ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """Broadcast-aware elementwise (reference elementwise.cc).  Output dim
    mapping = merge of (right-aligned) input mappings; inputs required to
    match on non-broadcast dims.  Partial inputs stay partial only if ALL
    inputs share the same partial axes (else require reshard-to-full)."""
    ndim = max(a.ndim for a in attrs)
    out_dm: List[Optional[str]] = [None] * ndim
    for a in attrs:
        off = ndim - a.ndim
        for i, ax in enumerate(a.dims_mapping):
            out_dm[off + i] = _merge_dim(out_dm[off + i], ax)
    reqs = []
    partials = [frozenset(a.partial) for a in attrs]
    same_partial = len(set(partials)) == 1
    for a in attrs:
        off = ndim - a.ndim
        # each input aligns to the merged mapping on its trailing dims;
        # size-1 broadcast dims are masked to None by the caller (the rule
        # sees only mappings, not shapes)
        dm = [out_dm[off + i] for i in range(a.ndim)]
        reqs.append(TensorDistAttr(
            dm, set(a.partial) if same_partial else set()))
    out = TensorDistAttr(out_dm,
                         set(attrs[0].partial) if same_partial else set())
    return reqs, out


def embedding_rule(table: TensorDistAttr, ids: TensorDistAttr
                   ) -> Tuple[TensorDistAttr, TensorDistAttr,
                              TensorDistAttr]:
    """table [V, H], ids [...] -> out [..., H] (reference embedding.cc).
    Row-parallel table (V sharded on axis a) -> out PARTIAL on a (the
    vocab-parallel masked-lookup pattern, c_embedding); col-parallel table
    (H sharded) -> out last dim sharded."""
    v_ax, h_ax = table.dims_mapping
    ids_req = TensorDistAttr(list(ids.dims_mapping))
    table_req = TensorDistAttr([v_ax, h_ax])
    out = TensorDistAttr(list(ids.dims_mapping) + [h_ax],
                         partial={v_ax} if v_ax is not None else set())
    return table_req, ids_req, out


def reduction_rule(x: TensorDistAttr, axis: Sequence[int], keepdim=False
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """sum/mean over ``axis`` (reference reduction.cc): reducing a sharded
    dim turns its mesh axis into a PARTIAL on the output."""
    axes = {a % x.ndim for a in axis}
    new_partial = set(x.partial)
    out_dm = []
    for i, ax in enumerate(x.dims_mapping):
        if i in axes:
            if ax is not None:
                new_partial.add(ax)
            if keepdim:
                out_dm.append(None)
        else:
            out_dm.append(ax)
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), \
        TensorDistAttr(out_dm, new_partial)


def softmax_rule(x: TensorDistAttr, axis: int = -1
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """softmax dim must be unsharded (reference softmax.cc): the rule
    requires the input resharded so dims_mapping[axis] is None."""
    req = x.with_dim(axis % x.ndim, None)
    req.partial = set()
    return req, TensorDistAttr(list(req.dims_mapping))


def transpose_rule(x: TensorDistAttr, perm: Sequence[int]
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    out = TensorDistAttr([x.dims_mapping[p] for p in perm], set(x.partial))
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), out


def reshape_rule(x: TensorDistAttr, src_shape: Sequence[int],
                 dst_shape: Sequence[int]
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Split/merge-aware reshape (reference reshape.cc): a sharded src dim
    survives if it maps to the MAJOR position of a merged/split group;
    otherwise the rule requires it replicated."""
    src = list(src_shape)
    dst = list(dst_shape)
    req = list(x.dims_mapping)
    out_dm: List[Optional[str]] = [None] * len(dst)
    si = di = 0
    while si < len(src) and di < len(dst):
        s_sz, d_sz = src[si], dst[di]
        if s_sz == d_sz:
            out_dm[di] = x.dims_mapping[si]
            si += 1
            di += 1
        elif s_sz > d_sz:
            # split: src dim si -> dst dims di.. ; shard maps to major part
            if s_sz % d_sz == 0:
                out_dm[di] = x.dims_mapping[si]
                run = d_sz
                di += 1
                while run < s_sz and di < len(dst):
                    run *= dst[di]
                    di += 1
                si += 1
            else:
                req[si] = None
                si += 1
                di += 1
        else:
            # merge: src dims si.. -> dst dim di; only the major src dim's
            # sharding survives; minor sharded dims must be replicated
            out_dm[di] = x.dims_mapping[si]
            run = s_sz
            si += 1
            while run < d_sz and si < len(src):
                if x.dims_mapping[si] is not None:
                    req[si] = None
                run *= src[si]
                si += 1
            di += 1
    return TensorDistAttr(req, set(x.partial)), \
        TensorDistAttr(out_dm, set(x.partial))


def flash_attention_rule(q: TensorDistAttr, k: TensorDistAttr,
                         v: TensorDistAttr, sep_axis: Optional[str] = None
                         ) -> Tuple[TensorDistAttr, TensorDistAttr,
                                    TensorDistAttr, TensorDistAttr]:
    """q/k/v [b, s, n, d] (reference flash_attention.cc): batch and head
    dims may shard; head_dim must be replicated.  The sequence dim may
    shard ONLY on ``sep_axis`` (ring/Ulysses context parallelism handles
    the KV exchange); otherwise it must be replicated."""
    b = _merge_dim(_merge_dim(q.dims_mapping[0], k.dims_mapping[0]),
                   v.dims_mapping[0])
    n = _merge_dim(_merge_dim(q.dims_mapping[2], k.dims_mapping[2]),
                   v.dims_mapping[2])
    s_q = q.dims_mapping[1]
    s = s_q if (sep_axis is not None and s_q == sep_axis) else None
    req = TensorDistAttr([b, s, n, None])
    return req, req, req, TensorDistAttr([b, s, n, None])


def cross_entropy_rule(logits: TensorDistAttr, label: TensorDistAttr
                       ) -> Tuple[TensorDistAttr, TensorDistAttr,
                                  TensorDistAttr]:
    """softmax CE over the class dim (reference
    cross_entropy_with_softmax.cc): a class-dim shard is ALLOWED (vocab-
    parallel CE computes with psum of max/denominator) and yields a
    PARTIAL loss; batch dims propagate."""
    cls_ax = logits.dims_mapping[-1]
    batch = logits.dims_mapping[:-1]
    lbl_req = TensorDistAttr(list(batch))
    out = TensorDistAttr(list(batch),
                         partial={cls_ax} if cls_ax is not None else set())
    return TensorDistAttr(list(logits.dims_mapping)), lbl_req, out


def layer_norm_rule(x: TensorDistAttr, begin_norm_axis: int = -1
                    ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Normalized dims must be replicated (reference layer_norm.cc)."""
    bn = begin_norm_axis % x.ndim
    req = TensorDistAttr([ax if i < bn else None
                          for i, ax in enumerate(x.dims_mapping)])
    return req, TensorDistAttr(list(req.dims_mapping))
