"""Per-op SPMD sharding-propagation rules for the semi-auto API.

Reference: paddle/phi/infermeta/spmd_rules/ (46 C++ rule files — e.g.
``MatmulInferSpmd`` matmul.h:25, embedding.cc, elementwise.cc,
softmax.cc, flash_attention.cc, reduction.cc) and the completion pass
(python/paddle/distributed/auto_parallel/static/completion.py).

TPU-native shape: a rule is a pure function over :class:`TensorDistAttr`
(dims_mapping + partial axes, same representation as the reference's
``TensorDistAttr``) that returns (a) the input attrs each operand must be
reshard-ed to and (b) the inferred output attr.  GSPMD does the actual
partitioning; the rule layer makes propagation *explicit and testable* —
each rule is pinned against GSPMD's observed behavior in
tests/test_spmd_rules.py, which is the analog of the reference's
spmd-rule unit suite (test/auto_parallel/spmd_rules).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Set, Tuple

__all__ = ["TensorDistAttr", "matmul_rule", "elementwise_rule",
           "embedding_rule", "reduction_rule", "softmax_rule",
           "transpose_rule", "reshape_rule", "flash_attention_rule",
           "cross_entropy_rule", "layer_norm_rule"]


@dataclass
class TensorDistAttr:
    """dims_mapping[i] = mesh-axis name sharding tensor dim i (None =
    replicated on that dim); partial = mesh axes holding unreduced
    partial sums (reference: phi/core/distributed/auto_parallel/
    dist_attr.h TensorDistAttr)."""
    dims_mapping: List[Optional[str]]
    partial: Set[str] = field(default_factory=set)

    @property
    def ndim(self) -> int:
        return len(self.dims_mapping)

    def replicate(self) -> "TensorDistAttr":
        return TensorDistAttr([None] * self.ndim)

    def with_dim(self, dim: int, axis: Optional[str]) -> "TensorDistAttr":
        dm = list(self.dims_mapping)
        dm[dim] = axis
        return TensorDistAttr(dm, set(self.partial))

    def __repr__(self):
        p = f", partial={sorted(self.partial)}" if self.partial else ""
        return f"DistAttr({self.dims_mapping}{p})"


def _merge_dim(a: Optional[str], b: Optional[str]) -> Optional[str]:
    """Merge two proposals for one tensor dim: agreement wins, conflict
    (or one-sided) prefers the sharded proposal; hard conflict -> None
    (replicate), matching the reference's ShardingMergeForTensors."""
    if a == b:
        return a
    if a is None:
        return b
    if b is None:
        return a
    return None          # conflicting axes: fall back to replicated


def _used_axes(*attrs: TensorDistAttr) -> Set[str]:
    used = set()
    for at in attrs:
        used |= {a for a in at.dims_mapping if a is not None}
        used |= at.partial
    return used


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def matmul_rule(x: TensorDistAttr, y: TensorDistAttr,
                trans_x: bool = False, trans_y: bool = False
                ) -> Tuple[TensorDistAttr, TensorDistAttr, TensorDistAttr]:
    """[..., m, k] @ [..., k, n] (reference MatmulInferSpmd matmul.h:25).

    Returns (x_required, y_required, out).  Einsum-notation alignment:
    batch dims merge elementwise; m from x, n from y; a shared contracted
    axis makes the output PARTIAL on that mesh axis (the caller's reshard
    of the output inserts the all-reduce — reference partial semantics).
    """
    # vector operands: pad to rank 2 the way MatmulInferSpmd does —
    # x gains an m dim in front, y gains an n dim at the back
    xm = list(x.dims_mapping)
    ym = list(y.dims_mapping)
    x_vec, y_vec = len(xm) == 1, len(ym) == 1
    if x_vec:
        xm = [None] + xm
        trans_x = False  # reference resets trans flags for 1-D operands
    if y_vec:
        ym = ym + [None]
        trans_y = False
    if trans_x:
        xm[-1], xm[-2] = xm[-2], xm[-1]
    if trans_y:
        ym[-1], ym[-2] = ym[-2], ym[-1]
    nb = max(len(xm), len(ym)) - 2
    xb = [None] * (nb - (len(xm) - 2)) + xm[:-2]
    yb = [None] * (nb - (len(ym) - 2)) + ym[:-2]
    batch = [_merge_dim(a, b) for a, b in zip(xb, yb)]
    m, kx = xm[-2], xm[-1]
    ky, n = ym[-2], ym[-1]
    k = _merge_dim(kx, ky)
    # m/n may not reuse an axis already taken by k, a batch dim, or each
    # other (a mesh axis can appear at most once in a PartitionSpec —
    # reference ShardingMergeForTensors resolves the same conflicts)
    taken = {a for a in batch if a is not None}
    if k is not None:
        taken.add(k)
    m = None if m in taken else m
    taken.add(m)
    n = None if n in taken or n == m else n

    x_req = TensorDistAttr(batch[nb - (len(xm) - 2):] + [m, k])
    y_req = TensorDistAttr(batch[nb - (len(ym) - 2):] + [k, n])
    if trans_x:
        x_req.dims_mapping[-1], x_req.dims_mapping[-2] = \
            x_req.dims_mapping[-2], x_req.dims_mapping[-1]
    if trans_y:
        y_req.dims_mapping[-1], y_req.dims_mapping[-2] = \
            y_req.dims_mapping[-2], y_req.dims_mapping[-1]
    out_map = batch + [m, n]
    # strip the vector-padding dims back off (MatmulInferSpmd squeeze)
    if x_vec:
        x_req = TensorDistAttr(x_req.dims_mapping[-1:])
        out_map = [d for i, d in enumerate(out_map) if i != len(out_map) - 2]
    if y_vec:
        y_req = TensorDistAttr(y_req.dims_mapping[:-1])
        out_map = out_map[:-1]
    out = TensorDistAttr(out_map,
                         partial={k} if k is not None else set())
    return x_req, y_req, out


def elementwise_rule(*attrs: TensorDistAttr
                     ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """Broadcast-aware elementwise (reference elementwise.cc).  Output dim
    mapping = merge of (right-aligned) input mappings; inputs required to
    match on non-broadcast dims.  Partial inputs stay partial only if ALL
    inputs share the same partial axes (else require reshard-to-full)."""
    ndim = max(a.ndim for a in attrs)
    out_dm: List[Optional[str]] = [None] * ndim
    for a in attrs:
        off = ndim - a.ndim
        for i, ax in enumerate(a.dims_mapping):
            out_dm[off + i] = _merge_dim(out_dm[off + i], ax)
    reqs = []
    partials = [frozenset(a.partial) for a in attrs]
    same_partial = len(set(partials)) == 1
    for a in attrs:
        off = ndim - a.ndim
        # each input aligns to the merged mapping on its trailing dims;
        # size-1 broadcast dims are masked to None by the caller (the rule
        # sees only mappings, not shapes)
        dm = [out_dm[off + i] for i in range(a.ndim)]
        reqs.append(TensorDistAttr(
            dm, set(a.partial) if same_partial else set()))
    out = TensorDistAttr(out_dm,
                         set(attrs[0].partial) if same_partial else set())
    return reqs, out


def embedding_rule(table: TensorDistAttr, ids: TensorDistAttr
                   ) -> Tuple[TensorDistAttr, TensorDistAttr,
                              TensorDistAttr]:
    """table [V, H], ids [...] -> out [..., H] (reference embedding.cc).
    Row-parallel table (V sharded on axis a) -> out PARTIAL on a (the
    vocab-parallel masked-lookup pattern, c_embedding); col-parallel table
    (H sharded) -> out last dim sharded."""
    v_ax, h_ax = table.dims_mapping
    ids_req = TensorDistAttr(list(ids.dims_mapping))
    table_req = TensorDistAttr([v_ax, h_ax])
    out = TensorDistAttr(list(ids.dims_mapping) + [h_ax],
                         partial={v_ax} if v_ax is not None else set())
    return table_req, ids_req, out


def reduction_rule(x: TensorDistAttr, axis: Sequence[int], keepdim=False
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """sum/mean over ``axis`` (reference reduction.cc): reducing a sharded
    dim turns its mesh axis into a PARTIAL on the output."""
    axes = {a % x.ndim for a in axis}
    new_partial = set(x.partial)
    out_dm = []
    for i, ax in enumerate(x.dims_mapping):
        if i in axes:
            if ax is not None:
                new_partial.add(ax)
            if keepdim:
                out_dm.append(None)
        else:
            out_dm.append(ax)
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), \
        TensorDistAttr(out_dm, new_partial)


def softmax_rule(x: TensorDistAttr, axis: int = -1
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """softmax dim must be unsharded (reference softmax.cc): the rule
    requires the input resharded so dims_mapping[axis] is None."""
    req = x.with_dim(axis % x.ndim, None)
    req.partial = set()
    return req, TensorDistAttr(list(req.dims_mapping))


def transpose_rule(x: TensorDistAttr, perm: Sequence[int]
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    out = TensorDistAttr([x.dims_mapping[p] for p in perm], set(x.partial))
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), out


def reshape_rule(x: TensorDistAttr, src_shape: Sequence[int],
                 dst_shape: Sequence[int]
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Split/merge-aware reshape (reference reshape.cc): a sharded src dim
    survives if it maps to the MAJOR position of a merged/split group;
    otherwise the rule requires it replicated."""
    src = list(src_shape)
    dst = list(dst_shape)
    req = list(x.dims_mapping)
    out_dm: List[Optional[str]] = [None] * len(dst)
    si = di = 0
    while si < len(src) and di < len(dst):
        s_sz, d_sz = src[si], dst[di]
        if s_sz == d_sz:
            out_dm[di] = x.dims_mapping[si]
            si += 1
            di += 1
        elif s_sz > d_sz:
            # split: src dim si -> dst dims di.. ; shard maps to major part
            if s_sz % d_sz == 0:
                out_dm[di] = x.dims_mapping[si]
                run = d_sz
                di += 1
                while run < s_sz and di < len(dst):
                    run *= dst[di]
                    di += 1
                si += 1
            else:
                req[si] = None
                si += 1
                di += 1
        else:
            # merge: src dims si.. -> dst dim di; only the major src dim's
            # sharding survives; minor sharded dims must be replicated
            out_dm[di] = x.dims_mapping[si]
            run = s_sz
            si += 1
            while run < d_sz and si < len(src):
                if x.dims_mapping[si] is not None:
                    req[si] = None
                run *= src[si]
                si += 1
            di += 1
    return TensorDistAttr(req, set(x.partial)), \
        TensorDistAttr(out_dm, set(x.partial))


def flash_attention_rule(q: TensorDistAttr, k: TensorDistAttr,
                         v: TensorDistAttr, sep_axis: Optional[str] = None
                         ) -> Tuple[TensorDistAttr, TensorDistAttr,
                                    TensorDistAttr, TensorDistAttr]:
    """q/k/v [b, s, n, d] (reference flash_attention.cc): batch and head
    dims may shard; head_dim must be replicated.  The sequence dim may
    shard ONLY on ``sep_axis`` (ring/Ulysses context parallelism handles
    the KV exchange); otherwise it must be replicated."""
    b = _merge_dim(_merge_dim(q.dims_mapping[0], k.dims_mapping[0]),
                   v.dims_mapping[0])
    n = _merge_dim(_merge_dim(q.dims_mapping[2], k.dims_mapping[2]),
                   v.dims_mapping[2])
    s_q = q.dims_mapping[1]
    s = s_q if (sep_axis is not None and s_q == sep_axis) else None
    req = TensorDistAttr([b, s, n, None])
    return req, req, req, TensorDistAttr([b, s, n, None])


def cross_entropy_rule(logits: TensorDistAttr, label: TensorDistAttr
                       ) -> Tuple[TensorDistAttr, TensorDistAttr,
                                  TensorDistAttr]:
    """softmax CE over the class dim (reference
    cross_entropy_with_softmax.cc): a class-dim shard is ALLOWED (vocab-
    parallel CE computes with psum of max/denominator) and yields a
    PARTIAL loss; batch dims propagate."""
    cls_ax = logits.dims_mapping[-1]
    batch = logits.dims_mapping[:-1]
    lbl_req = TensorDistAttr(list(batch))
    out = TensorDistAttr(list(batch),
                         partial={cls_ax} if cls_ax is not None else set())
    return TensorDistAttr(list(logits.dims_mapping)), lbl_req, out


def layer_norm_rule(x: TensorDistAttr, begin_norm_axis: int = -1
                    ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Normalized dims must be replicated (reference layer_norm.cc)."""
    bn = begin_norm_axis % x.ndim
    req = TensorDistAttr([ax if i < bn else None
                          for i, ax in enumerate(x.dims_mapping)])
    return req, TensorDistAttr(list(req.dims_mapping))


# ---------------------------------------------------------------------------
# round-4 rule tail: reference breadth (phi/infermeta/spmd_rules/, 46
# files).  Same contract as above: (required_input_attrs..., out_attr).
# ---------------------------------------------------------------------------

def concat_rule(attrs: Sequence[TensorDistAttr], axis: int
                ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """Concat axis replicated on every input; other dims merged
    (reference concat.cc builds the einsum notation EXCLUDING the concat
    axis, i.e. it cannot stay sharded — shards would be interleaved)."""
    nd = attrs[0].ndim
    ax = axis % nd
    merged = [None] * nd
    for i in range(nd):
        if i == ax:
            continue
        m = attrs[0].dims_mapping[i]
        for a in attrs[1:]:
            m = _merge_dim(m, a.dims_mapping[i])
        merged[i] = m
    reqs = [TensorDistAttr([merged[i] if i != ax else None
                            for i in range(nd)]) for _ in attrs]
    return reqs, TensorDistAttr([merged[i] if i != ax else None
                                 for i in range(nd)])


def split_rule(x: TensorDistAttr, axis: int, num_out: int
               ) -> Tuple[TensorDistAttr, List[TensorDistAttr]]:
    """Split axis replicated (reference split.cc); outputs inherit."""
    ax = axis % x.ndim
    req = x.with_dim(ax, None)
    req.partial = set()
    return req, [TensorDistAttr(list(req.dims_mapping))
                 for _ in range(num_out)]


def stack_rule(attrs: Sequence[TensorDistAttr], axis: int
               ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """Merge input mappings; the NEW stacked dim is replicated
    (reference stack.cc)."""
    nd = attrs[0].ndim
    merged = [attrs[0].dims_mapping[i] for i in range(nd)]
    for a in attrs[1:]:
        merged = [_merge_dim(m, d) for m, d in zip(merged, a.dims_mapping)]
    reqs = [TensorDistAttr(list(merged)) for _ in attrs]
    ax = axis % (nd + 1)
    out = merged[:ax] + [None] + merged[ax:]
    return reqs, TensorDistAttr(out)


def unbind_rule(x: TensorDistAttr, axis: int, num_out: int
                ) -> Tuple[TensorDistAttr, List[TensorDistAttr]]:
    """Unbind axis replicated; outputs drop it (reference unbind.cc)."""
    ax = axis % x.ndim
    req = x.with_dim(ax, None)
    req.partial = set()
    out_dm = [d for i, d in enumerate(req.dims_mapping) if i != ax]
    return req, [TensorDistAttr(list(out_dm)) for _ in range(num_out)]


def slice_rule(x: TensorDistAttr, axes: Sequence[int]
               ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Sliced axes must be replicated (reference slice.cc) — a slice range
    spans shard boundaries; untouched dims propagate."""
    req = TensorDistAttr(list(x.dims_mapping), set())
    for a in axes:
        req.dims_mapping[a % x.ndim] = None
    return req, TensorDistAttr(list(req.dims_mapping))


def squeeze_rule(x: TensorDistAttr, axes: Sequence[int]
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Dropped size-1 dims can't be sharded; the rest map through
    (reference squeeze.cc)."""
    drop = {a % x.ndim for a in axes}
    req = TensorDistAttr([None if i in drop else d
                          for i, d in enumerate(x.dims_mapping)],
                         set(x.partial))
    out = [d for i, d in enumerate(req.dims_mapping) if i not in drop]
    return req, TensorDistAttr(out, set(x.partial))


def unsqueeze_rule(x: TensorDistAttr, axes: Sequence[int]
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """New size-1 dims are replicated (reference unsqueeze.cc)."""
    nd_out = x.ndim + len(axes)
    ins = sorted(a % nd_out for a in axes)
    out: List[Optional[str]] = []
    src = iter(x.dims_mapping)
    for i in range(nd_out):
        out.append(None if i in ins else next(src))
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), \
        TensorDistAttr(out, set(x.partial))


def flatten_rule(x: TensorDistAttr, start: int, stop: int
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Merge [start, stop] into one dim: only the MAJOR (first) merged
    dim's sharding survives (reference flatten.cc == reshape merge)."""
    s, e = start % x.ndim, stop % x.ndim
    req = TensorDistAttr(list(x.dims_mapping), set(x.partial))
    for i in range(s + 1, e + 1):
        req.dims_mapping[i] = None
    out = (req.dims_mapping[:s] + [req.dims_mapping[s]]
           + req.dims_mapping[e + 1:])
    return req, TensorDistAttr(out, set(x.partial))


def gather_rule(x: TensorDistAttr, index: TensorDistAttr, axis: int
                ) -> Tuple[TensorDistAttr, TensorDistAttr, TensorDistAttr]:
    """x's gather axis replicated (arbitrary global indices); index
    shardings replace it in the output (reference gather.cc)."""
    ax = axis % x.ndim
    x_req = x.with_dim(ax, None)
    x_req.partial = set()
    idx_req = TensorDistAttr(list(index.dims_mapping))
    out = (list(x_req.dims_mapping[:ax]) + list(idx_req.dims_mapping)
           + list(x_req.dims_mapping[ax + 1:]))
    return x_req, idx_req, TensorDistAttr(out)


def scatter_rule(x: TensorDistAttr, index: TensorDistAttr,
                 updates: TensorDistAttr
                 ) -> Tuple[TensorDistAttr, TensorDistAttr, TensorDistAttr,
                            TensorDistAttr]:
    """Scatter writes along dim 0: dim 0 of x/updates and index must be
    replicated (reference scatter.cc); trailing dims merge."""
    tail = [_merge_dim(a, b) for a, b in zip(x.dims_mapping[1:],
                                             updates.dims_mapping[1:])]
    x_req = TensorDistAttr([None] + tail)
    upd_req = TensorDistAttr([None] + tail)
    idx_req = TensorDistAttr([None] * index.ndim)
    return x_req, idx_req, upd_req, TensorDistAttr([None] + tail)


def gather_nd_rule(x: TensorDistAttr, index: TensorDistAttr
                   ) -> Tuple[TensorDistAttr, TensorDistAttr,
                              TensorDistAttr]:
    """index dims (minus the last, the coordinate depth) lead the output;
    x dims beyond the coordinate depth trail (reference gather_nd.cc);
    indexed x dims replicated."""
    depth = 1  # conservative without static index shape: first x dim
    x_req = TensorDistAttr([None] * depth
                           + list(x.dims_mapping[depth:]), set())
    idx_req = TensorDistAttr(list(index.dims_mapping[:-1]) + [None])
    out = list(idx_req.dims_mapping[:-1]) + list(x_req.dims_mapping[depth:])
    return x_req, idx_req, TensorDistAttr(out)


def cumsum_rule(x: TensorDistAttr, axis: int
                ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Scan axis must be replicated (reference cumsum.cc:42)."""
    req = x.with_dim(axis % x.ndim, None)
    req.partial = set()
    return req, TensorDistAttr(list(req.dims_mapping))


def argmax_rule(x: TensorDistAttr, axis: int, keepdim: bool = False
                ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Arg-reduction axis replicated (cross-shard argmax needs global
    compare — reference argmax.cc); other dims propagate."""
    ax = axis % x.ndim
    req = x.with_dim(ax, None)
    req.partial = set()
    if keepdim:
        out = list(req.dims_mapping)
    else:
        out = [d for i, d in enumerate(req.dims_mapping) if i != ax]
    return req, TensorDistAttr(out)


def one_hot_rule(x: TensorDistAttr
                 ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Input dims propagate; the new class dim is replicated
    (reference one_hot.cc)."""
    req = TensorDistAttr(list(x.dims_mapping), set())
    return req, TensorDistAttr(list(x.dims_mapping) + [None])


def tile_rule(x: TensorDistAttr, repeats: Sequence[int]
              ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """A tiled (repeat>1) dim must be replicated — its global layout
    interleaves copies (reference tile.cc); repeat==1 dims propagate."""
    nd_out = max(x.ndim, len(repeats))
    reps = [1] * (nd_out - len(repeats)) + list(repeats)
    dm = [None] * (nd_out - x.ndim) + list(x.dims_mapping)
    req_dm = list(x.dims_mapping)
    out = []
    for i in range(nd_out):
        if reps[i] == 1:
            out.append(dm[i])
        else:
            out.append(None)
            xi = i - (nd_out - x.ndim)
            if xi >= 0:
                req_dm[xi] = None
    return TensorDistAttr(req_dm, set()), TensorDistAttr(out)


def expand_rule(x: TensorDistAttr, src_shape: Sequence[int],
                dst_shape: Sequence[int]
                ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Broadcast (1 -> n) dims are replicated in the output; matching
    dims propagate (reference expand_as.cc)."""
    nd_out = len(dst_shape)
    pad = nd_out - x.ndim
    out: List[Optional[str]] = [None] * nd_out
    for i in range(x.ndim):
        if src_shape[i] == dst_shape[pad + i]:
            out[pad + i] = x.dims_mapping[i]
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), \
        TensorDistAttr(out)


def where_rule(cond: TensorDistAttr, x: TensorDistAttr, y: TensorDistAttr
               ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """Three-way broadcast-aware elementwise merge (reference where.cc)."""
    reqs, out = elementwise_rule(cond, x, y)
    return reqs, out


def triu_rule(x: TensorDistAttr
              ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """The two matrix dims must be replicated — the mask depends on
    GLOBAL row/col indices (reference triu.cc); batch dims propagate."""
    req = TensorDistAttr(list(x.dims_mapping[:-2]) + [None, None],
                         set(x.partial))
    return req, TensorDistAttr(list(req.dims_mapping), set(x.partial))


def rms_norm_rule(x: TensorDistAttr
                  ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Normalized (last) dim replicated (reference rms_norm.cc)."""
    req = x.with_dim(x.ndim - 1, None)
    req.partial = set()
    return req, TensorDistAttr(list(req.dims_mapping))


def fused_rope_rule(q: TensorDistAttr
                    ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """[b, s, h, d]: the rotary (last) dim must be intact — the rotation
    mixes its halves (reference fused_rope.cc); b/s/h may shard (the seq
    dim's global offset is the context-parallel kernel's job)."""
    req = q.with_dim(q.ndim - 1, None)
    req.partial = set()
    return req, TensorDistAttr(list(req.dims_mapping))


def swiglu_rule(x: TensorDistAttr, y: Optional[TensorDistAttr] = None
                ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """Elementwise gate*up — mappings merge, any dim may shard
    (reference swiglu.cc)."""
    if y is None:
        return [TensorDistAttr(list(x.dims_mapping))], \
            TensorDistAttr(list(x.dims_mapping))
    reqs, out = elementwise_rule(x, y)
    return reqs, out


def squared_l2_norm_rule(x: TensorDistAttr
                         ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Full reduction: output is a PARTIAL scalar on every input shard
    axis (reference squared_l2_norm.cc) — the caller's reshard inserts
    the cross-shard sum."""
    shard_axes = {a for a in x.dims_mapping if a is not None}
    return TensorDistAttr(list(x.dims_mapping)), \
        TensorDistAttr([], partial=shard_axes)


def add_n_rule(attrs: Sequence[TensorDistAttr]
               ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """N-way elementwise merge; partials UNION (summing partials is
    legal — reference add_n spmd)."""
    reqs, out = elementwise_rule(*attrs)
    partial = set()
    for a in attrs:
        partial |= a.partial
    out = TensorDistAttr(list(out.dims_mapping), partial)
    return reqs, out


def scale_rule(x: TensorDistAttr) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Pure elementwise passthrough INCLUDING partial state (scaling
    commutes with the pending sum — reference scale.cc)."""
    keep = TensorDistAttr(list(x.dims_mapping), set(x.partial))
    return keep, TensorDistAttr(list(x.dims_mapping), set(x.partial))


cast_rule = scale_rule          # same passthrough semantics (cast.cc)
pow_rule = scale_rule           # pow.cc (partial does NOT commute through
                                # pow in general; reference keeps mapping,
                                # clears partial — handled by caller)


def numel_rule(x: TensorDistAttr) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Metadata op: output is a replicated scalar regardless of input
    sharding (reference numel.cc)."""
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), \
        TensorDistAttr([])


def full_like_rule(x: TensorDistAttr
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """Output keeps the input's mapping, never its partial (the fill
    value is dense — reference full_like.cc)."""
    return TensorDistAttr(list(x.dims_mapping), set(x.partial)), \
        TensorDistAttr(list(x.dims_mapping))


# ---------------------------------------------------------------------------
# round-4b tail: the last capability rules from the reference inventory
# (phi/infermeta/spmd_rules/: amp_ops.cc, expand_as.cc,
#  fused_linear_param_grad_add.cc, optimizer.cc)
# ---------------------------------------------------------------------------

def amp_ops_rule(xs: Sequence[TensorDistAttr]
                 ) -> Tuple[List[TensorDistAttr], List[TensorDistAttr],
                            TensorDistAttr]:
    """check_finite_and_unscale / update_loss_scaling (amp_ops.cc): every
    tensor keeps its sharding (the scale is elementwise), scaled outputs
    mirror the inputs, and found_inf is PARTIAL over every axis sharding
    any checked tensor — each rank tests only its local shard, so a
    cross-rank reduction (any/max) is REQUIRED before the scalar feeds
    the host-side skip-step branch.  The reference marks found_infinite
    partial for exactly this reason; declaring it replicated would be an
    assertion, not an operation, and ranks would silently diverge."""
    keep = [TensorDistAttr(list(x.dims_mapping), set(x.partial))
            for x in xs]
    outs = [TensorDistAttr(list(x.dims_mapping), set(x.partial))
            for x in xs]
    sharded = set()
    for x in xs:
        sharded |= {a for a in x.dims_mapping if a is not None}
        sharded |= x.partial
    return keep, outs, TensorDistAttr([], sharded)


def expand_as_rule(x: TensorDistAttr, src_shape: Sequence[int],
                   dst_shape: Sequence[int]
                   ) -> Tuple[TensorDistAttr, TensorDistAttr]:
    """expand_as.cc: identical propagation to expand — broadcast dims
    replicate, matching dims carry the input axis."""
    return expand_rule(x, src_shape, dst_shape)


def fused_linear_param_grad_add_rule(
        x: TensorDistAttr, dout: TensorDistAttr
) -> Tuple[List[TensorDistAttr], TensorDistAttr, TensorDistAttr]:
    """fused_linear_param_grad_add.cc — dweight (+= x^T @ dout) used by
    the TP/SP overlap path: dw maps [k from x's last dim, n from dout's
    last dim] and is PARTIAL over every axis sharding the contracted
    batch/sequence dims; dbias mirrors dout's last dim with the same
    partial set."""
    contracted = {a for a in x.dims_mapping[:-1] if a is not None}
    contracted |= {a for a in dout.dims_mapping[:-1] if a is not None}
    contracted |= x.partial | dout.partial
    dw = TensorDistAttr([x.dims_mapping[-1], dout.dims_mapping[-1]],
                        set(contracted))
    dbias = TensorDistAttr([dout.dims_mapping[-1]], set(contracted))
    return [TensorDistAttr(list(x.dims_mapping), set(x.partial)),
            TensorDistAttr(list(dout.dims_mapping), set(dout.partial))], \
        dw, dbias


def optimizer_rule(param: TensorDistAttr,
                   others: Sequence[TensorDistAttr],
                   other_shapes: Optional[Sequence] = None
                   ) -> Tuple[List[TensorDistAttr], TensorDistAttr]:
    """optimizer.cc (adam/adamw/sgd/momentum SPMD): the param's sharding
    is authoritative — grad and every moment/accumulator reshard to it
    (partials on the grad must be reduced first: a sharded optimizer
    update of a partial grad would apply the update twice); scalars
    (lr, beta pows — identified by SHAPE when provided, since a [1]
    tensor has the same rank as a 1-D param) replicate.  Output state
    mirrors the param."""
    def _numel(shape):
        n = 1
        for d in shape:
            n *= (1 if d in (None, -1) else int(d))
        return n

    reqs = [TensorDistAttr(list(param.dims_mapping))]
    for i, o in enumerate(others):
        shp = other_shapes[i] if other_shapes is not None             and i < len(other_shapes) else None
        scalar = (_numel(shp) <= 1) if shp is not None             else o.ndim != param.ndim
        if not scalar and o.ndim == param.ndim:
            reqs.append(TensorDistAttr(list(param.dims_mapping)))
        else:              # lr / beta1_pow / ... scalars
            reqs.append(o.replicate())
    return reqs, TensorDistAttr(list(param.dims_mapping))
