"""ElasticTrainer — worker-loss detection, mesh reshape with state
carryover, and straggler/SDC defense for :class:`DistributedEngine`
(ISSUE 17).

The training-side twin of ``serving.supervisor.SupervisedEngine``: the
reference's fleet elastic machinery (python/paddle/distributed/fleet
elastic scale-down + resharded resume) reduced to the parts a
single-controller SPMD runtime actually needs:

1. **Failure detection** — every step runs under a watchdog.  Typed
   transient faults (:class:`CollectiveTimeoutError`) are retried with
   bounded exponential backoff; retries exhausted, or a typed
   :class:`WorkerLostError`, declare the worker lost.  A step that
   COMPLETES but blows the step deadline ``deadline_strikes`` times in
   a row is treated the same way (a wedging worker is a failing
   worker).  The deadline check is post-hoc — a truly hung collective
   needs an out-of-process watchdog (bench.py's pattern); in-process we
   can only observe elapsed time between dispatches.

2. **Elastic reshape with state carryover** — on worker loss the mesh
   is rebuilt over the survivors at the nearest valid topology: the
   lost worker's data axis shrinks N→N−1 when the global batch stays
   divisible, else to the largest valid divisor (XLA requires exact
   divisibility for sharded batch dims).  When every lost shard is
   still replicated on some survivor (ZeRO os_g: params/slots carried
   over other axes), state is gathered from the survivors and
   repartitioned onto the new mesh via the ``parallel/sharding.py``
   specs; otherwise the last hardened sharded checkpoint is restored
   (explicit ``reshape=True``) and the data pipeline is replayed
   deterministically from the checkpoint step (per-step
   ``fold_in(run_key, step)`` RNG ≡ PR 2's ``rng_epoch_start``
   discipline).  Either way the post-reshape loss trajectory is
   bit-identical to an uninterrupted run launched at the new topology
   from the same step (pinned in tests/test_parallel_elastic.py).

3. **Straggler + SDC defense** — per-step wall-time tracking over a
   sliding window flags a DEGRADED state (``train.elastic.*`` metrics +
   flight-ring events) when a step exceeds ``straggler_factor`` × the
   window median.  Gradient bit-flips (SDC) are caught in-graph by the
   engine's StepGuard composition (``skip_nonfinite=True``): the
   poisoned update is where-selected away, params/opt-state come back
   bit-identical, and the host-side :class:`StepGuard` counts the skip.

4. **Warm rebuild** — with ``aot_dir`` set, each topology's step
   program is serialized under a per-topology artifact entry
   (``aot/train.py::export_engine_step``): resume at ANY
   previously-seen topology is ZERO backend compiles; a reshape to a
   new topology pays exactly one bounded compile and extends the store
   (``train_elastic_warm`` COMPILE_BUDGET.md row pins both).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass
from statistics import median
from typing import Any, Callable, Dict, List, Optional

import jax
import numpy as np

from ..checkpoint.step_guard import StepGuard
from .checkpoint import load_state_dict, save_state_dict
from .engine import DistributedEngine
from .topology import (AXIS_ORDER, DP_AXIS, SHARDING_AXIS, HybridTopology,
                       get_topology, set_topology)

__all__ = ["ElasticTrainer", "ElasticPolicy", "WorkerLostError",
           "CollectiveTimeoutError", "HEALTHY", "DEGRADED", "RESHAPING"]

HEALTHY = "HEALTHY"
DEGRADED = "DEGRADED"
RESHAPING = "RESHAPING"

_META_FILE = "elastic_meta.pdckpt"


class CollectiveTimeoutError(RuntimeError):
    """A collective timed out — transient until proven persistent: the
    step did NOT commit, so the trainer retries it with backoff."""

    def __init__(self, msg: str = "collective timeout",
                 lost_index: Optional[int] = None, axis: str = DP_AXIS):
        super().__init__(msg)
        self.lost_index = lost_index
        self.axis = axis


class WorkerLostError(RuntimeError):
    """A worker is gone for good.  ``lost_index`` is the flat index of
    the lost device in the current mesh (None when the failing worker
    could not be attributed — the mesh is rebuilt at the SAME topology);
    ``axis`` names the mesh axis the loss is attributed to."""

    def __init__(self, msg: str = "worker lost",
                 lost_index: Optional[int] = None, axis: str = DP_AXIS):
        super().__init__(msg)
        self.lost_index = lost_index
        self.axis = axis


@dataclass
class ElasticPolicy:
    """Knobs for detection, retry, reshape, and defense (documented in
    docs/fault_tolerance.md)."""

    step_deadline_s: float = 60.0     # post-hoc per-step wall budget
    deadline_strikes: int = 2         # consecutive blown deadlines → loss
    max_retries: int = 2              # transient-collective retries/step
    backoff_s: float = 0.05           # first retry sleep
    backoff_factor: float = 2.0       # exponential backoff multiplier
    straggler_window: int = 16        # step-time sliding window
    straggler_factor: float = 3.0     # × window median → DEGRADED
    max_consecutive_skips: int = 3    # StepGuard abort threshold
    checkpoint_every: int = 0         # 0 = only explicit save_checkpoint()
    min_world_size: int = 1           # refuse to shrink below this


class ElasticTrainer:
    """Supervise a :class:`DistributedEngine` through worker loss.

    ``data_fn(step) -> (inputs, labels)`` must be deterministic in
    ``step`` — it is both the training data source and the replay
    mechanism after a checkpoint restore.  RNG is derived per step as
    ``fold_in(key(rng_seed), step)`` so a resumed or reshaped run draws
    the exact keys of an uninterrupted one."""

    def __init__(self, network, optimizer, loss_fn,
                 data_fn: Callable[[int], Any], *,
                 topology: Optional[HybridTopology] = None,
                 sharding_stage: int = 0,
                 policy: Optional[ElasticPolicy] = None,
                 checkpoint_dir: Optional[str] = None,
                 aot_dir: Optional[str] = None,
                 rng_seed: int = 0,
                 recompute: bool = False,
                 amp_dtype: Optional[str] = None,
                 skip_nonfinite: bool = True,
                 metrics=None):
        if metrics is None:
            from ..observability import REGISTRY
            metrics = REGISTRY
        self.metrics = metrics
        self.policy = policy or ElasticPolicy()
        self.data_fn = data_fn
        self.checkpoint_dir = checkpoint_dir
        self.aot_dir = aot_dir
        self.rng_seed = int(rng_seed)
        self._base_key = jax.random.key(self.rng_seed)
        self.topo = topology or get_topology()
        self._engine_kwargs = dict(
            sharding_stage=sharding_stage, recompute=recompute,
            amp_dtype=amp_dtype, skip_nonfinite=skip_nonfinite)
        self._network = network
        self._optimizer = optimizer
        self._loss_fn = loss_fn
        self.engine = DistributedEngine(
            network, optimizer, loss_fn, topology=self.topo,
            **self._engine_kwargs)
        self.guard = StepGuard(
            max_consecutive=self.policy.max_consecutive_skips,
            metrics=metrics)
        self.state = HEALTHY
        self.reshapes = 0
        self.retries = 0
        self.workers_lost = 0
        self.steps_replayed = 0
        self.last_recovery_s = 0.0
        self._step_times: deque = deque(
            maxlen=self.policy.straggler_window)
        self._deadline_strikes = 0
        self._global_batch: Optional[int] = None
        self._last_ckpt_step: Optional[int] = None

    # ------------------------------------------------------------------
    # bookkeeping
    # ------------------------------------------------------------------
    @property
    def global_step(self) -> int:
        return self.engine._step_count

    def _rng_for(self, step: int):
        return jax.random.fold_in(self._base_key, step)

    def _event(self, action: str, **kw) -> None:
        m = self.metrics
        if m is not None and m.enabled:
            m.counter(f"train.elastic.{action}_total").inc()
            m.event("elastic", action=action, step=self.global_step, **kw)

    def _set_state(self, state: str) -> None:
        if state != self.state:
            self.state = state
            m = self.metrics
            if m is not None and m.enabled:
                m.gauge("train.elastic.degraded").set(
                    1.0 if state == DEGRADED else 0.0)
                m.event("elastic", action="state", state=state,
                        step=self.global_step)

    # ------------------------------------------------------------------
    # warm rebuild (per-topology AOT entries)
    # ------------------------------------------------------------------
    def _install_step_fn(self, inputs, labels) -> None:
        """Point ``engine._step_fn`` at this topology's program: the AOT
        entry when one exists (zero compiles), else a fresh compile that
        is immediately exported so the NEXT resume at this topology is
        warm."""
        if self.engine._step_fn is not None:
            return
        if self.engine._state is None:
            self.engine.shard_state()
        if self.aot_dir is None:
            self.engine.build_train_step()
            return
        from ..aot.artifact import AotError
        from ..aot.train import export_engine_step, load_engine_step
        try:
            self.engine._step_fn = load_engine_step(
                self.engine, self.aot_dir, registry=self.metrics)
            self._event("aot_warm_load",
                        topology=dict(self.topo.degrees))
            return
        except AotError as e:
            self._event("aot_fallback", reason=type(e).__name__)
        _, compiled = export_engine_step(
            self.engine, inputs, labels, self.aot_dir,
            registry=self.metrics)
        # export_engine_step left engine._step_fn as the fresh jit; the
        # already-compiled executable is strictly better (no retrace)
        self.engine._step_fn = compiled

    # ------------------------------------------------------------------
    # checkpointing (hardened sharded checkpoint + meta sidecar)
    # ------------------------------------------------------------------
    def _ckpt_state_dict(self) -> Dict[str, Any]:
        # Tensor-wrapped leaves: parameter names contain dots, so the
        # loader's in-place fill must go through Tensor._value (the
        # dotted-path write-back would mis-split the keys)
        from ..core.tensor import Tensor
        params, buffers, opt_state = self.engine._state
        sd: Dict[str, Any] = {
            "params": {n: Tensor(v) for n, v in params.items()},
            "buffers": {n: Tensor(v) for n, v in buffers.items()},
        }
        if opt_state is not None:
            sd["opt"] = {p: {s: Tensor(v) for s, v in slots.items()}
                         for p, slots in opt_state.items()}
        return sd

    def save_checkpoint(self) -> None:
        if self.checkpoint_dir is None:
            raise ValueError("ElasticTrainer(checkpoint_dir=...) unset")
        if self.engine._state is None:
            self.engine.shard_state()
        import os

        from ..framework import io as fio
        save_state_dict(self._ckpt_state_dict(), self.checkpoint_dir,
                        topology=self.topo)
        fio.save({"step": self.engine._step_count,
                  "rng_seed": self.rng_seed,
                  "optimizer": self._optimizer.state_dict(),
                  "guard": self.guard.state_dict()},
                 os.path.join(self.checkpoint_dir, _META_FILE))
        self._last_ckpt_step = self.engine._step_count
        self._event("checkpoint", step=self.engine._step_count)

    def _restore_checkpoint(self) -> int:
        """Load the hardened sharded checkpoint into the CURRENT engine
        (explicit reshape — the saved topology may differ) and return
        the restored step."""
        import os

        from ..framework import io as fio
        meta = fio.load(os.path.join(self.checkpoint_dir, _META_FILE))
        if self.engine._state is None:
            # stage placeholder state at the new topology so the loader
            # has correctly-sharded destination arrays to fill.  The
            # Layer's tensors may be DELETED (the previous engine's
            # donated step consumed them) — only their avals survive, so
            # rebuild zero arrays of the right shape/dtype first.
            import jax.numpy as jnp
            net = self.engine.network
            leaves = list(net.named_parameters()) + [
                (n, b) for n, b in net.named_buffers() if b is not None]
            for _, t in leaves:
                v = t._value
                if isinstance(v, jax.Array) and v.is_deleted():
                    t._value = jnp.zeros(v.shape, v.dtype)
            self.engine.shard_state()
        sd = self._ckpt_state_dict()
        load_state_dict(sd, self.checkpoint_dir, reshape=True)
        params, buffers, opt_state = self.engine._state
        new_params = {n: sd["params"][n]._value for n in params}
        new_buffers = {n: sd["buffers"][n]._value for n in buffers}
        new_opt = None
        if opt_state is not None:
            new_opt = {p: {s: sd["opt"][p][s]._value for s in slots}
                       for p, slots in opt_state.items()}
        self.engine._state = (new_params, new_buffers, new_opt)
        for n, p in self.engine.network.named_parameters():
            if n in new_params:
                p._value = new_params[n]
        self.engine._step_count = int(meta["step"])
        self._optimizer.set_state_dict(meta["optimizer"])
        self.guard.load_state_dict(meta.get("guard", {}))
        return int(meta["step"])

    # ------------------------------------------------------------------
    # reshape policy
    # ------------------------------------------------------------------
    def _valid_degree(self, axis: str, survivors: int) -> int:
        """Largest new degree for ``axis``: ≤ current−1, divides the
        global batch (with the other data axis), and the full mesh fits
        on the survivors.  Falls back through divisors — XLA refuses
        uneven sharded batch dims, so dp 8→7 with batch 8 lands on 4."""
        cur = self.topo.axis_size(axis)
        is_data = axis in (DP_AXIS, SHARDING_AXIS)
        other = int(np.prod([self.topo.axis_size(a)
                             for a in (DP_AXIS, SHARDING_AXIS)
                             if a != axis]))
        fixed = int(np.prod([self.topo.axis_size(a) for a in AXIS_ORDER
                             if a not in (DP_AXIS, SHARDING_AXIS)
                             and a != axis]))
        batch = self._global_batch
        for cand in range(cur - 1, 0, -1):
            if fixed * other * cand > survivors:
                continue
            if fixed * other * cand < self.policy.min_world_size:
                break
            # only data axes shard the batch dim — shrinking pp/mp/sep
            # leaves the per-device batch untouched
            data_deg = cand * other if is_data else other
            if batch is not None and batch % data_deg != 0:
                continue
            return cand
        raise WorkerLostError(
            f"no valid topology below {axis}={cur} for batch "
            f"{batch} on {survivors} survivors "
            f"(min_world_size={self.policy.min_world_size})")

    def _reconstructible(self, lost_axis: str) -> bool:
        """Is every shard the lost worker held still present on some
        survivor?  True when each spec either never shards over
        ``lost_axis`` or is replicated across another axis of size > 1
        (ZeRO os_g: os/grad shards ride the sharding axis, replicated
        over dp)."""
        eng = self.engine
        if not eng.param_specs:
            eng._derive_specs()
        all_specs: List = list(eng.param_specs.values())
        for slots in eng.opt_specs.values():
            all_specs.extend(slots.values())
        repl_product = int(np.prod(
            [self.topo.axis_size(a) for a in AXIS_ORDER if a != lost_axis]))
        for spec in all_specs:
            axes = set()
            for entry in spec:
                if entry is None:
                    continue
                axes.update(entry if isinstance(entry, tuple) else (entry,))
            if lost_axis not in axes:
                continue
            if repl_product <= 1:
                return False
        return True

    def _reshape(self, err) -> None:
        """Tear down the mesh, rebuild over the survivors, and carry or
        restore the training state.  On return the engine is ready to
        (re)execute the step that failed."""
        t0 = time.perf_counter()
        self._set_state(RESHAPING)
        self.workers_lost += 1
        before_step = self.engine._step_count
        lost_index = getattr(err, "lost_index", None)
        axis = getattr(err, "axis", DP_AXIS)
        devices = list(self.topo.mesh.devices.flat)
        if lost_index is not None:
            survivors = [d for i, d in enumerate(devices)
                         if i != int(lost_index)]
            degrees = dict(self.topo.degrees)
            degrees[axis] = self._valid_degree(axis, len(survivors))
        else:
            # unattributed persistent failure: rebuild at the SAME
            # topology (the resume-at-same-topology warm path)
            survivors = devices
            degrees = dict(self.topo.degrees)
        carry = self._reconstructible(axis) if lost_index is not None \
            else True
        host_state = self.engine.host_state() if carry else None
        new_topo = HybridTopology(devices=survivors, **degrees)
        set_topology(new_topo)
        self.topo = new_topo
        self.engine = DistributedEngine(
            self._network, self._optimizer, self._loss_fn,
            topology=new_topo, **self._engine_kwargs)
        replayed = 0
        if carry:
            self.engine.load_host_state(host_state)
        else:
            if self.checkpoint_dir is None:
                raise WorkerLostError(
                    "lost state is not reconstructible from survivors "
                    "and no checkpoint_dir is configured") from err
            restored = self._restore_checkpoint()
            # deterministic replay: same batches (data_fn is a pure
            # function of step) + same fold_in keys ⇒ the replayed
            # trajectory is the uninterrupted one
            self._install_step_fn(*self.data_fn(restored))
            while self.engine._step_count < before_step:
                s = self.engine._step_count
                inputs, labels = self.data_fn(s)
                self.engine.train_batch(inputs, labels,
                                        rng=self._rng_for(s))
                replayed += 1
        self._install_step_fn(*self.data_fn(self.engine._step_count))
        self.steps_replayed += replayed
        self.reshapes += 1
        self.last_recovery_s = time.perf_counter() - t0
        m = self.metrics
        if m is not None and m.enabled:
            m.counter("train.elastic.worker_lost_total").inc()
            m.counter("train.elastic.reshapes_total").inc()
            m.histogram("train.elastic.recovery_s", unit="s").record(
                self.last_recovery_s)
            m.event("elastic", action="reshape",
                    step=self.engine._step_count,
                    carryover=carry, replayed=replayed,
                    degrees={k: v for k, v in degrees.items() if v > 1},
                    world_size=new_topo.world_size,
                    recovery_s=round(self.last_recovery_s, 4),
                    cause=f"{type(err).__name__}: {err}")
        from ..observability.tracing import TRACER
        if TRACER.enabled:
            tr = TRACER.train_trace()
            t1 = tr.now()
            # a reshape can predate the lazily-created trace: clamp
            # into the trace window, keep the true duration in secs=
            tr.add("reshape", max(t1 - self.last_recovery_s, 0.0), t1,
                   carryover=bool(carry), replayed=int(replayed),
                   secs=round(self.last_recovery_s, 6),
                   world_size=int(new_topo.world_size),
                   cause=type(err).__name__)
        self._step_times.clear()
        self._deadline_strikes = 0
        self._set_state(HEALTHY)

    # ------------------------------------------------------------------
    # straggler tracking
    # ------------------------------------------------------------------
    def _observe_step_time(self, dt: float) -> bool:
        """Record one step's wall time; returns True when the step blew
        the deadline (a strike)."""
        m = self.metrics
        if m is not None and m.enabled:
            m.histogram("train.elastic.step_time_s", unit="s").record(dt)
        window = list(self._step_times)
        self._step_times.append(dt)
        if (len(window) >= max(4, self.policy.straggler_window // 4)
                and dt > self.policy.straggler_factor * median(window)):
            self._set_state(DEGRADED)
            self._event("straggler", step_time_s=round(dt, 4),
                        window_median_s=round(median(window), 4))
        elif self.state == DEGRADED:
            self._set_state(HEALTHY)
        if dt > self.policy.step_deadline_s:
            self._deadline_strikes += 1
            self._event("deadline_exceeded", step_time_s=round(dt, 4),
                        strikes=self._deadline_strikes)
            return True
        self._deadline_strikes = 0
        return False

    # ------------------------------------------------------------------
    # the supervised step
    # ------------------------------------------------------------------
    def step(self) -> float:
        """Run ONE training step at the current global step, surviving
        transient collective faults, worker loss (reshape + carryover /
        restore + replay), stragglers, and SDC.  Returns the loss."""
        inputs, labels = self.data_fn(self.global_step)
        arr0 = np.asarray(inputs[0] if isinstance(inputs, (list, tuple))
                          else inputs)
        self._global_batch = int(arr0.shape[0]) if arr0.ndim else None
        self._install_step_fn(inputs, labels)
        attempts = 0
        delay = self.policy.backoff_s
        while True:
            t0 = time.perf_counter()
            try:
                loss = self.engine.train_batch(
                    inputs, labels, rng=self._rng_for(self.global_step))
            except CollectiveTimeoutError as e:
                attempts += 1
                self.retries += 1
                self._event("retry", attempt=attempts,
                            cause=f"{type(e).__name__}: {e}")
                if attempts > self.policy.max_retries:
                    self._reshape(WorkerLostError(
                        f"collective failure persisted through "
                        f"{attempts} attempts: {e}",
                        lost_index=e.lost_index, axis=e.axis))
                    attempts = 0
                    delay = self.policy.backoff_s
                    continue
                time.sleep(delay)
                delay *= self.policy.backoff_factor
                continue
            except WorkerLostError as e:
                self._reshape(e)
                attempts = 0
                delay = self.policy.backoff_s
                continue
            blown = self._observe_step_time(time.perf_counter() - t0)
            if blown and self._deadline_strikes >= \
                    self.policy.deadline_strikes:
                # the step COMMITTED (state advanced) — reshape before
                # the next one rather than re-running this one
                self._reshape(WorkerLostError(
                    f"step deadline ({self.policy.step_deadline_s}s) "
                    f"blown {self._deadline_strikes}x consecutively"))
            break
        self.guard.record(self.engine.last_skipped,
                          step=self.global_step, loss=loss)
        if self.engine.last_skipped:
            self._event("sdc_skip", loss=loss)
        if (self.policy.checkpoint_every
                and self.checkpoint_dir is not None
                and self.global_step % self.policy.checkpoint_every == 0):
            self.save_checkpoint()
        return loss

    def run(self, num_steps: int) -> List[float]:
        """``num_steps`` supervised steps; returns their losses (replay
        after a checkpoint restore happens inside :meth:`step` and is
        not double-counted)."""
        return [self.step() for _ in range(num_steps)]
