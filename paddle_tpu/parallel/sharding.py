"""Sharding stages 1-3 (ZeRO) as sharding-spec policies.

Reference implementations are wrapper classes shuffling buffers by hand:
stage1 ``DygraphShardingOptimizer`` (fleet/meta_optimizers/dygraph_optimizer/
dygraph_sharding_optimizer.py:44), stage2 ``GroupShardedOptimizerStage2``
(group_sharded_optimizer_stage2.py:53), stage3 ``GroupShardedStage3``
(group_sharded_stage3.py:85 — pre-forward allgather, post-backward
reduce-scatter + release).

TPU-native: each stage is a *placement policy* over the ``sharding`` mesh
axis; XLA's SPMD partitioner then emits exactly the ZeRO communication
pattern (all-gather params before use, reduce-scatter grads to the owner
shard) — the hand-written bucketing/overlap machinery dissolves:

* stage 1 — optimizer state sharded; params+grads replicated
* stage 2 — optimizer state + grads sharded
* stage 3 — optimizer state + grads + params sharded
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
from jax.sharding import PartitionSpec as P

from .topology import SHARDING_AXIS, HybridTopology

__all__ = ["ShardingStage", "shard_spec_for", "opt_state_spec_for",
           "grad_spec_for", "group_sharded_parallel"]


class ShardingStage:
    NONE = 0
    STAGE1 = 1
    STAGE2 = 2
    STAGE3 = 3


def _first_shardable_dim(shape, taken_dims, size: int) -> Optional[int]:
    for i, s in enumerate(shape):
        if i in taken_dims:
            continue
        if s % size == 0 and s >= size:
            return i
    return None


def _add_axis(spec: P, shape, size: int) -> P:
    """Extend a param's spec with the sharding axis on the first free,
    divisible dim (the ZeRO partition dimension)."""
    entries = list(spec) if spec else []
    while len(entries) < len(shape):
        entries.append(None)
    taken = {i for i, e in enumerate(entries) if e is not None}
    dim = _first_shardable_dim(shape, taken, size)
    if dim is None:
        return P(*entries) if entries else P()
    entries[dim] = SHARDING_AXIS
    return P(*entries)


def shard_spec_for(param_spec: P, shape, stage: int,
                   topo: HybridTopology) -> P:
    """Parameter placement under the given stage."""
    size = topo.axis_size(SHARDING_AXIS)
    if stage >= ShardingStage.STAGE3 and size > 1:
        return _add_axis(param_spec or P(), shape, size)
    return param_spec or P()


def grad_spec_for(param_spec: P, shape, stage: int, topo: HybridTopology) -> P:
    size = topo.axis_size(SHARDING_AXIS)
    if stage >= ShardingStage.STAGE2 and size > 1:
        return _add_axis(param_spec or P(), shape, size)
    return param_spec or P()


def opt_state_spec_for(param_spec: P, shape, stage: int,
                       topo: HybridTopology) -> P:
    size = topo.axis_size(SHARDING_AXIS)
    if stage >= ShardingStage.STAGE1 and size > 1:
        return _add_axis(param_spec or P(), shape, size)
    return param_spec or P()


def group_sharded_parallel(model, optimizer, level: str = "os_g",
                           scaler=None, group=None, offload=False,
                           sync_buffers=False, buffer_max_size=2 ** 23,
                           segment_size=2 ** 20, sync_comm=False):
    """Facade parity with paddle.distributed.sharding.group_sharded_parallel
    (python/paddle/distributed/sharding/group_sharded.py): level 'os' →
    stage1, 'os_g' → stage2, 'p_g_os' → stage3.  Returns the engine-wrapped
    model/optimizer."""
    from .engine import DistributedEngine
    from .topology import get_topology
    stage = {"os": 1, "os_g": 2, "p_g_os": 3}[level]
    eng = DistributedEngine(model, optimizer, topology=get_topology(),
                            sharding_stage=stage)
    return eng, optimizer, scaler
