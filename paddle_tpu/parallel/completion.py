"""Sharding-completion pass + communication cost model for the semi-auto
Engine.

Reference: python/paddle/distributed/auto_parallel/static/completion.py
(sharding propagation over the Program), static/cost/ (comm/comp cost
model), phi/infermeta/spmd_rules dispatch.

TPU-native shape: the pass walks a recorded ``static.Program`` (our op
graph) in order, inferring a :class:`TensorDistAttr` for every Variable
from the per-op rules in :mod:`spmd_rules`; where a rule requires an
input placed differently than the producer provided, a **reshard edge**
is recorded.  The result is a :class:`CompletionPlan` the engine can (a)
apply as ``with_sharding_constraint`` annotations and (b) price with the
cost model — collective byte counts on the mesh, the reference's
CommOpCost analog.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .spmd_rules import (TensorDistAttr, add_n_rule, argmax_rule,
                         concat_rule, cumsum_rule, elementwise_rule,
                         embedding_rule, expand_rule, flash_attention_rule,
                         flatten_rule, full_like_rule, fused_rope_rule,
                         gather_nd_rule, gather_rule, layer_norm_rule,
                         matmul_rule, numel_rule, one_hot_rule,
                         reduction_rule, reshape_rule, rms_norm_rule,
                         scale_rule, scatter_rule, slice_rule, softmax_rule,
                         split_rule, squared_l2_norm_rule, squeeze_rule,
                         stack_rule, swiglu_rule, tile_rule, transpose_rule,
                         triu_rule, unbind_rule, unsqueeze_rule, where_rule)

__all__ = ["CompletionPlan", "Reshard", "complete_program",
           "estimate_reshard_cost", "estimate_plan_cost", "ICI_BW_GBPS"]

# v5p ICI per-link bandwidth ballpark used by the default cost model
# (GB/s, one direction).  The absolute number only scales the time
# estimate; RELATIVE plan comparisons (the tuner's use) are bw-free.
ICI_BW_GBPS = 90.0


@dataclass
class Reshard:
    """One required placement change on an edge (reference reshard pair)."""
    var_name: str
    src: TensorDistAttr
    dst: TensorDistAttr
    nbytes: int
    kind: str                 # r_to_s | s_to_r | s_to_s | p_to_r | ...
    comm_bytes: int           # bytes crossing ICI for this reshard


@dataclass
class CompletionPlan:
    attrs: Dict[str, TensorDistAttr] = field(default_factory=dict)
    reshards: List[Reshard] = field(default_factory=list)
    # op name -> SPMD rule that fired ("replicate_fallback" = no rule and
    # no rank to merge: the silent perf cliff VERDICT r3 item 3 tracks)
    node_rules: List[Tuple[str, str]] = field(default_factory=list)

    def fallback_nodes(self) -> List[str]:
        return [n for n, r in self.node_rules
                if r == "replicate_fallback"]

    def total_comm_bytes(self) -> int:
        return sum(r.comm_bytes for r in self.reshards)

    def summary(self) -> str:
        lines = [f"{len(self.attrs)} vars annotated, "
                 f"{len(self.reshards)} reshards, "
                 f"{self.total_comm_bytes() / 1e6:.2f} MB comm"]
        for r in self.reshards:
            lines.append(f"  {r.var_name}: {r.kind} {r.src} -> {r.dst} "
                         f"({r.comm_bytes / 1e6:.2f} MB)")
        return "\n".join(lines)


def _classify(src: TensorDistAttr, dst: TensorDistAttr) -> str:
    if src.partial and not dst.partial:
        return "p_to_s" if any(dst.dims_mapping) else "p_to_r"
    s_shard = [a for a in src.dims_mapping if a]
    d_shard = [a for a in dst.dims_mapping if a]
    if not s_shard and d_shard:
        return "r_to_s"
    if s_shard and not d_shard:
        return "s_to_r"
    if s_shard and d_shard and src.dims_mapping != dst.dims_mapping:
        return "s_to_s"
    return "noop"


def estimate_reshard_cost(nbytes: int, kind: str,
                          mesh_axis_size: int) -> int:
    """Bytes crossing the interconnect for one reshard (reference
    static/cost comm-op formulas; ring-algorithm counts):
      all-gather  (s_to_r): (n-1)/n * full_bytes
      all-reduce  (p_to_r): 2 (n-1)/n * full_bytes
      reduce-scatter (p_to_s): (n-1)/n * full_bytes
      all-to-all  (s_to_s): (n-1)/n * full_bytes / n  per-device slice move
      slice       (r_to_s): 0
    """
    n = max(mesh_axis_size, 1)
    f = (n - 1) / n
    if kind == "s_to_r":
        return int(nbytes * f)
    if kind == "p_to_r":
        return int(2 * nbytes * f)
    if kind == "p_to_s":
        return int(nbytes * f)
    if kind == "s_to_s":
        return int(nbytes * f / n)
    return 0


def _var_bytes(var) -> int:
    shape = tuple(1 if d in (None, -1) else int(d) for d in var.shape)
    return int(np.prod(shape, dtype=np.int64)) * var.dtype.itemsize


_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "pow",
    "relu", "gelu", "silu", "tanh", "sigmoid", "exp", "log", "sqrt",
    "rsqrt", "neg", "abs", "scale", "cast", "dropout", "where",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "assign", "clip",
}
_REDUCTIONS = {"mean", "sum", "max", "min", "prod"}


def _int_like(v) -> Optional[List[int]]:
    if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        return [int(v)]
    if isinstance(v, (list, tuple)) and v and all(
            isinstance(i, (int, np.integer)) and not isinstance(i, bool)
            for i in v):
        return [int(i) for i in v]
    return None


def _reduction_axes(node, ndim_in: int, ndim_out: int) -> List[int]:
    """Reduced axes from the node's recorded static args (the op's
    ``axis``); falls back to shape diffing (keepdim: out dim == 1 where
    in dim != 1; rank drop with no static info: all axes)."""
    for s in getattr(node, "statics", ()):
        ax = _int_like(s)
        if ax is not None and all(-ndim_in <= a < ndim_in for a in ax):
            n_drop = ndim_in - ndim_out
            if n_drop in (0, len(ax)):
                return [a % ndim_in for a in ax]
    if ndim_out == ndim_in and hasattr(node.in_vars[0], "shape"):
        ishape = node.in_vars[0].shape
        oshape = node.out_vars[0].shape
        return [i for i in range(ndim_in)
                if oshape[i] == 1 and ishape[i] != 1]
    return []


def _find_static_perm(node, nd: int) -> Optional[Sequence[int]]:
    for s in getattr(node, "statics", ()):
        p = _int_like(s)
        if p is not None and sorted(p) == list(range(nd)):
            return p
    return None


def _static_axis(node, default: int = 0) -> int:
    """First int-like static arg (the ``axis`` of concat/stack/split…)."""
    for s in getattr(node, "statics", ()):
        ax = _int_like(s)
        if ax is not None and len(ax) == 1:
            return ax[0]
    return default


def _static_axes(node) -> Optional[List[int]]:
    for s in getattr(node, "statics", ()):
        ax = _int_like(s)
        if ax is not None:
            return ax
    return None


def _static_ints_flat(node) -> List[int]:
    """ALL int-like static leaves in order (flatten's (start, stop) are
    two separate scalars, unlike slice's single axes list)."""
    out: List[int] = []
    for s in getattr(node, "statics", ()):
        ax = _int_like(s)
        if ax is not None:
            out.extend(ax)
    return out


def _split_axis(node) -> int:
    """split/chunk record (num_or_sections, axis): the axis is the LAST
    single-int static when two int-like statics exist; a lone static is
    the section count (axis defaults to 0)."""
    ints = [_int_like(s) for s in getattr(node, "statics", ())]
    ints = [i for i in ints if i is not None]
    if len(ints) >= 2 and len(ints[-1]) == 1:
        return ints[-1][0]
    return 0


def _infer_node(name: str, in_attrs: List[TensorDistAttr], node):
    """Dispatch an op to its SPMD rule; returns (required_in, out_attrs,
    rule_name).

    Unknown ops fall back to the elementwise merge when ranks match, else
    replicate — the reference completion's default strategy."""
    base = name.split("_\n")[0]
    outs = node.out_vars
    if base == "matmul" and len(in_attrs) >= 2:
        xr, yr, o = matmul_rule(in_attrs[0], in_attrs[1])
        return [xr, yr] + in_attrs[2:], [o] * len(outs), "matmul"
    if base == "linear" and len(in_attrs) >= 2:
        # linear(x, w[, b]) = matmul + bias broadcast; bias follows the
        # weight's n-dim sharding (reference fused_gemm_epilogue rule)
        xr, yr, o = matmul_rule(in_attrs[0], in_attrs[1])
        reqs = [xr, yr]
        if len(in_attrs) > 2:
            reqs.append(TensorDistAttr([yr.dims_mapping[-1]]))
            reqs.extend(in_attrs[3:])
        return reqs, [o] * len(outs), "matmul"
    if base == "softmax":
        req, o = softmax_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "softmax"
    if base == "layer_norm":
        req, o = layer_norm_rule(in_attrs[0])
        return [req] + [a.replicate() for a in in_attrs[1:]], \
            [o] * len(outs), "layer_norm"
    if base == "rms_norm" and in_attrs:
        req, o = rms_norm_rule(in_attrs[0])
        return [req] + [a.replicate() for a in in_attrs[1:]], \
            [o] * len(outs), "rms_norm"
    if base == "embedding" and len(in_attrs) >= 2:
        # our embedding op takes (ids, table)
        tr, ir, o = embedding_rule(in_attrs[1], in_attrs[0])
        return [ir, tr] + in_attrs[2:], [o] * len(outs), "embedding"
    if base in _REDUCTIONS and in_attrs:
        ndim_in = len(in_attrs[0].dims_mapping)
        ndim_out = len(outs[0].shape)
        axes = _reduction_axes(node, ndim_in, ndim_out)
        keepdim = ndim_out == ndim_in and ndim_in > 0 and axes != []
        req, o = reduction_rule(in_attrs[0], axes or
                                list(range(ndim_in)), keepdim=keepdim)
        return [req] + in_attrs[1:], [o] * len(outs), "reduction"
    if base == "transpose" and in_attrs:
        nd = len(in_attrs[0].dims_mapping)
        perm = _find_static_perm(node, nd) or tuple(range(nd))[::-1]
        req, o = transpose_rule(in_attrs[0], perm)
        return [req] + in_attrs[1:], [o] * len(outs), "transpose"
    if base == "reshape" and in_attrs:
        src_shape = [1 if d in (None, -1) else int(d)
                     for d in node.in_vars[0].shape] \
            if hasattr(node.in_vars[0], "shape") else None
        dst_shape = [1 if d in (None, -1) else int(d)
                     for d in outs[0].shape]
        if src_shape is not None:
            req, o = reshape_rule(in_attrs[0], src_shape, dst_shape)
            return [req] + in_attrs[1:], [o] * len(outs), "reshape"
    if base in ("flash_attention", "scaled_dot_product_attention") \
            and len(in_attrs) >= 3:
        q, k, v, o = flash_attention_rule(*in_attrs[:3])
        return [q, k, v] + in_attrs[3:], [o] * len(outs), "flash_attention"
    # ---- round-4 rule tail ------------------------------------------------
    if base == "concat" and in_attrs:
        nd = in_attrs[0].ndim
        same = [a for a in in_attrs if a.ndim == nd]
        if len(same) == len(in_attrs):
            reqs, o = concat_rule(in_attrs, _static_axis(node))
            return reqs, [o] * len(outs), "concat"
    if base in ("split", "chunk") and in_attrs:
        req, outs_a = split_rule(in_attrs[0], _split_axis(node),
                                 len(outs))
        return [req] + in_attrs[1:], outs_a, "split"
    if base == "stack" and in_attrs:
        nd = in_attrs[0].ndim
        if all(a.ndim == nd for a in in_attrs):
            reqs, o = stack_rule(in_attrs, _static_axis(node))
            return reqs, [o] * len(outs), "stack"
    if base == "unbind" and in_attrs:
        req, outs_a = unbind_rule(in_attrs[0], _static_axis(node),
                                  len(outs))
        return [req] + in_attrs[1:], outs_a, "unbind"
    if base in ("slice", "strided_slice") and in_attrs:
        axes = _static_axes(node) or list(range(in_attrs[0].ndim))
        axes = [a for a in axes if -in_attrs[0].ndim <= a
                < in_attrs[0].ndim]
        req, o = slice_rule(in_attrs[0], axes)
        return [req] + in_attrs[1:], [o] * len(outs), "slice"
    if base == "squeeze" and in_attrs and outs:
        nd_in, nd_out = in_attrs[0].ndim, len(outs[0].shape)
        axes = _static_axes(node)
        if axes is None and hasattr(node.in_vars[0], "shape"):
            axes = [i for i, d in enumerate(node.in_vars[0].shape)
                    if d == 1][: nd_in - nd_out]
        if axes and nd_in - len(axes) == nd_out:
            req, o = squeeze_rule(in_attrs[0], axes)
            return [req] + in_attrs[1:], [o] * len(outs), "squeeze"
    if base == "unsqueeze" and in_attrs and outs:
        axes = _static_axes(node)
        if axes and in_attrs[0].ndim + len(axes) == len(outs[0].shape):
            req, o = unsqueeze_rule(in_attrs[0], axes)
            return [req] + in_attrs[1:], [o] * len(outs), "unsqueeze"
    if base == "flatten" and in_attrs and outs:
        axes = _static_ints_flat(node) or [1, -1]
        if len(axes) >= 2:
            req, o = flatten_rule(in_attrs[0], axes[0], axes[1])
            if o.ndim == len(outs[0].shape):
                return [req] + in_attrs[1:], [o] * len(outs), "flatten"
    if base in ("gather", "take_along_axis", "index_select") \
            and len(in_attrs) >= 2:
        xr, ir, o = gather_rule(in_attrs[0], in_attrs[1],
                                _static_axis(node))
        return [xr, ir] + in_attrs[2:], [o] * len(outs), "gather"
    if base == "gather_nd" and len(in_attrs) >= 2:
        xr, ir, o = gather_nd_rule(in_attrs[0], in_attrs[1])
        return [xr, ir] + in_attrs[2:], [o] * len(outs), "gather_nd"
    if base in ("scatter", "put_along_axis") and len(in_attrs) >= 3:
        xr, ir, ur, o = scatter_rule(in_attrs[0], in_attrs[1],
                                     in_attrs[2])
        return [xr, ir, ur] + in_attrs[3:], [o] * len(outs), "scatter"
    if base in ("cumsum", "cumprod", "cummax", "cummin") and in_attrs:
        req, o = cumsum_rule(in_attrs[0], _static_axis(node))
        return [req] + in_attrs[1:], [o] * len(outs), "cumsum"
    if base in ("argmax", "argmin") and in_attrs and outs:
        nd_in, nd_out = in_attrs[0].ndim, len(outs[0].shape)
        req, o = argmax_rule(in_attrs[0], _static_axis(node),
                             keepdim=nd_in == nd_out)
        if o.ndim == nd_out:
            return [req] + in_attrs[1:], [o] * len(outs), "argmax"
    if base == "one_hot" and in_attrs:
        req, o = one_hot_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "one_hot"
    if base == "tile" and in_attrs and outs:
        reps = _static_axes(node)
        if reps:
            req, o = tile_rule(in_attrs[0], reps)
            if o.ndim == len(outs[0].shape):
                return [req] + in_attrs[1:], [o] * len(outs), "tile"
    if base in ("expand", "broadcast_to", "expand_as") and in_attrs \
            and outs and hasattr(node.in_vars[0], "shape"):
        src = [1 if d in (None, -1) else int(d)
               for d in node.in_vars[0].shape]
        dst = [1 if d in (None, -1) else int(d) for d in outs[0].shape]
        # expand_as_rule is a pure alias of expand_rule (kept for
        # reference-inventory parity in spmd_rules); route both here
        req, o = expand_rule(in_attrs[0], src, dst)
        return [req] + in_attrs[1:], [o] * len(outs), "expand"
    if base in ("triu", "tril") and in_attrs and in_attrs[0].ndim >= 2:
        req, o = triu_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "triu"
    if base in ("fused_rope", "fused_rotary_position_embedding") \
            and in_attrs:
        reqs, os_ = [], []
        for a in in_attrs:
            r, o = fused_rope_rule(a)
            reqs.append(r)
            os_.append(o)
        return reqs, os_[:len(outs)] + [os_[0]] * max(
            0, len(outs) - len(os_)), "fused_rope"
    if base == "swiglu" and in_attrs:
        reqs, o = swiglu_rule(*in_attrs[:2])
        return list(reqs) + in_attrs[2:], [o] * len(outs), "swiglu"
    if base in ("check_finite_and_unscale_", "check_finite_and_unscale",
                "update_loss_scaling_", "update_loss_scaling") and in_attrs:
        from .spmd_rules import amp_ops_rule
        reqs, outs_a, found = amp_ops_rule(in_attrs)
        # found_inf is the LAST output slot of both amp ops; the scaled
        # tensors fill the slots before it
        if len(outs) >= 1:
            o_list = outs_a[:len(outs) - 1] + [found]
        else:
            o_list = []
        return reqs, o_list, "amp_ops"
    if base == "fused_linear_param_grad_add" and len(in_attrs) >= 2:
        from .spmd_rules import fused_linear_param_grad_add_rule
        reqs, dw, dbias = fused_linear_param_grad_add_rule(
            in_attrs[0], in_attrs[1])
        # accumulator inputs (dweight/dbias being added into) must sit in
        # the OUTPUT's layout, partial included — a replicated accumulator
        # summed into per-rank partials would be multiplied by world size
        # at the closing p_to_r
        accs = []
        for a in in_attrs[2:]:
            like = dw if a.ndim == dw.ndim else dbias
            accs.append(TensorDistAttr(list(like.dims_mapping),
                                       set(like.partial)))
        o_list = [dw, dbias][:len(outs)] or [dw]
        return reqs + accs, o_list, "fused_linear_param_grad_add"
    if base in ("sgd_", "momentum_", "adam_", "adamw_", "adamax_",
                "lamb_", "nadam_", "radam_", "asgd_", "rmsprop_",
                "adagrad_", "adadelta_", "rprop_") and in_attrs:
        from .spmd_rules import optimizer_rule
        in_shapes = [getattr(v, "shape", None)
                     for v in getattr(node, "in_vars", [])][1:]
        reqs, o = optimizer_rule(in_attrs[0], in_attrs[1:],
                                 in_shapes or None)
        # scalar state outputs (beta pows, lr) stay replicated; tensor
        # state mirrors the param.  Classify by NUMEL, not ndim — a
        # [1]-shaped beta-pow output on a 1-D param must not inherit the
        # param's sharded mapping (its aliased input is replicated).
        o_list = []
        for ov in outs:
            shp = getattr(ov, "shape", ()) or ()
            nd = len(shp)
            numel = 1
            for d in shp:
                numel *= 1 if d in (None, -1) else int(d)
            if nd == o.ndim and numel > 1:
                o_list.append(TensorDistAttr(list(o.dims_mapping)))
            else:
                o_list.append(TensorDistAttr([None] * nd))
        return reqs, o_list, "optimizer"
    if base == "squared_l2_norm" and in_attrs:
        req, o = squared_l2_norm_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "squared_l2_norm"
    if base == "add_n" and in_attrs:
        nd = in_attrs[0].ndim
        if all(a.ndim == nd for a in in_attrs):
            reqs, o = add_n_rule(in_attrs)
            return reqs, [o] * len(outs), "add_n"
    if base in ("scale", "cast") and in_attrs:
        req, o = scale_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "scale"
    if base == "increment" and in_attrs:
        # x+1 does NOT commute with a pending cross-shard sum: require
        # the partial resolved (p_to_r reshard) before the op
        req = TensorDistAttr(list(in_attrs[0].dims_mapping), set())
        return [req] + in_attrs[1:], \
            [TensorDistAttr(list(req.dims_mapping))] * len(outs), "scale"
    if base == "numel" and in_attrs:
        req, o = numel_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "numel"
    if base in ("full_like", "zeros_like", "ones_like") and in_attrs:
        req, o = full_like_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs), "full_like"
    if base == "where" and len(in_attrs) >= 3:
        reqs, o = where_rule(in_attrs[0], in_attrs[1], in_attrs[2])
        return list(reqs) + in_attrs[3:], [o] * len(outs), "where"

    # default: broadcast-aware elementwise over rank-matching inputs
    ranked = [a for a in in_attrs if a.ndim > 0]
    if ranked:
        reqs, o = elementwise_rule(*in_attrs)
        out_attrs = []
        for ov in outs:
            nd = len(ov.shape)
            out_attrs.append(TensorDistAttr(o.dims_mapping[-nd:] if nd
                                            else [], o.partial))
        rule = "elementwise" if base in _ELEMENTWISE \
            else "elementwise_default"
        return reqs, out_attrs, rule
    return in_attrs, [TensorDistAttr([None] * len(ov.shape))
                      for ov in outs], "replicate_fallback"


def complete_program(program, input_attrs: Dict[str, TensorDistAttr],
                     mesh_shape: Optional[Dict[str, int]] = None,
                     param_attrs: Optional[Dict[str, TensorDistAttr]] = None
                     ) -> CompletionPlan:
    """Propagate placements through a recorded ``static.Program``
    (reference completion.py complete_forward_annotation).

    input_attrs: feed name -> TensorDistAttr.
    param_attrs: parameter name -> attr (default replicated).
    mesh_shape:  axis name -> size (for the cost model; default 8).
    """
    from ..core.tensor import Parameter

    mesh_shape = mesh_shape or {}
    plan = CompletionPlan()
    env: Dict[int, TensorDistAttr] = {}

    for fname, var in program.feeds.items():
        attr = input_attrs.get(fname,
                               TensorDistAttr([None] * len(var.shape)))
        env[id(var)] = attr
        plan.attrs[var.name] = attr

    def axis_size(attr_pair):
        axes = {a for a in attr_pair.dims_mapping if a} | attr_pair.partial
        return max((mesh_shape.get(a, 8) for a in axes), default=8)

    for node in program.nodes:
        in_attrs: List[TensorDistAttr] = []
        holders = []
        for v in node.in_vars:
            if isinstance(v, Parameter):
                pa = (param_attrs or {}).get(
                    v.name, TensorDistAttr([None] * v.ndim))
                in_attrs.append(pa)
                holders.append(v)
            elif v is None:
                in_attrs.append(TensorDistAttr([]))
                holders.append(None)
            else:
                in_attrs.append(env.get(
                    id(v), TensorDistAttr([None] * len(v.shape))))
                holders.append(v)
        req_attrs, out_attrs, rule = _infer_node(node.name, in_attrs, node)
        plan.node_rules.append((node.name, rule))
        for v, have, want in zip(holders, in_attrs, req_attrs):
            if v is None or want is None:
                continue
            if have.dims_mapping != want.dims_mapping or \
                    have.partial != want.partial:
                kind = _classify(have, want)
                if kind != "noop":
                    nb = _var_bytes(v) if hasattr(v, "shape") else 0
                    plan.reshards.append(Reshard(
                        getattr(v, "name", "?"), have, want, nb, kind,
                        estimate_reshard_cost(nb, kind, axis_size(have))))
        for ov, oa in zip(node.out_vars, out_attrs):
            env[id(ov)] = oa
            plan.attrs[ov.name] = oa
    return plan


def estimate_plan_cost(plan: CompletionPlan,
                       bandwidth_gbps: float = ICI_BW_GBPS) -> float:
    """Seconds of pure communication implied by the plan's reshards."""
    return plan.total_comm_bytes() / (bandwidth_gbps * 1e9)
