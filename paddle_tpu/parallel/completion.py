"""Sharding-completion pass + communication cost model for the semi-auto
Engine.

Reference: python/paddle/distributed/auto_parallel/static/completion.py
(sharding propagation over the Program), static/cost/ (comm/comp cost
model), phi/infermeta/spmd_rules dispatch.

TPU-native shape: the pass walks a recorded ``static.Program`` (our op
graph) in order, inferring a :class:`TensorDistAttr` for every Variable
from the per-op rules in :mod:`spmd_rules`; where a rule requires an
input placed differently than the producer provided, a **reshard edge**
is recorded.  The result is a :class:`CompletionPlan` the engine can (a)
apply as ``with_sharding_constraint`` annotations and (b) price with the
cost model — collective byte counts on the mesh, the reference's
CommOpCost analog.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .spmd_rules import (TensorDistAttr, elementwise_rule, embedding_rule,
                         flash_attention_rule, layer_norm_rule, matmul_rule,
                         reduction_rule, reshape_rule, softmax_rule,
                         transpose_rule)

__all__ = ["CompletionPlan", "Reshard", "complete_program",
           "estimate_reshard_cost", "estimate_plan_cost", "ICI_BW_GBPS"]

# v5p ICI per-link bandwidth ballpark used by the default cost model
# (GB/s, one direction).  The absolute number only scales the time
# estimate; RELATIVE plan comparisons (the tuner's use) are bw-free.
ICI_BW_GBPS = 90.0


@dataclass
class Reshard:
    """One required placement change on an edge (reference reshard pair)."""
    var_name: str
    src: TensorDistAttr
    dst: TensorDistAttr
    nbytes: int
    kind: str                 # r_to_s | s_to_r | s_to_s | p_to_r | ...
    comm_bytes: int           # bytes crossing ICI for this reshard


@dataclass
class CompletionPlan:
    attrs: Dict[str, TensorDistAttr] = field(default_factory=dict)
    reshards: List[Reshard] = field(default_factory=list)

    def total_comm_bytes(self) -> int:
        return sum(r.comm_bytes for r in self.reshards)

    def summary(self) -> str:
        lines = [f"{len(self.attrs)} vars annotated, "
                 f"{len(self.reshards)} reshards, "
                 f"{self.total_comm_bytes() / 1e6:.2f} MB comm"]
        for r in self.reshards:
            lines.append(f"  {r.var_name}: {r.kind} {r.src} -> {r.dst} "
                         f"({r.comm_bytes / 1e6:.2f} MB)")
        return "\n".join(lines)


def _classify(src: TensorDistAttr, dst: TensorDistAttr) -> str:
    if src.partial and not dst.partial:
        return "p_to_s" if any(dst.dims_mapping) else "p_to_r"
    s_shard = [a for a in src.dims_mapping if a]
    d_shard = [a for a in dst.dims_mapping if a]
    if not s_shard and d_shard:
        return "r_to_s"
    if s_shard and not d_shard:
        return "s_to_r"
    if s_shard and d_shard and src.dims_mapping != dst.dims_mapping:
        return "s_to_s"
    return "noop"


def estimate_reshard_cost(nbytes: int, kind: str,
                          mesh_axis_size: int) -> int:
    """Bytes crossing the interconnect for one reshard (reference
    static/cost comm-op formulas; ring-algorithm counts):
      all-gather  (s_to_r): (n-1)/n * full_bytes
      all-reduce  (p_to_r): 2 (n-1)/n * full_bytes
      reduce-scatter (p_to_s): (n-1)/n * full_bytes
      all-to-all  (s_to_s): (n-1)/n * full_bytes / n  per-device slice move
      slice       (r_to_s): 0
    """
    n = max(mesh_axis_size, 1)
    f = (n - 1) / n
    if kind == "s_to_r":
        return int(nbytes * f)
    if kind == "p_to_r":
        return int(2 * nbytes * f)
    if kind == "p_to_s":
        return int(nbytes * f)
    if kind == "s_to_s":
        return int(nbytes * f / n)
    return 0


def _var_bytes(var) -> int:
    shape = tuple(1 if d in (None, -1) else int(d) for d in var.shape)
    return int(np.prod(shape, dtype=np.int64)) * var.dtype.itemsize


_ELEMENTWISE = {
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "pow",
    "relu", "gelu", "silu", "tanh", "sigmoid", "exp", "log", "sqrt",
    "rsqrt", "neg", "abs", "scale", "cast", "dropout", "where",
    "less_than", "less_equal", "greater_than", "greater_equal", "equal",
    "assign", "clip",
}
_REDUCTIONS = {"mean", "sum", "max", "min", "prod"}


def _int_like(v) -> Optional[List[int]]:
    if isinstance(v, (int, np.integer)) and not isinstance(v, bool):
        return [int(v)]
    if isinstance(v, (list, tuple)) and v and all(
            isinstance(i, (int, np.integer)) and not isinstance(i, bool)
            for i in v):
        return [int(i) for i in v]
    return None


def _reduction_axes(node, ndim_in: int, ndim_out: int) -> List[int]:
    """Reduced axes from the node's recorded static args (the op's
    ``axis``); falls back to shape diffing (keepdim: out dim == 1 where
    in dim != 1; rank drop with no static info: all axes)."""
    for s in getattr(node, "statics", ()):
        ax = _int_like(s)
        if ax is not None and all(-ndim_in <= a < ndim_in for a in ax):
            n_drop = ndim_in - ndim_out
            if n_drop in (0, len(ax)):
                return [a % ndim_in for a in ax]
    if ndim_out == ndim_in and hasattr(node.in_vars[0], "shape"):
        ishape = node.in_vars[0].shape
        oshape = node.out_vars[0].shape
        return [i for i in range(ndim_in)
                if oshape[i] == 1 and ishape[i] != 1]
    return []


def _find_static_perm(node, nd: int) -> Optional[Sequence[int]]:
    for s in getattr(node, "statics", ()):
        p = _int_like(s)
        if p is not None and sorted(p) == list(range(nd)):
            return p
    return None


def _infer_node(name: str, in_attrs: List[TensorDistAttr], node):
    """Dispatch an op to its SPMD rule; returns (required_in, out_attrs).

    Unknown ops fall back to the elementwise merge when ranks match, else
    replicate — the reference completion's default strategy."""
    base = name.split("_\n")[0]
    outs = node.out_vars
    if base == "matmul" and len(in_attrs) >= 2:
        xr, yr, o = matmul_rule(in_attrs[0], in_attrs[1])
        return [xr, yr] + in_attrs[2:], [o] * len(outs)
    if base == "linear" and len(in_attrs) >= 2:
        # linear(x, w[, b]) = matmul + bias broadcast; bias follows the
        # weight's n-dim sharding (reference fused_gemm_epilogue rule)
        xr, yr, o = matmul_rule(in_attrs[0], in_attrs[1])
        reqs = [xr, yr]
        if len(in_attrs) > 2:
            reqs.append(TensorDistAttr([yr.dims_mapping[-1]]))
            reqs.extend(in_attrs[3:])
        return reqs, [o] * len(outs)
    if base == "softmax":
        req, o = softmax_rule(in_attrs[0])
        return [req] + in_attrs[1:], [o] * len(outs)
    if base == "layer_norm":
        req, o = layer_norm_rule(in_attrs[0])
        return [req] + [a.replicate() for a in in_attrs[1:]], \
            [o] * len(outs)
    if base == "embedding" and len(in_attrs) >= 2:
        # our embedding op takes (ids, table)
        tr, ir, o = embedding_rule(in_attrs[1], in_attrs[0])
        return [ir, tr] + in_attrs[2:], [o] * len(outs)
    if base in _REDUCTIONS and in_attrs:
        ndim_in = len(in_attrs[0].dims_mapping)
        ndim_out = len(outs[0].shape)
        axes = _reduction_axes(node, ndim_in, ndim_out)
        keepdim = ndim_out == ndim_in and ndim_in > 0 and axes != []
        req, o = reduction_rule(in_attrs[0], axes or
                                list(range(ndim_in)), keepdim=keepdim)
        return [req] + in_attrs[1:], [o] * len(outs)
    if base == "transpose" and in_attrs:
        nd = len(in_attrs[0].dims_mapping)
        perm = _find_static_perm(node, nd) or tuple(range(nd))[::-1]
        req, o = transpose_rule(in_attrs[0], perm)
        return [req] + in_attrs[1:], [o] * len(outs)
    if base == "reshape" and in_attrs:
        src_shape = [1 if d in (None, -1) else int(d)
                     for d in node.in_vars[0].shape] \
            if hasattr(node.in_vars[0], "shape") else None
        dst_shape = [1 if d in (None, -1) else int(d)
                     for d in outs[0].shape]
        if src_shape is not None:
            req, o = reshape_rule(in_attrs[0], src_shape, dst_shape)
            return [req] + in_attrs[1:], [o] * len(outs)
    if base in ("flash_attention", "scaled_dot_product_attention") \
            and len(in_attrs) >= 3:
        q, k, v, o = flash_attention_rule(*in_attrs[:3])
        return [q, k, v] + in_attrs[3:], [o] * len(outs)

    # default: broadcast-aware elementwise over rank-matching inputs
    ranked = [a for a in in_attrs if a.ndim > 0]
    if ranked:
        reqs, o = elementwise_rule(*in_attrs)
        out_attrs = []
        for ov in outs:
            nd = len(ov.shape)
            out_attrs.append(TensorDistAttr(o.dims_mapping[-nd:] if nd
                                            else [], o.partial))
        return reqs, out_attrs
    return in_attrs, [TensorDistAttr([None] * len(ov.shape))
                      for ov in outs]


def complete_program(program, input_attrs: Dict[str, TensorDistAttr],
                     mesh_shape: Optional[Dict[str, int]] = None,
                     param_attrs: Optional[Dict[str, TensorDistAttr]] = None
                     ) -> CompletionPlan:
    """Propagate placements through a recorded ``static.Program``
    (reference completion.py complete_forward_annotation).

    input_attrs: feed name -> TensorDistAttr.
    param_attrs: parameter name -> attr (default replicated).
    mesh_shape:  axis name -> size (for the cost model; default 8).
    """
    from ..core.tensor import Parameter

    mesh_shape = mesh_shape or {}
    plan = CompletionPlan()
    env: Dict[int, TensorDistAttr] = {}

    for fname, var in program.feeds.items():
        attr = input_attrs.get(fname,
                               TensorDistAttr([None] * len(var.shape)))
        env[id(var)] = attr
        plan.attrs[var.name] = attr

    def axis_size(attr_pair):
        axes = {a for a in attr_pair.dims_mapping if a} | attr_pair.partial
        return max((mesh_shape.get(a, 8) for a in axes), default=8)

    for node in program.nodes:
        in_attrs: List[TensorDistAttr] = []
        holders = []
        for v in node.in_vars:
            if isinstance(v, Parameter):
                pa = (param_attrs or {}).get(
                    v.name, TensorDistAttr([None] * v.ndim))
                in_attrs.append(pa)
                holders.append(v)
            elif v is None:
                in_attrs.append(TensorDistAttr([]))
                holders.append(None)
            else:
                in_attrs.append(env.get(
                    id(v), TensorDistAttr([None] * len(v.shape))))
                holders.append(v)
        req_attrs, out_attrs = _infer_node(node.name, in_attrs, node)
        for v, have, want in zip(holders, in_attrs, req_attrs):
            if v is None or want is None:
                continue
            if have.dims_mapping != want.dims_mapping or \
                    have.partial != want.partial:
                kind = _classify(have, want)
                if kind != "noop":
                    nb = _var_bytes(v) if hasattr(v, "shape") else 0
                    plan.reshards.append(Reshard(
                        getattr(v, "name", "?"), have, want, nb, kind,
                        estimate_reshard_cost(nb, kind, axis_size(have))))
        for ov, oa in zip(node.out_vars, out_attrs):
            env[id(ov)] = oa
            plan.attrs[ov.name] = oa
    return plan


def estimate_plan_cost(plan: CompletionPlan,
                       bandwidth_gbps: float = ICI_BW_GBPS) -> float:
    """Seconds of pure communication implied by the plan's reshards."""
    return plan.total_comm_bytes() / (bandwidth_gbps * 1e9)
