"""fleet.utils parity (reference distributed/fleet/utils/__init__.py:
LocalFS, recompute, DistributedInfer, HDFSClient).

Mounted as both ``paddle_tpu.parallel.fleet_utils`` and the reference
import path ``paddle_tpu.distributed.fleet.utils``."""

from __future__ import annotations

import os
import shutil
from typing import List, Tuple

from ..distributed.recompute import recompute  # noqa: F401

__all__ = ["LocalFS", "recompute", "DistributedInfer", "HDFSClient"]


class LocalFS:
    """Local filesystem client (reference fleet/utils/fs.py LocalFS —
    the FS abstraction checkpoint/elastic tooling writes through)."""

    def ls_dir(self, fs_path: str) -> Tuple[List[str], List[str]]:
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(fs_path)):
            (dirs if os.path.isdir(os.path.join(fs_path, e))
             else files).append(e)
        return dirs, files

    def mkdirs(self, fs_path: str) -> None:
        os.makedirs(fs_path, exist_ok=True)

    def is_exist(self, fs_path: str) -> bool:
        return os.path.exists(fs_path)

    def is_file(self, fs_path: str) -> bool:
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path: str) -> bool:
        return os.path.isdir(fs_path)

    def delete(self, fs_path: str) -> None:
        if os.path.isdir(fs_path):
            shutil.rmtree(fs_path, ignore_errors=True)
        elif os.path.exists(fs_path):
            os.remove(fs_path)

    def rename(self, fs_src_path: str, fs_dst_path: str) -> None:
        os.replace(fs_src_path, fs_dst_path)

    def touch(self, fs_path: str, exist_ok: bool = True) -> None:
        if os.path.exists(fs_path) and not exist_ok:
            raise FileExistsError(fs_path)
        open(fs_path, "a").close()

    def upload(self, local_path: str, fs_path: str) -> None:
        self._copy(local_path, fs_path)

    def download(self, fs_path: str, local_path: str) -> None:
        self._copy(fs_path, local_path)

    def mv(self, src_path: str, dst_path: str, overwrite: bool = False,
           test_exists: bool = False) -> None:
        if not overwrite and os.path.exists(dst_path):
            raise FileExistsError(dst_path)
        os.replace(src_path, dst_path)

    @staticmethod
    def _copy(src: str, dst: str) -> None:
        if os.path.isdir(src):
            shutil.copytree(src, dst, dirs_exist_ok=True)
        else:
            os.makedirs(os.path.dirname(dst) or ".", exist_ok=True)
            shutil.copy2(src, dst)

    def list_dirs(self, fs_path: str) -> List[str]:
        return self.ls_dir(fs_path)[0]


class HDFSClient:
    """Reference fleet/utils/fs.py HDFSClient: needs a hadoop
    installation this image doesn't ship — the constructor raises the
    documented guard (use LocalFS / a mounted GCS fuse path on TPU)."""

    def __init__(self, hadoop_home: str = "", configs=None, **kw):
        raise NotImplementedError(
            "HDFSClient needs a hadoop runtime; on TPU pods use LocalFS "
            "over a shared/FUSE-mounted path instead (SURVEY §7 stance "
            "on vendor storage clients)")


class DistributedInfer:
    """Reference fleet/utils/__init__.py DistributedInfer — a parameter-
    server-era inference splitter (SURVEY §7: PS is a non-goal).  The
    TPU serving path is paddle.inference.create_predictor over a
    STABLEHLO artifact."""

    def __init__(self, main_program=None, startup_program=None):
        raise NotImplementedError(
            "DistributedInfer is parameter-server-era (SURVEY §7 "
            "non-goal); serve with paddle.inference.create_predictor "
            "instead")
