"""Distributed checkpoint: sharded save + reshard-on-load (reference
python/paddle/distributed/checkpoint/save_state_dict.py:145,
load_state_dict.py — per-rank shard files + a metadata file recording global
shape/placement so load can re-shard onto a different topology; SURVEY §5
checkpoint/resume).

TPU-first: shards are discovered from ``jax.Array.addressable_shards`` (the
GSPMD sharding is the "dist_attr"), written per-process as .npz; load
assembles each *target* shard from whichever saved chunks overlap it, so
any source topology loads onto any destination topology (dp8 -> mp2pp2
etc.).  Works single-process (full arrays) as the degenerate case.

Hardened (ISSUE 17): every file lands via the ``framework.io`` atomic-save
convention (same-dir temp + fsync + rename — a crash mid-save never leaves
a torn shard at the destination), every chunk carries a CRC32 verified on
read, assembly REFUSES partially-covered targets (a missing shard raises
``CheckpointCorruptError``, never zero-fills), and a checkpoint saved with
``topology=`` records a mesh manifest: loading it under a different
topology requires ``reshape=True`` or raises :class:`TopologyMismatchError`
— a silent wrong-topology scatter is the SDC of checkpointing.
"""

from __future__ import annotations

import io as _io
import json
import os
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..framework.io import CheckpointCorruptError, atomic_write_bytes

__all__ = ["save_state_dict", "load_state_dict", "read_topology_manifest",
           "TopologyMismatchError", "clear_async_save_task_queue"]


class TopologyMismatchError(RuntimeError):
    """A sharded checkpoint recorded a mesh-topology manifest that does
    not match the topology it is being loaded under, and the caller did
    not opt into an explicit reshape (``load_state_dict(...,
    reshape=True)``)."""

# -- async save (reference distributed/checkpoint/save_state_dict.py
#    async_save=True + async_save_queue / clear_async_save_task_queue) ----
class _AsyncSaveTask:
    """Background checkpoint writer: records its exception (surfaced by
    :func:`clear_async_save_task_queue`) and remembers its target path
    (saves to the same path serialize instead of racing)."""

    def __init__(self, path: str, fn, args):
        import threading
        self.path = os.path.abspath(path)
        self.exc: BaseException | None = None

        def run():
            try:
                fn(*args)
            except BaseException as e:  # noqa: BLE001 — surfaced on join
                self.exc = e

        # non-daemon: interpreter exit must not truncate a half-written
        # shard file (atexit below also drains the queue)
        self._thread = threading.Thread(target=run, daemon=False)

    def start(self):
        self._thread.start()

    def join(self):
        self._thread.join()
        if self.exc is not None:
            raise RuntimeError(
                f"async checkpoint save to {self.path!r} failed"
            ) from self.exc

    def is_alive(self):
        return self._thread.is_alive()


_async_tasks: list = []


def _drain_done() -> None:
    done = [t for t in _async_tasks if not t.is_alive()]
    _async_tasks[:] = [t for t in _async_tasks if t.is_alive()]
    for t in done:
        t.join()                       # raises if the write failed


def _join_same_path(path: str) -> None:
    """Serialize saves targeting one directory (reference semantics):
    a pending write to the same path must finish before a new one
    starts, or both would interleave into the same shard files."""
    ap = os.path.abspath(path)
    same = [t for t in _async_tasks if t.path == ap]
    for t in same:
        t.join()
    _async_tasks[:] = [t for t in _async_tasks if t.path != ap]


# Joining writer threads from atexit is deliberate: the alternative is
# truncated shard files on interpreter exit.  The writers are plain
# non-daemon threads doing bounded file IO, and the join order (pop
# from the front) cannot deadlock — there are no locks to invert.
def clear_async_save_task_queue() -> None:  # locklint: disable=LK005
    """Block until every pending async checkpoint write finishes; raises
    if any write failed (reference clear_async_save_task_queue)."""
    while _async_tasks:
        t = _async_tasks.pop(0)
        t.join()


import atexit  # noqa: E402

atexit.register(clear_async_save_task_queue)

_META = "metadata.json"


def _flatten(d: Any, prefix: str = "") -> Dict[str, Any]:
    out = {}
    if isinstance(d, dict):
        for k, v in d.items():
            out.update(_flatten(v, f"{prefix}{k}."))
        return out
    # leaf
    out[prefix[:-1]] = d
    return out


def _unwrap(v):
    return v._value if isinstance(v, Tensor) else v


def _index_to_offsets(index: Tuple[slice, ...], shape) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [start, stop] pairs."""
    out = []
    for sl, dim in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = dim if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    return out


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    async_save: bool = False, topology=None) -> None:
    """Write each value's addressable shards + global metadata under
    ``path``.  Multi-process: every process writes its own shard file and
    its own metadata slice; process 0's metadata merge happens at load time
    (all metadata_*.json files are read).

    ``topology=`` (a :class:`~.topology.HybridTopology`) stamps a mesh
    manifest into the metadata; a later :func:`load_state_dict` under a
    DIFFERENT topology then demands an explicit ``reshape=True`` instead
    of silently resharding (ISSUE 17 elastic-training contract).

    ``async_save=True`` (reference async checkpoint): device->host shard
    copies happen NOW (so training can mutate the arrays immediately),
    the disk writes on a background thread;
    ``clear_async_save_task_queue()`` joins all pending writes."""
    rank = getattr(jax, "process_index", lambda: 0)()
    meta, arrays = _snapshot(_flatten(state_dict), rank)
    if topology is not None:
        meta["topology"] = {"degrees": dict(topology.degrees),
                            "world_size": int(topology.world_size)}
    if async_save:
        _drain_done()
        _join_same_path(path)
        t = _AsyncSaveTask(path, _write_snapshot,
                           (path, rank, meta, arrays, coordinator_rank))
        _async_tasks.append(t)
        t.start()
        return
    _write_snapshot(path, rank, meta, arrays, coordinator_rank)


def _snapshot(flat: Dict[str, Any], rank: int):
    """Device->host copy of this process's addressable shards (the part
    that must happen synchronously before training continues)."""
    arrays = {}
    meta: Dict[str, Any] = {"arrays": {}, "chunks": []}
    for key, val in flat.items():
        v = _unwrap(val)
        if v is None:
            continue
        if not isinstance(v, jax.Array):
            v = jnp.asarray(np.asarray(v))
        meta["arrays"][key] = {
            "global_shape": list(v.shape),
            "dtype": str(v.dtype),
        }
        seen = set()
        for shard in v.addressable_shards:
            offs = _index_to_offsets(shard.index, v.shape)
            hkey = tuple(map(tuple, offs))
            if hkey in seen:      # replicated shards: store once
                continue
            seen.add(hkey)
            chunk_id = len(meta["chunks"])
            name = f"c{chunk_id}"
            host = np.asarray(shard.data)
            arrays[name] = host
            meta["chunks"].append({
                "key": key, "npz": f"shard_rank{rank}.npz",
                "name": name, "offsets": offs,
                "crc32": zlib.crc32(np.ascontiguousarray(host).tobytes()),
            })
    return meta, arrays


def _write_snapshot(path: str, rank: int, meta, arrays,
                    coordinator_rank: int) -> None:
    os.makedirs(path, exist_ok=True)
    # atomic-save convention (framework.io): build in memory, land via
    # same-dir temp + fsync + rename — a kill at any byte leaves either
    # the old shard or no shard, never a torn one
    buf = _io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(buf.getvalue(),
                       os.path.join(path, f"shard_rank{rank}.npz"))
    atomic_write_bytes(json.dumps(meta).encode(),
                       os.path.join(path, f"metadata_rank{rank}.json"))
    if rank == coordinator_rank:
        # single merged view for tooling; load() reads the per-rank files
        atomic_write_bytes(
            json.dumps({"format": "paddle_tpu.dist_checkpoint.v1"}).encode(),
            os.path.join(path, _META))


def _read_all_meta(path: str) -> Tuple[Dict, List[Dict], Optional[Dict]]:
    arrays, chunks, topo = {}, [], None
    for fn in sorted(os.listdir(path)):
        if fn.startswith("metadata_rank") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                m = json.load(f)
            arrays.update(m["arrays"])
            chunks.extend(m["chunks"])
            topo = m.get("topology", topo)
    if not arrays:
        raise FileNotFoundError(f"no checkpoint metadata under {path!r}")
    return arrays, chunks, topo


def read_topology_manifest(path: str) -> Optional[Dict]:
    """The mesh-topology manifest a checkpoint was saved under (``None``
    for legacy/manifest-free checkpoints)."""
    return _read_all_meta(path)[2]


def _chunk_data(ch: Dict, loaders) -> np.ndarray:
    """One saved chunk's host array, CRC-verified when the chunk carries
    a checksum (legacy chunks without one load unverified)."""
    try:
        data = loaders[ch["npz"]][ch["name"]]
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint chunk {ch['name']!r} of {ch['key']!r} unreadable "
            f"from {ch['npz']}: {type(e).__name__}: {e}") from e
    if "crc32" in ch:
        got = zlib.crc32(np.ascontiguousarray(data).tobytes())
        if got != ch["crc32"]:
            raise CheckpointCorruptError(
                f"checkpoint chunk {ch['name']!r} of {ch['key']!r} failed "
                f"CRC32 (stored {ch['crc32']}, read {got}) — bit-rot or a "
                "torn write; restore from an older checkpoint")
    return data


def _assemble(target_shape, target_off, chunks, loaders,
              key: str = "?") -> np.ndarray:
    """Fill a buffer of target_shape located at target_off (per-dim
    [start,stop]) from overlapping saved chunks.  Every cell of the
    target must be covered by some chunk — a partially-covered target
    (missing shard file / truncated metadata) raises instead of silently
    zero-filling."""
    buf = None
    covered = None
    for ch in chunks:
        offs = ch["offsets"]
        inter = []
        ok = True
        for (ts, te), (cs, ce) in zip(target_off, offs):
            s, e = max(ts, cs), min(te, ce)
            if s >= e:
                ok = False
                break
            inter.append((s, e))
        if not ok:
            continue
        data = _chunk_data(ch, loaders)
        if buf is None:
            dt = data.dtype
            buf = np.zeros([te - ts for ts, te in target_off], dt)
            covered = np.zeros(buf.shape, dtype=bool)
        src = tuple(slice(s - cs, e - cs) for (s, e), (cs, ce)
                    in zip(inter, offs))
        dst = tuple(slice(s - ts, e - ts) for (s, e), (ts, te)
                    in zip(inter, target_off))
        buf[dst] = data[src]
        covered[dst] = True
    if buf is None:
        raise CheckpointCorruptError(
            f"no saved chunk overlaps the requested shard of {key!r}")
    if not covered.all():
        missing = int(covered.size - covered.sum())
        raise CheckpointCorruptError(
            f"checkpoint shard of {key!r} only partially covered by saved "
            f"chunks ({missing}/{covered.size} cells missing) — a shard "
            "file is absent or its metadata was truncated")
    return buf


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, *, reshape: bool = False,
                    topology=None) -> None:
    """In-place load: every Tensor/array in ``state_dict`` is filled from
    the checkpoint, resharded to its CURRENT sharding.

    When the checkpoint carries a topology manifest (saved with
    ``topology=``) and the loading topology differs, the reshard must be
    requested EXPLICITLY with ``reshape=True`` — otherwise a typed
    :class:`TopologyMismatchError` is raised.  ``topology`` defaults to
    the process-global topology."""
    saved_arrays, chunks, saved_topo = _read_all_meta(path)
    if saved_topo is not None and not reshape:
        from .topology import get_topology
        topo = topology if topology is not None else get_topology()
        here = {"degrees": {k: int(v) for k, v in topo.degrees.items()},
                "world_size": int(topo.world_size)}
        saved = {"degrees": {k: int(v)
                             for k, v in saved_topo["degrees"].items()},
                 "world_size": int(saved_topo["world_size"])}
        if here != saved:
            raise TopologyMismatchError(
                f"checkpoint {path!r} was saved under topology "
                f"{saved} but is being loaded under {here}; pass "
                "reshape=True to reshard explicitly")
    by_key: Dict[str, List[Dict]] = {}
    for ch in chunks:
        by_key.setdefault(ch["key"], []).append(ch)
    try:
        loaders = {fn: np.load(os.path.join(path, fn))
                   for fn in {c["npz"] for c in chunks}}
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint shard file unreadable under {path!r}: "
            f"{type(e).__name__}: {e}") from e

    flat = _flatten(state_dict)
    for key, val in flat.items():
        if key not in saved_arrays:
            raise KeyError(f"{key!r} not found in checkpoint {path!r}")
        info = saved_arrays[key]
        gshape = tuple(info["global_shape"])
        v = _unwrap(val)
        if isinstance(v, jax.Array) and hasattr(v, "sharding") and \
                len(v.sharding.device_set) > 1:
            sharding = v.sharding
            pieces = []
            for d in sharding.addressable_devices:
                idx = sharding.addressable_devices_indices_map(gshape)[d]
                offs = _index_to_offsets(idx, gshape)
                local = _assemble(gshape, offs, by_key[key], loaders, key)
                pieces.append(jax.device_put(local, d))
            new = jax.make_array_from_single_device_arrays(
                gshape, sharding, pieces)
        else:
            full = _assemble(gshape, [[0, s] for s in gshape],
                             by_key[key], loaders, key)
            new = jnp.asarray(full)
            if isinstance(v, jax.Array):
                new = jax.device_put(new, v.sharding)
        if isinstance(val, Tensor):
            val._value = new.astype(jnp.dtype(info["dtype"]))
        else:
            # plain array leaf: write back into the (mutable) dict slot
            _set_by_path(state_dict, key, new)


def _set_by_path(d: Dict, dotted: str, value) -> None:
    parts = dotted.split(".")
    cur = d
    for p in parts[:-1]:
        cur = cur[p]
    cur[parts[-1]] = value
