"""DistributedEngine — the sharded training executor.

Combines the roles of the reference's ``fleet.distributed_model`` wrappers
(fleet/model.py:32), ``HybridParallelOptimizer``
(hybrid_parallel_optimizer.py:255) and the semi-auto ``Engine``
(auto_parallel/static/engine.py:96): given a Layer + Optimizer + topology +
strategy, it

1. derives a PartitionSpec for every parameter (TP layers annotate
   ``param_spec``; ZeRO stages extend specs over the ``sharding`` axis);
2. stages params/opt-state onto the mesh with ``jax.device_put``;
3. compiles ONE donated SPMD train step (forward + backward + grad sync +
   clip + optimizer) with explicit in/out shardings — XLA inserts every
   collective (dp grad psum = the EagerReducer, ZeRO reduce-scatters,
   TP psums) on ICI.

The per-step Python cost is one dispatch — the reference's whole C++
executor/reducer machinery (SURVEY §2.3/§2.5) collapses into the compiled
program.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.rng import next_rng_key
from ..core.tensor import Tensor
from ..nn.layer.layers import Layer, functional_call_with_buffers
from ..optimizer.optimizer import Optimizer
from .sharding import grad_spec_for, opt_state_spec_for, shard_spec_for
from .topology import (DP_AXIS, SHARDING_AXIS, HybridTopology, get_topology)

__all__ = ["DistributedEngine"]


class DistributedEngine:
    def __init__(self, network: Layer, optimizer: Optional[Optimizer] = None,
                 loss_fn: Optional[Callable] = None,
                 topology: Optional[HybridTopology] = None,
                 sharding_stage: int = 0,
                 recompute: bool = False,
                 amp_dtype: Optional[str] = None,
                 skip_nonfinite: bool = False):
        self.network = network
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.topo = topology or get_topology()
        self.sharding_stage = sharding_stage
        self.recompute = recompute
        self.amp_dtype = amp_dtype
        self.skip_nonfinite = skip_nonfinite
        self.grad_hook: Optional[Callable] = None
        self.last_skipped = False
        self._step_fn = None
        self._eval_fn = None
        self._state = None          # (params, buffers, opt_state)
        self._step_count = 0
        self.param_specs: Dict[str, P] = {}
        self.opt_specs: Dict[str, Dict[str, P]] = {}
        self._trainable = {n for n, p in network.named_parameters()
                           if p.trainable}

    # ------------------------------------------------------------------
    # spec derivation
    # ------------------------------------------------------------------
    def _derive_specs(self):
        for name, p in self.network.named_parameters():
            base = getattr(p, "param_spec", P())
            self.param_specs[name] = shard_spec_for(
                base, tuple(p.shape), self.sharding_stage, self.topo)
        for name, b in self.network.named_buffers():
            if b is not None and name not in self.param_specs:
                self.param_specs[name] = P()

    def _opt_state_specs(self, opt_state):
        specs = {}
        named = dict(self.network.named_parameters())
        for pname, slots in opt_state.items():
            base = getattr(named.get(pname), "param_spec",
                           P()) if pname in self._trainable else P()
            sspec = {}
            for sname, v in slots.items():
                sspec[sname] = opt_state_spec_for(
                    base, tuple(np.shape(v)), max(self.sharding_stage, 1)
                    if self.sharding_stage else 0, self.topo)
            specs[pname] = sspec
        return specs

    def _sharding(self, spec: P) -> NamedSharding:
        return NamedSharding(self.topo.mesh, spec)

    # ------------------------------------------------------------------
    # state staging
    # ------------------------------------------------------------------
    def shard_state(self):
        """Place params/buffers/opt-state onto the mesh per derived specs."""
        if not self.param_specs:
            self._derive_specs()
        params, buffers = {}, {}
        for n, p in self.network.named_parameters():
            params[n] = jax.device_put(p._value,
                                       self._sharding(self.param_specs[n]))
            p._value = params[n]
        for n, b in self.network.named_buffers():
            if b is not None:
                buffers[n] = jax.device_put(b._value, self._sharding(P()))
                b._value = buffers[n]
        opt_state = None
        if self.optimizer is not None:
            trainable = {n: params[n] for n in params
                         if n in self._trainable}
            opt_state = self.optimizer.init_state(trainable)
            specs = self._opt_state_specs(opt_state)
            opt_state = {
                pname: {sname: jax.device_put(
                    v, self._sharding(specs[pname][sname]))
                    for sname, v in slots.items()}
                for pname, slots in opt_state.items()}
            self.opt_specs = specs
        self._state = (params, buffers, opt_state)
        return self._state

    # ------------------------------------------------------------------
    # compiled step
    # ------------------------------------------------------------------
    def _data_spec(self) -> P:
        axes = [a for a in (DP_AXIS, SHARDING_AXIS)
                if self.topo.axis_size(a) > 1]
        return P(tuple(axes) if len(axes) > 1 else axes[0]) if axes else P()

    def build_train_step(self, donate: bool = True):
        net = self.network
        opt = self.optimizer
        loss_fn = self.loss_fn
        trainable_names = self._trainable
        amp_dtype = self.amp_dtype
        skip_nonfinite = self.skip_nonfinite
        grad_hook = self.grad_hook

        buffer_names = {n for n, b in net.named_buffers() if b is not None}

        def step(params, buffers, opt_state, step_no, lr, rng, inputs,
                 labels):
            def compute_loss(train_params):
                arrays = {**buffers, **params, **train_params}
                if amp_dtype is not None:
                    # cast params only — buffers (BN running stats, counters)
                    # keep fp32 state per the O1/O2 AMP contract
                    cast = {n: (v.astype(amp_dtype)
                                if n not in buffer_names
                                and jnp.issubdtype(v.dtype, jnp.floating)
                                else v)
                            for n, v in arrays.items()}
                else:
                    cast = arrays
                net.train()
                t_in = [Tensor(v) for v in inputs]
                outs, new_buffers = functional_call_with_buffers(
                    net, cast, *t_in, rng=rng)
                outs_l = outs if isinstance(outs, (list, tuple)) else [outs]
                if loss_fn is not None:
                    t_lab = [Tensor(v) for v in labels]
                    loss = loss_fn(*outs_l, *t_lab)
                else:
                    loss = outs_l[0]
                lv = loss._value if isinstance(loss, Tensor) else loss
                lv = jnp.mean(lv)
                return lv.astype(jnp.float32), new_buffers

            train_params = {n: v for n, v in params.items()
                            if n in trainable_names}
            loss_fn_maybe_remat = (jax.checkpoint(compute_loss)
                                   if self.recompute else compute_loss)
            (loss_v, new_buffers), grads = jax.value_and_grad(
                loss_fn_maybe_remat, has_aux=True)(train_params)
            if grad_hook is not None:
                # chaos seam: a traced grads->grads transform (e.g. the SDC
                # bit-flip injector), gated on step_no so it never retraces
                grads = grad_hook(grads, step_no)
            new_train, new_opt = opt.apply_gradients(
                train_params, grads, opt_state, lr, step_no)
            new_params = dict(params)
            new_params.update(new_train)
            kept = {n: new_buffers.get(n, v) for n, v in buffers.items()}
            if skip_nonfinite:
                from ..checkpoint.step_guard import (guard_select,
                                                     nonfinite_guard)
                ok = nonfinite_guard(loss_v, grads)
                new_params = guard_select(ok, new_params, dict(params))
                kept = guard_select(ok, kept, dict(buffers))
                new_opt = guard_select(ok, new_opt, opt_state)
                return new_params, kept, new_opt, loss_v, ok
            return new_params, kept, new_opt, loss_v

        named_params = dict(self.network.named_parameters())
        param_sh = {n: self._sharding(self.param_specs[n])
                    for n in self.param_specs if n in named_params}
        buffer_sh = {n: self._sharding(P())
                     for n, b in self.network.named_buffers() if b is not None}
        opt_sh = {p: {s: self._sharding(sp) for s, sp in slots.items()}
                  for p, slots in self.opt_specs.items()}
        repl = self._sharding(P())

        # data args take their sharding from device_put in train_batch (the
        # arity of inputs/labels varies per model, so no fixed specs here)
        out_sh = (param_sh, buffer_sh, opt_sh, repl)
        if skip_nonfinite:
            out_sh = out_sh + (repl,)
        self._step_fn = jax.jit(
            step,
            donate_argnums=(0, 1, 2) if donate else (),
            in_shardings=(param_sh, buffer_sh, opt_sh, None, None, None,
                          None, None),
            out_shardings=out_sh,
        )
        return self._step_fn

    def place_batch(self, inputs, labels=None):
        """Stage one batch onto the mesh per the data spec — the same
        placement ``train_batch`` performs, exposed so AOT exporters can
        build the exact call signature."""
        data_sh = self._sharding(self._data_spec())
        inputs = [jax.device_put(
            v._value if isinstance(v, Tensor) else jnp.asarray(v), data_sh)
            for v in (inputs if isinstance(inputs, (list, tuple))
                      else [inputs])]
        labels = [jax.device_put(
            v._value if isinstance(v, Tensor) else jnp.asarray(v), data_sh)
            for v in (labels if isinstance(labels, (list, tuple))
                      else ([labels] if labels is not None else []))]
        return inputs, labels

    # ------------------------------------------------------------------
    def train_batch(self, inputs, labels=None, rng=None):
        if self._state is None:
            self.shard_state()
        if self._step_fn is None:
            self.build_train_step()
        params, buffers, opt_state = self._state
        inputs, labels = self.place_batch(inputs, labels)
        lr = self.optimizer.get_lr()
        if rng is None:
            # default: the global stream.  Elastic/replay callers pass an
            # explicit per-step key (fold_in of the run key and the global
            # step) so a resumed trajectory is bit-identical regardless of
            # how many keys were drawn before the restart.
            rng = next_rng_key()
        out = self._step_fn(
            params, buffers, opt_state, self._step_count + 1, lr, rng,
            inputs, labels)
        if self.skip_nonfinite:
            params, buffers, opt_state, loss, ok = out
            self.last_skipped = not bool(np.asarray(jax.device_get(ok)))
        else:
            params, buffers, opt_state, loss = out
            self.last_skipped = False
        self._state = (params, buffers, opt_state)
        self._step_count += 1
        self.optimizer._scheduler_step()
        return float(np.asarray(jax.device_get(loss)))

    def sync_state_to_layer(self):
        """Write the engine's (possibly sharded) state back onto the Layer's
        Tensors (global arrays — jax keeps them addressable)."""
        if self._state is None:
            return
        params, buffers, _ = self._state
        for n, p in self.network.named_parameters():
            if n in params:
                p._value = params[n]
        for n, b in self.network.named_buffers():
            if b is not None and n in buffers:
                b._value = buffers[n]

    def state_dict(self):
        self.sync_state_to_layer()
        return self.network.state_dict()

    # ------------------------------------------------------------------
    # elastic state carryover (parallel/elastic.py)
    # ------------------------------------------------------------------
    def host_state(self):
        """Gather the full (unsharded) training state to host numpy.

        Single-process meshes keep every shard addressable, so a plain
        ``device_get`` of the global array reassembles it; the result is
        topology-free and can be re-staged onto ANY mesh by
        :meth:`load_host_state` — the gather-and-repartition half of the
        elastic reshape (ZeRO os_g state is reconstructible from the
        survivors whenever the arrays are still replicated across some
        other axis, which a host-local gather subsumes)."""
        if self._state is None:
            self.shard_state()
        params, buffers, opt_state = self._state

        def _np(tree):
            return jax.tree_util.tree_map(
                lambda v: np.asarray(jax.device_get(v)), tree)

        return {
            "params": _np(params),
            "buffers": _np(buffers),
            "opt_state": _np(opt_state) if opt_state is not None else None,
            "step_count": self._step_count,
        }

    def load_host_state(self, host_state):
        """Re-stage a :meth:`host_state` snapshot onto THIS engine's mesh.

        The repartition half of the elastic reshape: specs are re-derived
        for the current topology and every leaf is ``device_put`` per its
        new spec.  Unlike :meth:`shard_state` this injects the carried
        optimizer slots instead of calling ``optimizer.init_state`` (fresh
        moments would silently reset Adam)."""
        if not self.param_specs:
            self._derive_specs()
        params = {n: jax.device_put(v, self._sharding(self.param_specs[n]))
                  for n, v in host_state["params"].items()}
        buffers = {n: jax.device_put(v, self._sharding(P()))
                   for n, v in host_state["buffers"].items()}
        for n, p in self.network.named_parameters():
            if n in params:
                p._value = params[n]
        for n, b in self.network.named_buffers():
            if b is not None and n in buffers:
                b._value = buffers[n]
        opt_state = None
        if host_state.get("opt_state") is not None:
            specs = self._opt_state_specs(host_state["opt_state"])
            opt_state = {
                pname: {sname: jax.device_put(
                    v, self._sharding(specs[pname][sname]))
                    for sname, v in slots.items()}
                for pname, slots in host_state["opt_state"].items()}
            self.opt_specs = specs
        self._state = (params, buffers, opt_state)
        self._step_count = int(host_state.get("step_count", 0))
        return self._state
