"""Tensor-parallel (Megatron-style) layers.

Analog of /root/reference/python/paddle/distributed/fleet/layers/mpu/
mp_layers.py — ``VocabParallelEmbedding`` :47, ``ColumnParallelLinear``
:334, ``RowParallelLinear`` :541, ``ParallelCrossEntropy`` :742 — and the
comm PyLayers in mp_ops.py (:91 _c_identity, :293 _mp_allreduce).

TPU-native design: each layer creates its FULL logical parameter but
annotates it with a PartitionSpec over the ``mp`` mesh axis
(``param_spec`` attribute).  Under jit with those shardings, XLA's SPMD
partitioner materializes only the local shard per device and inserts the
exact Megatron collectives (all-gather for column backward, psum for row
forward) on ICI — the hand-written _c_identity/_mp_allreduce PyLayers
dissolve into the compiler.  ``with_sharding_constraint`` pins activation
layouts at layer boundaries (gather_output / input_is_parallel semantics).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.dispatch import run_op
from ..core.tensor import Tensor
from ..nn import functional as F
from ..nn import initializer as I
from ..nn.layer.layers import Layer
from .topology import MP_AXIS, get_topology

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear",
           "RowParallelLinear", "ParallelCrossEntropy", "mark_sharding",
           "constrain"]


def mark_sharding(param, *axes):
    """Attach a PartitionSpec to a parameter; the parallel engine reads
    ``param_spec`` when staging state onto the mesh."""
    param.param_spec = P(*axes)
    return param


def constrain(x, *axes):
    """with_sharding_constraint on a Tensor/array inside a traced step
    (no-op outside jit or on meshless values)."""
    spec = P(*axes)
    v = x._value if isinstance(x, Tensor) else x
    try:
        topo = get_topology()
        out = jax.lax.with_sharding_constraint(
            v, jax.sharding.NamedSharding(topo.mesh, spec))
    except Exception:
        out = v
    return Tensor(out) if isinstance(x, Tensor) else out


class ColumnParallelLinear(Layer):
    """Weight [in, out] split on out (columns) over mp.  Forward: local
    matmul producing mp-sharded activations; ``gather_output=True`` adds an
    all-gather (mp_layers.py:334 semantics)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, gather_output: bool = True,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        topo = get_topology()
        self.world_size = topo.get_model_parallel_world_size()
        if out_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"out_features {out_features} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        mark_sharding(self.weight, None, MP_AXIS)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            mark_sharding(self.bias, MP_AXIS)
        else:
            self.bias = None

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            out = constrain(out, None)          # replicate (all-gather)
        else:
            out = constrain(out, *([None] * (out.ndim - 1) + [MP_AXIS]))
        return out


class RowParallelLinear(Layer):
    """Weight [in, out] split on in (rows) over mp.  With
    ``input_is_parallel=True`` the input arrives mp-sharded on its last dim;
    forward is a partial matmul + psum (mp_layers.py:541)."""

    def __init__(self, in_features: int, out_features: int, weight_attr=None,
                 has_bias: bool = True, input_is_parallel: bool = False,
                 fuse_matmul_bias: bool = False, mp_group=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        topo = get_topology()
        self.world_size = topo.get_model_parallel_world_size()
        if in_features % max(self.world_size, 1) != 0:
            raise ValueError(
                f"in_features {in_features} not divisible by mp degree "
                f"{self.world_size}")
        self.weight = self.create_parameter((in_features, out_features),
                                            attr=weight_attr)
        mark_sharding(self.weight, MP_AXIS, None)
        if has_bias:
            self.bias = self.create_parameter((out_features,), is_bias=True)
            mark_sharding(self.bias)            # replicated
        else:
            self.bias = None

    def forward(self, x):
        if self.input_is_parallel:
            x = constrain(x, *([None] * (x.ndim - 1) + [MP_AXIS]))
        out = F.linear(x, self.weight, None)
        out = constrain(out, None)               # psum happens here
        if self.bias is not None:
            from ..ops import api as _api
            out = _api.add(out, self.bias)
        return out


class VocabParallelEmbedding(Layer):
    """Embedding table row-sharded over mp (mp_layers.py:47); lookup of
    out-of-shard ids contributes zero and a psum combines shards — all
    emitted by XLA from a gather on a row-sharded table."""

    def __init__(self, num_embeddings: int, embedding_dim: int,
                 weight_attr=None, mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        topo = get_topology()
        self.world_size = topo.get_model_parallel_world_size()
        if num_embeddings % max(self.world_size, 1) != 0:
            raise ValueError("vocab size not divisible by mp degree")
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        mark_sharding(self.weight, MP_AXIS, None)

    def forward(self, x):
        out = F.embedding(x, self.weight)
        return constrain(out, None)


class ParallelCrossEntropy(Layer):
    """Cross entropy over mp-sharded logits (mp_layers.py:742 /
    c_softmax_with_cross_entropy).  With logits constrained mp-sharded on
    the class dim, XLA fuses the log-sum-exp psum; numerically identical to
    the single-device loss."""

    def __init__(self, mp_group=None, name=None, ignore_index: int = -100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, label):
        logits = constrain(logits,
                           *([None] * (logits.ndim - 1) + [MP_AXIS]))
        return F.cross_entropy(logits, label, reduction="none",
                               ignore_index=self.ignore_index)
