"""Collective communication API.

Analog of the reference's ``paddle.distributed`` collective surface
(/root/reference/python/paddle/distributed/communication/ — all_reduce.py:29
etc.) and the C++ ProcessGroup (phi/core/distributed/collective/
process_group.h:48).

TPU-native mapping (SURVEY §5 'Distributed communication backend'): a
"process group" is a mesh axis name; collectives are XLA ops
(``psum``/``all_gather``/``ppermute``/``all_to_all``) emitted under
``shard_map``.  Two call modes:

* **in-trace** (inside shard_map'd code): thin wrappers over jax.lax
  collectives — zero overhead, XLA schedules them async on ICI (the
  reference's ``sync_op/use_calc_stream`` machinery dissolves here);
* **eager** (on global Tensors): the call jit-wraps itself in a shard_map
  over the topology mesh, giving Paddle-API parity for scripts and tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .topology import get_topology

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group",
    "all_reduce", "all_gather", "reduce_scatter", "all_to_all", "broadcast",
    "reduce", "scatter", "barrier", "send", "recv",
    "in_all_reduce", "in_all_gather", "in_reduce_scatter", "in_all_to_all",
    "in_ppermute", "in_axis_index",
]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A named communication group = a mesh axis (or tuple of axes)."""

    def __init__(self, axis: Union[str, Sequence[str]] = "dp", topo=None):
        self.axis = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
        self._topo = topo

    @property
    def topo(self):
        return self._topo or get_topology()

    @property
    def nranks(self) -> int:
        n = 1
        for a in self.axis:
            n *= self.topo.axis_size(a)
        return n

    world_size = nranks

    def __repr__(self):
        return f"Group(axis={self.axis}, nranks={self.nranks})"


_named_groups = {}


def new_group(ranks=None, axis: Union[str, Sequence[str]] = "dp",
              backend=None) -> Group:
    g = Group(axis)
    _named_groups[g.axis] = g
    return g


def get_group(axis="dp") -> Group:
    key = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    if key not in _named_groups:
        _named_groups[key] = Group(axis)
    return _named_groups[key]


def _resolve_group(group) -> Group:
    if group is None:
        return get_group("dp")
    if isinstance(group, Group):
        return group
    return get_group(group)


# ---------------------------------------------------------------------------
# in-trace primitives (use inside shard_map'd functions)
# ---------------------------------------------------------------------------
def in_all_reduce(x, axis: Union[str, Sequence[str]], op: str = ReduceOp.SUM):
    if op == ReduceOp.SUM:
        return jax.lax.psum(x, axis)
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis)
    if op == ReduceOp.AVG:
        return jax.lax.pmean(x, axis)
    if op == ReduceOp.PROD:
        gathered = jax.lax.all_gather(x, axis if isinstance(axis, str)
                                      else axis[0], axis=0)
        return jnp.prod(gathered, axis=0)
    raise ValueError(f"unknown reduce op {op}")


def in_all_gather(x, axis: str, concat_axis: int = 0, tiled: bool = True):
    return jax.lax.all_gather(x, axis, axis=concat_axis, tiled=tiled)


def in_reduce_scatter(x, axis: str, scatter_axis: int = 0):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                                tiled=True)


def in_all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    return jax.lax.all_to_all(x, axis, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def in_ppermute(x, axis: str, perm):
    return jax.lax.ppermute(x, axis, perm)


def in_axis_index(axis: str):
    return jax.lax.axis_index(axis)


# ---------------------------------------------------------------------------
# eager wrappers over global Tensors
# ---------------------------------------------------------------------------
def _eager_collective(tensor: Tensor, group, fn, in_spec=None,
                      out_spec=None, op_name: str = "collective"):
    g = _resolve_group(group)
    topo = g.topo
    mesh = topo.mesh
    if g.nranks == 1:
        return tensor
    in_spec = in_spec if in_spec is not None else P(g.axis)
    out_spec = out_spec if out_spec is not None else in_spec
    mapped = jax.jit(jax.shard_map(fn, mesh=mesh, in_specs=in_spec,
                                   out_specs=out_spec, check_vma=False))
    v = tensor._value if isinstance(tensor, Tensor) else tensor
    out = _monitored(op_name, g.axis, lambda: mapped(v))
    return Tensor(out) if isinstance(tensor, Tensor) else out


def all_reduce(tensor: Tensor, op: str = ReduceOp.SUM, group=None,
               sync_op: bool = True):
    """Eager all-reduce with single-controller semantics: the global Tensor
    stands for the value every rank holds, so SUM over an N-way group
    returns ``x * N`` — exactly what the reference produces when all ranks
    hold identical tensors.  (In-trace code uses in_all_reduce / psum on
    genuinely per-shard values.)"""
    g = _resolve_group(group)
    if g.nranks == 1:
        return tensor
    out = _eager_collective(
        tensor, g, lambda x: in_all_reduce(x, list(g.axis), op),
        in_spec=P(), out_spec=P(), op_name=f"all_reduce[{op}]")
    if isinstance(tensor, Tensor):
        tensor._value = out._value if isinstance(out, Tensor) else out
        return tensor
    return out


def all_gather(tensor_list, tensor: Tensor, group=None, sync_op: bool = True):
    """Paddle-compatible: appends nranks shards to tensor_list.  The input is
    the local shard (replicated globally in single-controller mode), so the
    gather is a tile."""
    g = _resolve_group(group)
    for _ in range(g.nranks):
        tensor_list.append(tensor.clone() if isinstance(tensor, Tensor)
                           else Tensor(tensor))
    return tensor_list


def reduce_scatter(tensor: Tensor, tensor_or_tensor_list, op=ReduceOp.SUM,
                   group=None, sync_op: bool = True):
    g = _resolve_group(group)
    src = tensor_or_tensor_list
    if isinstance(src, (list, tuple)):
        from ..ops import api as _api
        cat = _api.concat(list(src), axis=0)
    else:
        cat = src
    n = g.nranks
    if n == 1:
        tensor._value = cat._value
        return tensor
    # single-controller semantics: every rank holds the same full tensor;
    # reduce = sum over identical copies (scale by n); scatter = this
    # process's chunk by its global rank
    from .env import get_rank
    r = get_rank() % n
    chunk = cat.shape[0] // n
    tensor._value = (cat._value[r * chunk:(r + 1) * chunk] * n)
    return tensor


def all_to_all(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    g = _resolve_group(group)
    out_tensor_list.extend(t.clone() for t in in_tensor_list)
    return out_tensor_list


def broadcast(tensor: Tensor, src: int = 0, group=None, sync_op: bool = True):
    # single-controller: all ranks see the same value already
    return tensor


def reduce(tensor: Tensor, dst: int = 0, op=ReduceOp.SUM, group=None,
           sync_op: bool = True):
    return all_reduce(tensor, op, group, sync_op)


def scatter(tensor: Tensor, tensor_list=None, src: int = 0, group=None,
            sync_op: bool = True):
    if tensor_list:
        tensor._value = tensor_list[0]._value
    return tensor


def barrier(group=None):
    """Synchronize: enqueue a trivial computation on every device and wait.
    Device execution is FIFO per device, so this drains all previously
    dispatched work (the reference's stream-sync barrier semantics)."""
    jax.effects_barrier()
    import jax.numpy as _jnp
    for d in jax.devices():
        jax.device_get(jax.device_put(_jnp.zeros(()), d) + 1)
    return None


def send(tensor: Tensor, dst: int = 0, group=None, sync_op: bool = True):
    raise NotImplementedError(
        "point-to-point send/recv between ranks is expressed as "
        "lax.ppermute inside shard_map on TPU (see parallel.pipeline); "
        "host-level send is not part of the single-controller model")


def recv(tensor: Tensor, src: int = 0, group=None, sync_op: bool = True):
    raise NotImplementedError(
        "see send(): use parallel.pipeline p2p or shard_map ppermute")


# ---------------------------------------------------------------------------
# per-collective monitoring (reference distributed/fleet comm-op timeout
# tracking / FLAGS_distributed_timeout; most hang detection dissolves into
# XLA, so the surface here is eager collectives + completion timing)
# ---------------------------------------------------------------------------
class CollectiveMonitor:
    """Times every eager collective and warns past a soft deadline.

    ``with CollectiveMonitor(warn_after=30.0) as mon:`` — each eager
    collective's wall time is recorded in ``mon.events`` as
    (name, group_axis, seconds); calls slower than ``warn_after`` log a
    warning with the collective's identity — the reference's per-op comm
    watchdog (comm monitoring in ProcessGroupNCCL) adapted to the
    compiled-collective world: in-jit collectives are covered by the step
    watchdog (distributed/elastic.py), eager ones by this monitor."""

    _active = None

    def __init__(self, warn_after: float = 30.0):
        self.warn_after = warn_after
        self.events = []

    def __enter__(self):
        CollectiveMonitor._active = self
        return self

    def __exit__(self, *exc):
        CollectiveMonitor._active = None
        return False

    def record(self, name, axis, seconds):
        self.events.append((name, axis, seconds))
        if seconds > self.warn_after:
            import warnings
            warnings.warn(
                f"collective {name!r} over axis {axis!r} took "
                f"{seconds:.1f}s (> {self.warn_after:.1f}s) — possible "
                "straggler or hang")

    def summary(self):
        """Total time and call count per collective name."""
        agg = {}
        for name, axis, sec in self.events:
            t, n = agg.get(name, (0.0, 0))
            agg[name] = (t + sec, n + 1)
        return agg


def _monitored(name, axis, fn):
    mon = CollectiveMonitor._active
    if mon is None:
        return fn()
    import time as _time
    t0 = _time.perf_counter()
    out = fn()
    jax.tree.map(lambda t: t.block_until_ready()
                 if hasattr(t, "block_until_ready") else t,
                 getattr(out, "_value", out))
    mon.record(name, axis, _time.perf_counter() - t0)
    return out


def gather(tensor, gather_list=None, dst: int = 0, group=None,
           sync_op: bool = True):
    """Gather tensors to dst (reference communication/gather.py).
    Single-controller: all shards are addressable, so gather = the
    all_gather list (dst distinction has no process boundary here)."""
    if gather_list is None:
        gather_list = []
    all_gather(gather_list, tensor, group=group, sync_op=sync_op)
    return gather_list
