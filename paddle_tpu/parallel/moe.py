"""Expert-parallel MoE FFN for the compiled hybrid train step.

The eager :class:`~paddle_tpu.incubate.distributed.models.moe.MoELayer`
covers the reference's imperative MoE API (moe_layer.py:263) with dense
[T, E, C] dispatch/combine einsums.  This module is the MANUAL-SPMD
counterpart used inside the all-axes shard_map of
:func:`~paddle_tpu.parallel.manual.build_hybrid_train_step`:

* Routing is scatter-based — positions come from a [T*k, E] cumsum and
  tokens are scattered straight into the [E, C, h] expert buffers — so
  memory is O(T*E + E*C*h) instead of the O(T*E*C) one-hot dispatch mask
  (which is quadratic in tokens at fixed expert count).
* Expert parallelism follows the reference's distributed design
  (global_scatter/global_gather over the expert-parallel group,
  moe_layer.py:55): expert weights are SHARDED over the ``dp`` mesh axis
  (each data rank owns E/ep experts) and tokens move with ONE
  ``lax.all_to_all`` each way.  The all_to_all rides ICI inside the
  compiled step — no host round trip, unlike the reference's NCCL
  global_scatter.
* Tensor parallelism inside experts is Megatron-style (w1 column-split,
  w2 row-split over ``mp``) with the same mp_copy / fwd_psum collectives
  as the dense block.
* The GShard load-balance loss enters training through
  :func:`inject_aux_grad` — a custom-VJP identity that contributes
  ``coef * d(aux)/dparams`` to the backward pass without threading an
  extra scalar through the pipeline schedules (the compiled-step analog
  of the reference gate's ``get_loss()`` being added to the model loss).
"""

from __future__ import annotations

import functools
import math
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..incubate.distributed.models.moe.gating import (compute_capacity,
                                                      gshard_aux_loss)
from .manual import fwd_psum, mp_copy

__all__ = ["inject_aux_grad", "topk_scatter_routing", "moe_ffn_ep",
           "moe_swiglu_ffn_ep", "moe_dispatch_combine", "compute_capacity",
           "schedule_aux_coef", "expert_choice_routing",
           "moe_expert_choice_ffn", "moe_swiglu_ffn_grouped",
           "moe_gelu_ffn_grouped"]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def inject_aux_grad(x, aux, coef: float):
    """Identity on ``x`` whose backward adds ``coef`` as the cotangent of
    ``aux`` — exactly as if ``coef * aux`` had been added to the final
    scalar loss, without changing any forward value or signature.

    This lets per-layer auxiliary losses (MoE load balance) reach the
    optimizer through pipeline schedules whose carries are activation
    tensors only.  The forward loss value deliberately EXCLUDES the aux
    term (monitor it separately if needed); gradients include it exactly.
    """
    del aux, coef
    return x


def _inject_fwd(x, aux, coef):
    del aux
    return x, None


def _inject_bwd(coef, _, g):
    return g, jnp.asarray(coef, jnp.float32)


inject_aux_grad.defvjp(_inject_fwd, _inject_bwd)


def schedule_aux_coef(coef: float, num_layers: int, schedule: str,
                      pp_degree: int, num_microbatches: int,
                      data_replicas: int, mb_tokens: int
                      ) -> Optional[float]:
    """Per-site injection coefficient so every schedule path realizes the
    same effective term ``loss += coef * mean_over_sites(aux)`` (sites =
    layers x microbatches x data ranks).

    Single source of the contract with build_hybrid_train_step's grad
    normalization (shared by the gpt/llama builders — do not fork):
    the manual-vjp pipeline schedules (1f1b/zbh1/interleave) divide the
    summed vjp by ``norm = b_l*s_l*R`` AFTER the fact, which also scales
    the injected constant, while the value_and_grad paths (pp==1, gpipe)
    divide the loss inside loss_fn, which the injected constant bypasses.

    Args:
      data_replicas: dp * sharding * sep (each rank's aux is a distinct
        site whose grads later sum across these axes).
      mb_tokens: per-microbatch local tokens b_mb * s_l (only used by the
        manual-vjp branch; pass 0 otherwise).
    """
    if not coef:
        return None
    if pp_degree > 1 and schedule in ("1f1b", "zbh1", "interleave"):
        return coef * mb_tokens / num_layers
    M = num_microbatches if pp_degree > 1 else 1
    return coef / (num_layers * M * data_replicas)


def topk_scatter_routing(logits: jax.Array, top_k: int, capacity: int,
                         normalize: bool = True
                         ) -> Tuple[jax.Array, jax.Array, jax.Array,
                                    jax.Array]:
    """Top-k router emitting scatter indices instead of dispatch masks.

    Same semantics as :func:`...moe.gating.topk_capacity_gating` (GShard
    priority: every token's k-th choice is ranked after all (k-1)-th
    choices; overflow beyond ``capacity`` is dropped), but O(T*E) memory.

    Args:
      logits: [T, E] router logits (softmaxed in fp32).
    Returns:
      idx:  [T, k] int32 — expert id per assignment.
      pos:  [T, k] int32 — slot in the expert buffer; == ``capacity``
            where the assignment was dropped (out-of-range on purpose so
            mode="drop"/"fill" scatters/gathers ignore it).
      w:    [T, k] fp32 — combine weights (0 where dropped).
      aux:  scalar GShard load-balance loss.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    aux = gshard_aux_loss(probs, jnp.argmax(probs, axis=-1))
    w, idx = lax.top_k(probs, top_k)                    # [T, k]
    idx = idx.astype(jnp.int32)
    # slot = number of earlier assignments to the same expert, counting
    # k-major (all 1st choices in token order, then all 2nd choices)
    oh = jax.nn.one_hot(idx, E, dtype=jnp.int32)        # [T, k, E]
    ohf = oh.transpose(1, 0, 2).reshape(top_k * T, E)
    prior = jnp.cumsum(ohf, axis=0) - ohf
    pos = jnp.sum(prior * ohf, axis=-1).reshape(top_k, T).T  # [T, k]
    keep = pos < capacity
    w = w * keep
    if normalize and top_k > 1:
        w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)
    pos = jnp.where(keep, pos, capacity).astype(jnp.int32)
    return idx, pos, w, aux


def expert_choice_routing(logits: jax.Array, capacity: int
                          ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Expert-choice routing (Zhou et al. 2022): EXPERTS pick their top-C
    tokens instead of tokens picking experts — perfect load balance by
    construction (every expert processes exactly C tokens), no aux loss
    and no dropped-capacity heuristics.  Complements the GShard/Switch
    token-choice gates the reference ships (gshard_gate.py/switch_gate.py).

    Args:
      logits: [T, E] router logits (softmax over experts in fp32).
      capacity: tokens per expert C (typically T * cf * k / E).
    Returns:
      sel: [E, C] int32 — token index chosen per expert slot.
      w:   [E, C] fp32 — combine weight (the token's gate prob for this
           expert).
      probs: [T, E] fp32 — full router probabilities (for monitoring).
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    w, sel = lax.top_k(probs.T, min(capacity, T))        # [E, C]
    return sel.astype(jnp.int32), w, probs


def moe_expert_choice_ffn(x: jax.Array, gate_w: jax.Array,
                          expert_apply: Callable, n_experts_local: int, *,
                          capacity_factor: float = 2.0,
                          ep_axis: Optional[str] = None) -> jax.Array:
    """MoE FFN under expert-choice routing, expert-parallel over
    ``ep_axis``.

    Dispatch is a plain gather (each expert's C chosen tokens), combine a
    weighted scatter-add back to token positions; both are linear, so AD
    handles the transposes.  With ``ep_axis`` the gathered buffers move
    with the same pair of all_to_alls as the token-choice path.

    ``capacity_factor`` here means AVERAGE EXPERTS PER TOKEN (the
    expert-choice paper's c): C = T * c / E.
    """
    shape = x.shape
    h = shape[-1]
    tokens = x.reshape(-1, h)
    T = tokens.shape[0]
    ep = 1 if ep_axis is None else lax.axis_size(ep_axis)
    E = n_experts_local * ep
    if gate_w.shape[1] != E:
        raise ValueError(f"gate_w experts {gate_w.shape[1]} != "
                         f"{n_experts_local}x{ep} sharded expert bank")
    C = max(1, min(T, int(T * capacity_factor / E)))

    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    sel, w, _ = expert_choice_routing(logits, C)          # [E, C]

    buf = tokens[sel]                                     # [E, C, h] gather
    if ep_axis is not None:
        buf = lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=1,
                             tiled=True)
    out = expert_apply(buf)
    if ep_axis is not None:
        out = lax.all_to_all(out, ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)
    # combine: weighted scatter-add back to token slots
    res = jnp.zeros((T, h), jnp.float32)
    res = res.at[sel.reshape(-1)].add(
        (w[..., None].astype(jnp.float32)
         * out.astype(jnp.float32)).reshape(E * C, h))
    return res.astype(x.dtype).reshape(shape)


def moe_swiglu_ffn_grouped(x: jax.Array, router_w: jax.Array,
                           wg: jax.Array, wu: jax.Array, wd: jax.Array, *,
                           top_k: int = 2, normalize: bool = True,
                           with_aux: bool = False):
    """Exact SwiGLU MoE via sorted grouped GEMM (`lax.ragged_dot`) — the
    SERVING formulation: assignments are sorted by expert and each expert
    multiplies only its own contiguous row block, so there is no capacity
    padding (top_k*T slot cost, vs E*C for the dispatch-buffer path) and
    no token is ever dropped.  On TPU ragged_dot lowers to the Mosaic
    grouped-matmul; this is the MegaBlocks-style dropless MoE.

    Single-device only (no ep/mp axes).  ragged_dot differentiates, so
    this serves AND trains (the ``dropless`` mode of the ffn wrappers);
    EP/TP layouts keep the fixed-capacity dispatch buffers whose static
    shapes the all_to_alls need.
    """
    shape = x.shape
    h = shape[-1]
    tokens = x.reshape(-1, h)
    T = tokens.shape[0]
    E = wg.shape[0]
    logits = tokens.astype(jnp.float32) @ router_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    w, idx = lax.top_k(probs, top_k)                     # [T, k]
    if normalize and top_k > 1:
        w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)

    e_flat = idx.reshape(-1)                             # [T*k]
    order = jnp.argsort(e_flat)
    tok_rep = jnp.broadcast_to(tokens[:, None, :],
                               (T, top_k, h)).reshape(T * top_k, h)
    sorted_tok = tok_rep[order]
    gs = jnp.bincount(e_flat, length=E).astype(jnp.int32)
    gate = lax.ragged_dot(sorted_tok, wg, gs)
    up = lax.ragged_dot(sorted_tok, wu, gs)
    out_sorted = lax.ragged_dot(jax.nn.silu(gate) * up, wd, gs)
    inv = jnp.argsort(order)
    out = out_sorted[inv].reshape(T, top_k, h)
    res = jnp.sum(w[..., None] * out.astype(jnp.float32), axis=1)
    res = res.astype(x.dtype).reshape(shape)
    if with_aux:
        return res, gshard_aux_loss(probs, jnp.argmax(probs, axis=-1))
    return res


def moe_gelu_ffn_grouped(x: jax.Array, gate_w: jax.Array, w1: jax.Array,
                         b1: jax.Array, w2: jax.Array, b2: jax.Array, *,
                         top_k: int = 2, normalize: bool = True,
                         activation: Callable = functools.partial(
                             jax.nn.gelu, approximate=True),
                         with_aux: bool = False):
    """GELU-MLP counterpart of :func:`moe_swiglu_ffn_grouped` (the GPT
    expert bank with per-expert biases): per-assignment biases come from
    a gather on the sorted expert ids, everything else is the same
    sorted ragged_dot pipeline.  Serving path — single device, no
    ep/mp axes, dropless by construction."""
    shape = x.shape
    h = shape[-1]
    tokens = x.reshape(-1, h)
    T = tokens.shape[0]
    E = w1.shape[0]
    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    w, idx = lax.top_k(probs, top_k)
    if normalize and top_k > 1:
        w = w / jnp.maximum(jnp.sum(w, axis=-1, keepdims=True), 1e-9)

    e_flat = idx.reshape(-1)
    order = jnp.argsort(e_flat)
    e_sorted = e_flat[order]
    tok_rep = jnp.broadcast_to(tokens[:, None, :],
                               (T, top_k, h)).reshape(T * top_k, h)
    sorted_tok = tok_rep[order]
    gs = jnp.bincount(e_flat, length=E).astype(jnp.int32)
    hdn = lax.ragged_dot(sorted_tok, w1, gs) + b1[e_sorted]
    out_sorted = lax.ragged_dot(activation(hdn), w2, gs) + b2[e_sorted]
    inv = jnp.argsort(order)
    out = out_sorted[inv].reshape(T, top_k, h)
    res = jnp.sum(w[..., None] * out.astype(jnp.float32), axis=1)
    res = res.astype(x.dtype).reshape(shape)
    if with_aux:
        return res, gshard_aux_loss(probs, jnp.argmax(probs, axis=-1))
    return res


def _run_dropless(grouped_fn, ep_axis, mp_axis, aux_coef):
    """Shared dropless-branch contract for the ffn wrappers: require
    degree-1 ep/mp (capacity buffers carry the static shapes collectives
    need), then run the grouped fn and inject the aux loss it already
    computed.  (The expert_choice x dropless conflict is rejected in the
    wrappers' expert_choice branches, which return before this runs.)"""
    ep_d = 1 if ep_axis is None else lax.axis_size(ep_axis)
    mp_d = 1 if mp_axis is None else lax.axis_size(mp_axis)
    if ep_d > 1 or mp_d > 1:
        raise ValueError("dropless=True requires local expert banks "
                         "(ep/mp degree 1) — capacity buffers carry "
                         "the static shapes collectives need")
    if aux_coef:
        out, aux = grouped_fn(True)
        return inject_aux_grad(out, aux, aux_coef)
    return grouped_fn(False)


def moe_dispatch_combine(x: jax.Array, gate_w: jax.Array,
                         expert_apply: Callable, n_experts_local: int, *,
                         top_k: int = 2, capacity_factor: float = 1.25,
                         ep_axis: Optional[str] = None,
                         aux_coef: float = 0.0,
                         normalize: bool = True,
                         capacity: Optional[int] = None) -> jax.Array:
    """Shared routing + EP transport around any expert function.

    Routes device-local tokens into fixed-capacity per-expert buffers,
    moves them to the owning expert rank with one ``lax.all_to_all``
    (global_scatter parity, reference moe_utils.py), applies
    ``expert_apply(buf [E_local, slots, h]) -> [E_local, slots, h]``
    (which embeds its own mp collectives), brings the slots home with the
    inverse all_to_all, and combines with the routing weights.

    Args:
      x: [..., h] device-local tokens (the FULL gathered sequence when
         the caller runs Megatron sequence parallelism).
      gate_w: [h, E] router weights (math in fp32).
      n_experts_local: experts held by THIS rank (E/ep).
      ep_axis: mesh axis the expert dim is sharded over (the hybrid step
         passes ``dp``); None = experts all local.
      aux_coef: weight on the GShard balance loss, injected via
         :func:`inject_aux_grad` (0 = off).
      capacity: explicit per-expert slot count overriding the GShard
         formula — inference paths pass the token count so NO token is
         ever dropped (capacity truncation is a training regularizer;
         at decode time a drop silently corrupts the output).
    """
    shape = x.shape
    h = shape[-1]
    tokens = x.reshape(-1, h)
    T = tokens.shape[0]
    ep = 1 if ep_axis is None else lax.axis_size(ep_axis)
    E = n_experts_local * ep
    if gate_w.shape[1] != E:
        raise ValueError(f"gate_w experts {gate_w.shape[1]} != "
                         f"{n_experts_local}x{ep} sharded expert bank")
    C = capacity if capacity is not None \
        else compute_capacity(T, E, top_k, capacity_factor)

    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    idx, pos, w, aux = topk_scatter_routing(logits, top_k, C, normalize)

    # dispatch: scatter each kept assignment's token into its expert slot
    tok_rep = jnp.broadcast_to(tokens[:, None, :],
                               (T, top_k, h)).reshape(T * top_k, h)
    buf = jnp.zeros((E, C, h), x.dtype)
    buf = buf.at[idx.reshape(-1), pos.reshape(-1)].set(tok_rep, mode="drop")

    if ep_axis is not None:
        # [E, C, h] -> [E/ep, ep*C, h]: every rank's slots for MY experts
        buf = lax.all_to_all(buf, ep_axis, split_axis=0, concat_axis=1,
                             tiled=True)
    out = expert_apply(buf)
    if ep_axis is not None:
        # inverse all_to_all: my slots come home from every expert rank
        out = lax.all_to_all(out, ep_axis, split_axis=1, concat_axis=0,
                             tiled=True)

    got = out.at[idx, pos].get(mode="fill", fill_value=0)   # [T, k, h]
    res = jnp.sum(w[..., None].astype(jnp.float32)
                  * got.astype(jnp.float32), axis=1)
    res = res.astype(x.dtype).reshape(shape)
    if aux_coef:
        res = inject_aux_grad(res, aux, aux_coef)
    return res


def moe_ffn_ep(x: jax.Array, gate_w: jax.Array, w1: jax.Array,
               b1: jax.Array, w2: jax.Array, b2: jax.Array, *,
               top_k: int = 2, capacity_factor: float = 1.25,
               ep_axis: Optional[str] = None,
               mp_axis: Optional[str] = None,
               sequence_parallel: bool = False,
               aux_coef: float = 0.0,
               activation: Callable = functools.partial(jax.nn.gelu,
                                                        approximate=True),
               normalize: bool = True,
               router: str = "topk",
               dropless: bool = False) -> jax.Array:
    """GELU-MLP mixture of experts (the GPT block's FFN), expert-parallel
    over ``ep_axis``.

    w1/b1/w2/b2: LOCAL expert shards — [E/ep, h, f/mp], [E/ep, f/mp],
    [E/ep, f/mp, h], [E/ep, h].  With no mesh axes these are the full
    [E, ...] banks and the function is a plain jit MoE FFN.

    mp_axis: Megatron TP inside each expert — w1 column-split (mp_copy
    on the input: identity fwd / psum bwd), w2 row-split (fwd_psum on
    the output).  Under ``sequence_parallel`` the caller gathered the
    sequence over mp, so the input reduction lives in that all_gather's
    transpose and no mp_copy is inserted; the caller scatters after
    (outputs here are replicated over mp post-psum, biases included —
    hence full, not mp-partial, bias grads)."""
    def expert_apply(buf):
        y = buf
        if mp_axis is not None and not sequence_parallel:
            y = mp_copy(y, mp_axis)       # identity fwd / psum bwd (col in)
        hdn = jnp.einsum("gch,ghf->gcf", y, w1) + b1[:, None, :]
        hdn = activation(hdn)
        out = jnp.einsum("gcf,gfh->gch", hdn, w2)
        if mp_axis is not None:
            out = fwd_psum(out, mp_axis)  # row out: sum the f/mp partials
        return out + b2[:, None, :]

    if router == "expert_choice":
        if dropless:
            raise ValueError(
                "moe_dropless applies to token-choice routing only; "
                "expert_choice is capacity-shaped by construction")
        return moe_expert_choice_ffn(
            x, gate_w, expert_apply, w1.shape[0],
            capacity_factor=capacity_factor, ep_axis=ep_axis)
    if dropless:
        return _run_dropless(
            lambda wa: moe_gelu_ffn_grouped(
                x, gate_w, w1, b1, w2, b2, top_k=top_k,
                normalize=normalize, activation=activation, with_aux=wa),
            ep_axis, mp_axis, aux_coef)
    return moe_dispatch_combine(
        x, gate_w, expert_apply, w1.shape[0], top_k=top_k,
        capacity_factor=capacity_factor, ep_axis=ep_axis,
        aux_coef=aux_coef, normalize=normalize)


def moe_swiglu_ffn_ep(x: jax.Array, router_w: jax.Array, wg: jax.Array,
                      wu: jax.Array, wd: jax.Array, *,
                      top_k: int = 2, capacity_factor: float = 1.25,
                      ep_axis: Optional[str] = None,
                      mp_axis: Optional[str] = None,
                      sequence_parallel: bool = False,
                      aux_coef: float = 0.0,
                      normalize: bool = True,
                      capacity: Optional[int] = None,
                      router: str = "topk",
                      dropless: bool = False) -> jax.Array:
    """SwiGLU mixture of experts (Mixtral-style Llama FFN): per-expert
    gate/up column-split + down row-split over ``mp_axis``, biasless.

    wg/wu: [E/ep, h, f/mp]; wd: [E/ep, f/mp, h].  Routing normalization
    follows the GShard convention (renormalize kept top-k weights) —
    numerically equivalent to Mixtral's softmax-over-top-k when no token
    overflows capacity."""
    def expert_apply(buf):
        y = buf
        if mp_axis is not None and not sequence_parallel:
            y = mp_copy(y, mp_axis)
        g = jnp.einsum("gch,ghf->gcf", y, wg)
        u = jnp.einsum("gch,ghf->gcf", y, wu)
        out = jnp.einsum("gcf,gfh->gch", jax.nn.silu(g) * u, wd)
        if mp_axis is not None:
            out = fwd_psum(out, mp_axis)
        return out

    if router == "expert_choice":
        if dropless:
            raise ValueError(
                "moe_dropless applies to token-choice routing only; "
                "expert_choice is capacity-shaped by construction")
        if capacity is not None:
            raise ValueError(
                "capacity override is a token-choice (no-drop) contract; "
                "expert_choice routing sizes its own buffers and can "
                "leave tokens unrouted — use router='topk' for serving")
        return moe_expert_choice_ffn(
            x, router_w, expert_apply, wg.shape[0],
            capacity_factor=capacity_factor, ep_axis=ep_axis)
    if dropless:
        # MegaBlocks-style dropless training (ragged_dot differentiates)
        return _run_dropless(
            lambda wa: moe_swiglu_ffn_grouped(
                x, router_w, wg, wu, wd, top_k=top_k,
                normalize=normalize, with_aux=wa),
            ep_axis, mp_axis, aux_coef)
    return moe_dispatch_combine(
        x, router_w, expert_apply, wg.shape[0], top_k=top_k,
        capacity_factor=capacity_factor, ep_axis=ep_axis,
        aux_coef=aux_coef, normalize=normalize, capacity=capacity)
