"""Distributed environment bootstrap.

Analog of the reference's ``init_parallel_env``
(/root/reference/python/paddle/distributed/parallel.py:978) + TCPStore
rendezvous (phi/core/distributed/store/tcp_store.h:121).  On TPU the
rendezvous/NCCL-id machinery collapses into ``jax.distributed.initialize``
(coordination service) — env vars follow the launcher contract
(PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / MASTER_ADDR / MASTER_PORT, with
PT_* equivalents)."""

from __future__ import annotations

import os
from typing import Optional

import jax

__all__ = ["init_parallel_env", "get_rank", "get_world_size",
           "is_initialized", "ParallelEnv"]

_initialized = False


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def get_rank() -> int:
    if _initialized:
        return jax.process_index()
    return int(_env("PADDLE_TRAINER_ID", "PT_RANK", "RANK", default="0"))


def get_world_size() -> int:
    if _initialized:
        return jax.process_count()
    return int(_env("PADDLE_TRAINERS_NUM", "PT_WORLD_SIZE", "WORLD_SIZE",
                    default="1"))


def is_initialized() -> bool:
    return _initialized


def init_parallel_env() -> "ParallelEnv":
    """Initialize multi-host coordination.  Single-process (world_size==1)
    is a no-op: all jax.devices() are already visible."""
    global _initialized
    if _initialized:
        return ParallelEnv()
    world = get_world_size()
    if world > 1 and not _initialized:
        addr = _env("MASTER_ADDR", "PADDLE_MASTER", default="127.0.0.1")
        port = _env("MASTER_PORT", default="8476")
        jax.distributed.initialize(
            coordinator_address=f"{addr}:{port}",
            num_processes=world,
            process_id=get_rank())
    _initialized = True
    return ParallelEnv()


class ParallelEnv:
    @property
    def rank(self) -> int:
        return get_rank()

    @property
    def world_size(self) -> int:
        return get_world_size()

    @property
    def local_rank(self) -> int:
        return int(_env("PADDLE_RANK_IN_NODE", "LOCAL_RANK", default="0"))

    @property
    def nranks(self) -> int:
        return self.world_size

    @property
    def dev_id(self) -> int:
        return self.local_rank
