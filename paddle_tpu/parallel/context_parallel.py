"""Context parallelism (CP): ring flash attention + Ulysses all-to-all.

The reference snapshot has NO ring/Ulysses implementation (SURVEY §2.5 "CP /
ring attention / Ulysses — NOT present"); its long-sequence story is
Megatron-SP + SEP + FlashAttention.  This module supplies the missing
capability TPU-first: the sequence dimension is a mesh axis (``sep``), KV
blocks rotate over the ICI ring via ``jax.lax.ppermute`` (ring attention), or
heads<->sequence swap via ``jax.lax.all_to_all`` (Ulysses / DeepSpeed-style).

Both entry points are designed to be called INSIDE ``jax.shard_map`` with the
sequence dimension sharded over ``axis_name``:

    q, k, v : [batch, seq_local, heads, head_dim]   (paddle flash layout)

``ring_flash_attention`` is a ``jax.custom_vjp``: the forward carries the
online-softmax state (m, l, acc) across ring steps; the backward replays the
ring, rotating (k, v, dk, dv) together so each chunk's gradient lands back on
its owner after exactly ``axis_size`` hops.  Causal steps whose KV chunk lies
entirely in the masked future are skipped via ``lax.cond``.  Math follows the
blockwise-parallel scheme of the public RingAttention formulation
(PAPERS.md), computed in fp32.

``ulysses_attention`` is automatically differentiable (all_to_all has a
transpose rule); it requires num_heads % axis_size == 0.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas.common import NEG_INF

__all__ = ["ring_flash_attention", "ulysses_attention",
           "zigzag_ring_flash_attention", "zigzag_permutation",
           "zigzag_positions"]


def _ring_perm(n: int):
    # send local KV chunk to the next rank; after s hops rank i holds
    # chunk (i - s) mod n
    return [(j, (j + 1) % n) for j in range(n)]


def _ring_fwd_loop(q, k, v, scale, causal, axis_name, axis_size):
    """q/k/v: [B, S, H, D] (local shard; GQA ok).  Returns
    (out [B,S,H,D] fp32, lse [B,H,S,1] fp32).

    Inner compute is the Pallas flash kernel per KV chunk
    (ops/pallas/flash_attention.py — VERDICT r1: ring's inner math was
    plain jnp and the flagship TPU path never ran the flagship kernel);
    chunk results merge by the associative log-sum-exp rule."""
    from ..ops.pallas.flash_attention import flash_attention_with_lse
    B, S, H, D = q.shape
    my_idx = lax.axis_index(axis_name)
    perm = _ring_perm(axis_size)

    def merge(o_acc, lse_acc, o_c, lse_c):
        m = jnp.maximum(lse_acc, lse_c)
        w1 = jnp.exp(lse_acc - m)
        w2 = jnp.exp(lse_c - m)
        o = (o_acc * jnp.swapaxes(w1, 1, 2)
             + o_c.astype(jnp.float32) * jnp.swapaxes(w2, 1, 2)) \
            / jnp.swapaxes(w1 + w2, 1, 2)
        return o, m + jnp.log(w1 + w2)

    def chunk(kc, vc, diag_causal):
        return flash_attention_with_lse(q, kc, vc, scale, diag_causal)

    def step(s_i, carry):
        o_acc, lse_acc, kc, vc = carry
        if causal:
            kv_idx = (my_idx - s_i) % axis_size

            def active():
                o_c, lse_c = lax.cond(kv_idx == my_idx,
                                      lambda: chunk(kc, vc, True),
                                      lambda: chunk(kc, vc, False))
                return merge(o_acc, lse_acc, o_c, lse_c)

            # chunks strictly in the masked future contribute nothing
            o_acc2, lse_acc2 = lax.cond(kv_idx <= my_idx, active,
                                        lambda: (o_acc, lse_acc))
        else:
            o_c, lse_c = chunk(kc, vc, False)
            o_acc2, lse_acc2 = merge(o_acc, lse_acc, o_c, lse_c)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o_acc2, lse_acc2, kc, vc

    init = (jnp.zeros((B, S, H, D), jnp.float32),
            jnp.full((B, H, S, 1), NEG_INF, jnp.float32), k, v)
    out, lse, _, _ = lax.fori_loop(0, axis_size, step, init)
    return out, lse


def _ring_bwd_loop(q, k, v, out, lse, do, scale, causal, axis_name,
                   axis_size):
    """Backward ring (all [B, S, H, D]): dq stays local; (k, v, dk, dv)
    rotate together so each KV chunk accumulates its gradient from every
    rank and arrives home after axis_size hops.  Per-chunk gradients come
    from the Pallas bwd kernels with the GLOBAL lse, so the chunk
    contributions sum to the exact gradient."""
    from ..ops.pallas.flash_attention import flash_attention_bwd
    my_idx = lax.axis_index(axis_name)
    perm = _ring_perm(axis_size)
    out_cast = out.astype(q.dtype)

    def chunk(kc, vc, diag_causal):
        return flash_attention_bwd(q, kc, vc, out_cast, lse, do, scale,
                                   diag_causal)

    def step(s_i, carry):
        dq, kc, vc, dk, dv = carry
        if causal:
            kv_idx = (my_idx - s_i) % axis_size

            def active():
                dq_c, dk_c, dv_c = lax.cond(kv_idx == my_idx,
                                            lambda: chunk(kc, vc, True),
                                            lambda: chunk(kc, vc, False))
                return (dq + dq_c.astype(jnp.float32),
                        dk + dk_c.astype(jnp.float32),
                        dv + dv_c.astype(jnp.float32))

            dq, dk, dv = lax.cond(kv_idx <= my_idx, active,
                                  lambda: (dq, dk, dv))
        else:
            dq_c, dk_c, dv_c = chunk(kc, vc, False)
            dq = dq + dq_c.astype(jnp.float32)
            dk = dk + dk_c.astype(jnp.float32)
            dv = dv + dv_c.astype(jnp.float32)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, kc, vc, dk, dv

    init = (jnp.zeros(q.shape, jnp.float32), k, v,
            jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32))
    dq, _, _, dk, dv = lax.fori_loop(0, axis_size, step, init)
    return dq, dk, dv


def _resolved_scale(scale, d):
    return scale if scale is not None else 1.0 / math.sqrt(d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Ring attention over a sharded sequence dimension.

    Call inside ``shard_map`` with q/k/v [B, seq_local, H, D] sharded on the
    seq dim over ``axis_name`` (size derived via ``lax.axis_size``).  Exact
    (not approximate): equivalent to full softmax attention over the global
    sequence.  ``causal`` masks with GLOBAL positions.
    """
    return _ring_fwd_rule(q, k, v, axis_name, causal, scale)[0]


def _ring_fwd_rule(q, k, v, axis_name, causal, scale):
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    out, lse = _ring_fwd_loop(q, k, v, s, causal, axis_name, axis_size)
    return out.astype(q.dtype), (q, k, v, out, lse)


def _ring_bwd_rule(axis_name, causal, scale, res, g):
    q, k, v, out, lse = res
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    dq, dk, dv = _ring_bwd_loop(q, k, v, out, lse, g, s, causal,
                                axis_name, axis_size)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


ring_flash_attention.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ---------------------------------------------------------------------------
# Zigzag ring attention (load-balanced causal CP)
# ---------------------------------------------------------------------------
# Contiguous causal rings are imbalanced: rank 0 attends 1 KV chunk, rank
# R-1 attends R — wall time tracks the worst rank.  The zigzag layout
# (public ring-flash-attention/llama3 recipe) splits the sequence into 2R
# blocks and gives rank i blocks (i, 2R-1-i); then every rank computes
# EXACTLY 2 causal block-pairs per ring step (3 on its own diagonal step),
# so the ring is balanced and ~2x faster at large R.  Exact, not
# approximate — same math as ring_flash_attention under a permuted layout.


def zigzag_permutation(seq_len: int, ring_size: int):
    """Global token permutation realizing the zigzag layout: after the
    standard CONTIGUOUS sharding of the permuted sequence over the sep
    axis, rank i holds original blocks (i, 2R-1-i).

    Returns an int array ``perm`` with ``permuted[t] = original[perm[t]]``.
    Apply to ids AND labels before a zigzag train step (token losses are
    permutation-invariant; attention/rope use original positions via
    :func:`zigzag_positions`)."""
    import numpy as np
    if seq_len % (2 * ring_size):
        raise ValueError(f"seq_len {seq_len} must divide into "
                         f"2*ring_size={2 * ring_size} blocks")
    sb = seq_len // (2 * ring_size)
    parts = []
    for i in range(ring_size):
        parts.append(np.arange(i * sb, (i + 1) * sb))
        parts.append(np.arange((2 * ring_size - 1 - i) * sb,
                               (2 * ring_size - i) * sb))
    return np.concatenate(parts)


def zigzag_positions(s_local: int, axis_name: str):
    """ORIGINAL global positions of this rank's zigzag shard
    ([block i | block 2R-1-i], each s_local/2 long) — feeds rope tables /
    learned position embeddings.  Call inside shard_map."""
    if s_local % 2:
        raise ValueError(f"zigzag layout needs an even local seq length "
                         f"(two blocks per rank), got {s_local}")
    R = lax.axis_size(axis_name)
    i = lax.axis_index(axis_name)
    sb = s_local // 2
    a = i * sb + jnp.arange(sb)
    b = (2 * R - 1 - i) * sb + jnp.arange(sb)
    return jnp.concatenate([a, b])


def _zz_fwd_loop(q, k, v, scale, axis_name, axis_size):
    """Balanced causal forward.  q/k/v local [B, 2*Sb, H, D] in zigzag
    layout; per ring step computes pair (qA,kvA) xor (qB,kvB) plus the
    always-on (qB,kvA) — 2 flash calls/step (3 on the diagonal)."""
    from ..ops.pallas.flash_attention import flash_attention_with_lse
    B, S2, H, D = q.shape
    Sb = S2 // 2
    my = lax.axis_index(axis_name)
    perm = _ring_perm(axis_size)
    qA, qB = q[:, :Sb], q[:, Sb:]

    def merge(o_acc, lse_acc, o_c, lse_c):
        m = jnp.maximum(lse_acc, lse_c)
        w1 = jnp.exp(lse_acc - m)
        w2 = jnp.exp(lse_c - m)
        o = (o_acc * jnp.swapaxes(w1, 1, 2)
             + o_c.astype(jnp.float32) * jnp.swapaxes(w2, 1, 2)) \
            / jnp.swapaxes(w1 + w2, 1, 2)
        return o, m + jnp.log(w1 + w2)

    def step(s_i, carry):
        oA, lA, oB, lB, kc, vc = carry
        j = (my - s_i) % axis_size
        kA, vA = kc[:, :Sb], vc[:, :Sb]
        kB, vB = kc[:, Sb:], vc[:, Sb:]

        def pair_a():       # qA (block i) vs kvA (block j): j <= i
            o, l = lax.cond(
                j == my,
                lambda: flash_attention_with_lse(qA, kA, vA, scale, True),
                lambda: flash_attention_with_lse(qA, kA, vA, scale, False))
            return merge(oA, lA, o, l)

        oA2, lA2 = lax.cond(j <= my, pair_a, lambda: (oA, lA))
        # qB (block 2R-1-i) vs kvA (block j): always strictly past
        o_c, l_c = flash_attention_with_lse(qB, kA, vA, scale, False)
        oB2, lB2 = merge(oB, lB, o_c, l_c)

        def pair_b(oB2=oB2, lB2=lB2):   # qB vs kvB (block 2R-1-j): j >= i
            o, l = lax.cond(
                j == my,
                lambda: flash_attention_with_lse(qB, kB, vB, scale, True),
                lambda: flash_attention_with_lse(qB, kB, vB, scale, False))
            return merge(oB2, lB2, o, l)

        oB3, lB3 = lax.cond(j >= my, pair_b, lambda: (oB2, lB2))
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return oA2, lA2, oB3, lB3, kc, vc

    init = (jnp.zeros((B, Sb, H, D), jnp.float32),
            jnp.full((B, H, Sb, 1), NEG_INF, jnp.float32),
            jnp.zeros((B, Sb, H, D), jnp.float32),
            jnp.full((B, H, Sb, 1), NEG_INF, jnp.float32), k, v)
    oA, lA, oB, lB, _, _ = lax.fori_loop(0, axis_size, step, init)
    return (jnp.concatenate([oA, oB], axis=1),
            jnp.concatenate([lA, lB], axis=2))


def _zz_bwd_loop(q, k, v, out, lse, do, scale, axis_name, axis_size):
    """Backward: dq stays local per q-block; (k, v, dk, dv) rotate
    together; per-pair grads come from the Pallas bwd kernel with the
    block's GLOBAL lse slice, so contributions sum exactly."""
    from ..ops.pallas.flash_attention import flash_attention_bwd
    B, S2, H, D = q.shape
    Sb = S2 // 2
    my = lax.axis_index(axis_name)
    perm = _ring_perm(axis_size)
    oc = out.astype(q.dtype)
    qA, qB = q[:, :Sb], q[:, Sb:]
    oA, oB = oc[:, :Sb], oc[:, Sb:]
    lA, lB = lse[:, :, :Sb], lse[:, :, Sb:]
    doA, doB = do[:, :Sb], do[:, Sb:]

    def step(s_i, carry):
        dqA, dqB, kc, vc, dk, dv = carry
        j = (my - s_i) % axis_size
        kA, vA = kc[:, :Sb], vc[:, :Sb]
        kB, vB = kc[:, Sb:], vc[:, Sb:]
        dkA, dvA = dk[:, :Sb], dv[:, :Sb]
        dkB, dvB = dk[:, Sb:], dv[:, Sb:]

        def pair_a():
            dq_c, dk_c, dv_c = lax.cond(
                j == my,
                lambda: flash_attention_bwd(qA, kA, vA, oA, lA, doA,
                                            scale, True),
                lambda: flash_attention_bwd(qA, kA, vA, oA, lA, doA,
                                            scale, False))
            return (dqA + dq_c.astype(jnp.float32),
                    dkA + dk_c.astype(jnp.float32),
                    dvA + dv_c.astype(jnp.float32))

        dqA2, dkA2, dvA2 = lax.cond(j <= my, pair_a,
                                    lambda: (dqA, dkA, dvA))
        dq_c, dk_c, dv_c = flash_attention_bwd(qB, kA, vA, oB, lB, doB,
                                               scale, False)
        dqB2 = dqB + dq_c.astype(jnp.float32)
        dkA3 = dkA2 + dk_c.astype(jnp.float32)
        dvA3 = dvA2 + dv_c.astype(jnp.float32)

        def pair_b(dqB2=dqB2):
            dq_c, dk_c, dv_c = lax.cond(
                j == my,
                lambda: flash_attention_bwd(qB, kB, vB, oB, lB, doB,
                                            scale, True),
                lambda: flash_attention_bwd(qB, kB, vB, oB, lB, doB,
                                            scale, False))
            return (dqB2 + dq_c.astype(jnp.float32),
                    dkB + dk_c.astype(jnp.float32),
                    dvB + dv_c.astype(jnp.float32))

        dqB3, dkB2, dvB2 = lax.cond(j >= my, pair_b,
                                    lambda: (dqB2, dkB, dvB))
        dk2 = jnp.concatenate([dkA3, dkB2], axis=1)
        dv2 = jnp.concatenate([dvA3, dvB2], axis=1)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dk2 = lax.ppermute(dk2, axis_name, perm)
        dv2 = lax.ppermute(dv2, axis_name, perm)
        return dqA2, dqB3, kc, vc, dk2, dv2

    init = (jnp.zeros((B, Sb, H, D), jnp.float32),
            jnp.zeros((B, Sb, H, D), jnp.float32), k, v,
            jnp.zeros(k.shape, jnp.float32), jnp.zeros(v.shape,
                                                       jnp.float32))
    dqA, dqB, _, _, dk, dv = lax.fori_loop(0, axis_size, step, init)
    return jnp.concatenate([dqA, dqB], axis=1), dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def zigzag_ring_flash_attention(q, k, v, axis_name: str,
                                scale: Optional[float] = None):
    """Load-balanced CAUSAL ring attention over a zigzag-sharded sequence.

    Local q/k/v [B, 2*Sb, H, D] hold original blocks (i, 2R-1-i) — lay
    the data out with :func:`zigzag_permutation` and compute positions
    with :func:`zigzag_positions`.  Exact: equals full causal softmax
    attention over the global (un-permuted) sequence.
    """
    return _zz_fwd_rule(q, k, v, axis_name, scale)[0]


def _zz_fwd_rule(q, k, v, axis_name, scale):
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    if q.shape[1] % 2:
        raise ValueError("zigzag layout needs an even local seq length "
                         "(two blocks per rank)")
    out, lse = _zz_fwd_loop(q, k, v, s, axis_name, axis_size)
    return out.astype(q.dtype), (q, k, v, out, lse)


def _zz_bwd_rule(axis_name, scale, res, g):
    q, k, v, out, lse = res
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    dq, dk, dv = _zz_bwd_loop(q, k, v, out, lse, g, s, axis_name,
                              axis_size)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


zigzag_ring_flash_attention.defvjp(_zz_fwd_rule, _zz_bwd_rule)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------
def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None):
    """All-to-all context parallelism (Ulysses).

    Inside shard_map with seq sharded over ``axis_name``: swaps the sharded
    dim from seq to heads (all_to_all), runs full flash attention on the
    complete sequence locally, swaps back.  Requires
    num_heads % axis_size == 0.  Fully differentiable (all_to_all transposes
    to all_to_all).
    """
    axis_size = lax.axis_size(axis_name)
    if q.shape[2] % axis_size != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({q.shape[2]}) divisible by "
            f"axis size ({axis_size})")
    if k.shape[2] % axis_size != 0:
        # GQA group too coarse for the head all-to-all: locally replicate
        # kv heads up to the q head count (the all_to_all needs the split
        # dim divisible; the flash kernel then sees plain MHA)
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from ..ops.pallas.flash_attention import flash_attention
    # [B, S_loc, H, D] -> [B, S_full, H_loc, D]
    qg, kg, vg = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True) for x in (q, k, v))
    out = flash_attention(qg, kg, vg, scale, causal)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
