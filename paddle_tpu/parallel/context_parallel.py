"""Context parallelism (CP): ring flash attention + Ulysses all-to-all.

The reference snapshot has NO ring/Ulysses implementation (SURVEY §2.5 "CP /
ring attention / Ulysses — NOT present"); its long-sequence story is
Megatron-SP + SEP + FlashAttention.  This module supplies the missing
capability TPU-first: the sequence dimension is a mesh axis (``sep``), KV
blocks rotate over the ICI ring via ``jax.lax.ppermute`` (ring attention), or
heads<->sequence swap via ``jax.lax.all_to_all`` (Ulysses / DeepSpeed-style).

Both entry points are designed to be called INSIDE ``jax.shard_map`` with the
sequence dimension sharded over ``axis_name``:

    q, k, v : [batch, seq_local, heads, head_dim]   (paddle flash layout)

``ring_flash_attention`` is a ``jax.custom_vjp``: the forward carries the
online-softmax state (m, l, acc) across ring steps; the backward replays the
ring, rotating (k, v, dk, dv) together so each chunk's gradient lands back on
its owner after exactly ``axis_size`` hops.  Causal steps whose KV chunk lies
entirely in the masked future are skipped via ``lax.cond``.  Math follows the
blockwise-parallel scheme of the public RingAttention formulation
(PAPERS.md), computed in fp32.

``ulysses_attention`` is automatically differentiable (all_to_all has a
transpose rule); it requires num_heads % axis_size == 0.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas.common import NEG_INF

__all__ = ["ring_flash_attention", "ulysses_attention"]


def _ring_perm(n: int):
    # send local KV chunk to the next rank; after s hops rank i holds
    # chunk (i - s) mod n
    return [(j, (j + 1) % n) for j in range(n)]


def _masked_logits(q, k, *, scale, causal, my_idx, kv_idx, seq_local):
    # q, k: [B, H, S, D] fp32 -> logits [B, H, S, S]
    s = lax.dot_general(q, k, (((3,), (3,)), ((0, 1), (0, 1))),
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = my_idx * seq_local + lax.broadcasted_iota(
            jnp.int32, (seq_local, seq_local), 0)
        k_pos = kv_idx * seq_local + lax.broadcasted_iota(
            jnp.int32, (seq_local, seq_local), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    return s


def _ring_fwd_loop(q, k, v, scale, causal, axis_name, axis_size):
    """q/k/v: [B, H, S, D] (local shard).  Returns (out, lse) fp32."""
    B, H, S, D = q.shape
    my_idx = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    perm = _ring_perm(axis_size)

    def compute(s_i, m, l, acc, kc, vc):
        kv_idx = (my_idx - s_i) % axis_size
        logits = _masked_logits(qf, kc.astype(jnp.float32), scale=scale,
                                causal=causal, my_idx=my_idx, kv_idx=kv_idx,
                                seq_local=S)
        m_cur = jnp.max(logits, -1, keepdims=True)
        m_new = jnp.maximum(m, m_cur)
        p = jnp.exp(logits - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + jnp.sum(p, -1, keepdims=True)
        acc = acc * alpha + lax.dot_general(
            p, vc.astype(jnp.float32), (((3,), (2,)), ((0, 1), (0, 1))),
            preferred_element_type=jnp.float32)
        return m_new, l, acc

    def step(s_i, carry):
        m, l, acc, kc, vc = carry
        if causal:
            # chunks strictly in the masked future contribute nothing
            kv_idx = (my_idx - s_i) % axis_size
            m, l, acc = lax.cond(
                kv_idx <= my_idx,
                lambda: compute(s_i, m, l, acc, kc, vc),
                lambda: (m, l, acc))
        else:
            m, l, acc = compute(s_i, m, l, acc, kc, vc)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return m, l, acc, kc, vc

    init = (jnp.full((B, H, S, 1), NEG_INF, jnp.float32),
            jnp.zeros((B, H, S, 1), jnp.float32),
            jnp.zeros((B, H, S, D), jnp.float32), k, v)
    m, l, acc, _, _ = lax.fori_loop(0, axis_size, step, init)
    l = jnp.maximum(l, 1e-30)
    return acc / l, m + jnp.log(l)


def _ring_bwd_loop(q, k, v, out, lse, do, scale, causal, axis_name,
                   axis_size):
    """Backward ring: dq stays local; (k, v, dk, dv) rotate together so each
    KV chunk accumulates its gradient from every rank and arrives home after
    axis_size hops."""
    B, H, S, D = q.shape
    my_idx = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(out * dof, -1, keepdims=True)   # [B, H, S, 1] fp32
    perm = _ring_perm(axis_size)

    def compute(s_i, dq, kc, vc, dk, dv):
        kv_idx = (my_idx - s_i) % axis_size
        kf = kc.astype(jnp.float32)
        vf = vc.astype(jnp.float32)
        logits = _masked_logits(qf, kf, scale=scale, causal=causal,
                                my_idx=my_idx, kv_idx=kv_idx, seq_local=S)
        p = jnp.exp(logits - lse)                    # [B, H, S, Sk]
        dv = dv + lax.dot_general(p, dof, (((2,), (2,)), ((0, 1), (0, 1))),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(dof, vf, (((3,), (3,)), ((0, 1), (0, 1))),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq = dq + lax.dot_general(ds, kf, (((3,), (2,)), ((0, 1), (0, 1))),
                                  preferred_element_type=jnp.float32)
        dk = dk + lax.dot_general(ds, qf, (((2,), (2,)), ((0, 1), (0, 1))),
                                  preferred_element_type=jnp.float32)
        return dq, dk, dv

    def step(s_i, carry):
        dq, kc, vc, dk, dv = carry
        if causal:
            kv_idx = (my_idx - s_i) % axis_size
            dq, dk, dv = lax.cond(
                kv_idx <= my_idx,
                lambda: compute(s_i, dq, kc, vc, dk, dv),
                lambda: (dq, dk, dv))
        else:
            dq, dk, dv = compute(s_i, dq, kc, vc, dk, dv)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, kc, vc, dk, dv

    init = (jnp.zeros((B, H, S, D), jnp.float32), k, v,
            jnp.zeros((B, H, S, D), jnp.float32),
            jnp.zeros((B, H, S, D), jnp.float32))
    dq, _, _, dk, dv = lax.fori_loop(0, axis_size, step, init)
    return dq, dk, dv


def _resolved_scale(scale, d):
    return scale if scale is not None else 1.0 / math.sqrt(d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Ring attention over a sharded sequence dimension.

    Call inside ``shard_map`` with q/k/v [B, seq_local, H, D] sharded on the
    seq dim over ``axis_name`` (size derived via ``lax.axis_size``).  Exact
    (not approximate): equivalent to full softmax attention over the global
    sequence.  ``causal`` masks with GLOBAL positions.
    """
    return _ring_fwd_rule(q, k, v, axis_name, causal, scale)[0]


def _ring_fwd_rule(q, k, v, axis_name, causal, scale):
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    out, lse = _ring_fwd_loop(qt, kt, vt, s, causal, axis_name, axis_size)
    return (jnp.swapaxes(out, 1, 2).astype(q.dtype),
            (q, k, v, out, lse))


def _ring_bwd_rule(axis_name, causal, scale, res, g):
    q, k, v, out, lse = res
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    qt, kt, vt = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    dot_ = jnp.swapaxes(g, 1, 2)
    dq, dk, dv = _ring_bwd_loop(qt, kt, vt, out, lse, dot_, s, causal,
                                axis_name, axis_size)
    return (jnp.swapaxes(dq, 1, 2).astype(q.dtype),
            jnp.swapaxes(dk, 1, 2).astype(k.dtype),
            jnp.swapaxes(dv, 1, 2).astype(v.dtype))


ring_flash_attention.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------
def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None):
    """All-to-all context parallelism (Ulysses).

    Inside shard_map with seq sharded over ``axis_name``: swaps the sharded
    dim from seq to heads (all_to_all), runs full flash attention on the
    complete sequence locally, swaps back.  Requires
    num_heads % axis_size == 0.  Fully differentiable (all_to_all transposes
    to all_to_all).
    """
    axis_size = lax.axis_size(axis_name)
    if q.shape[2] % axis_size != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({q.shape[2]}) divisible by "
            f"axis size ({axis_size})")
    from ..ops.pallas.flash_attention import flash_attention
    # [B, S_loc, H, D] -> [B, S_full, H_loc, D]
    qg, kg, vg = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True) for x in (q, k, v))
    out = flash_attention(qg, kg, vg, scale, causal)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
