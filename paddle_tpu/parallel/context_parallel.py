"""Context parallelism (CP): ring flash attention + Ulysses all-to-all.

The reference snapshot has NO ring/Ulysses implementation (SURVEY §2.5 "CP /
ring attention / Ulysses — NOT present"); its long-sequence story is
Megatron-SP + SEP + FlashAttention.  This module supplies the missing
capability TPU-first: the sequence dimension is a mesh axis (``sep``), KV
blocks rotate over the ICI ring via ``jax.lax.ppermute`` (ring attention), or
heads<->sequence swap via ``jax.lax.all_to_all`` (Ulysses / DeepSpeed-style).

Both entry points are designed to be called INSIDE ``jax.shard_map`` with the
sequence dimension sharded over ``axis_name``:

    q, k, v : [batch, seq_local, heads, head_dim]   (paddle flash layout)

``ring_flash_attention`` is a ``jax.custom_vjp``: the forward carries the
online-softmax state (m, l, acc) across ring steps; the backward replays the
ring, rotating (k, v, dk, dv) together so each chunk's gradient lands back on
its owner after exactly ``axis_size`` hops.  Causal steps whose KV chunk lies
entirely in the masked future are skipped via ``lax.cond``.  Math follows the
blockwise-parallel scheme of the public RingAttention formulation
(PAPERS.md), computed in fp32.

``ulysses_attention`` is automatically differentiable (all_to_all has a
transpose rule); it requires num_heads % axis_size == 0.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.pallas.common import NEG_INF

__all__ = ["ring_flash_attention", "ulysses_attention"]


def _ring_perm(n: int):
    # send local KV chunk to the next rank; after s hops rank i holds
    # chunk (i - s) mod n
    return [(j, (j + 1) % n) for j in range(n)]


def _ring_fwd_loop(q, k, v, scale, causal, axis_name, axis_size):
    """q/k/v: [B, S, H, D] (local shard; GQA ok).  Returns
    (out [B,S,H,D] fp32, lse [B,H,S,1] fp32).

    Inner compute is the Pallas flash kernel per KV chunk
    (ops/pallas/flash_attention.py — VERDICT r1: ring's inner math was
    plain jnp and the flagship TPU path never ran the flagship kernel);
    chunk results merge by the associative log-sum-exp rule."""
    from ..ops.pallas.flash_attention import flash_attention_with_lse
    B, S, H, D = q.shape
    my_idx = lax.axis_index(axis_name)
    perm = _ring_perm(axis_size)

    def merge(o_acc, lse_acc, o_c, lse_c):
        m = jnp.maximum(lse_acc, lse_c)
        w1 = jnp.exp(lse_acc - m)
        w2 = jnp.exp(lse_c - m)
        o = (o_acc * jnp.swapaxes(w1, 1, 2)
             + o_c.astype(jnp.float32) * jnp.swapaxes(w2, 1, 2)) \
            / jnp.swapaxes(w1 + w2, 1, 2)
        return o, m + jnp.log(w1 + w2)

    def chunk(kc, vc, diag_causal):
        return flash_attention_with_lse(q, kc, vc, scale, diag_causal)

    def step(s_i, carry):
        o_acc, lse_acc, kc, vc = carry
        if causal:
            kv_idx = (my_idx - s_i) % axis_size

            def active():
                o_c, lse_c = lax.cond(kv_idx == my_idx,
                                      lambda: chunk(kc, vc, True),
                                      lambda: chunk(kc, vc, False))
                return merge(o_acc, lse_acc, o_c, lse_c)

            # chunks strictly in the masked future contribute nothing
            o_acc2, lse_acc2 = lax.cond(kv_idx <= my_idx, active,
                                        lambda: (o_acc, lse_acc))
        else:
            o_c, lse_c = chunk(kc, vc, False)
            o_acc2, lse_acc2 = merge(o_acc, lse_acc, o_c, lse_c)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return o_acc2, lse_acc2, kc, vc

    init = (jnp.zeros((B, S, H, D), jnp.float32),
            jnp.full((B, H, S, 1), NEG_INF, jnp.float32), k, v)
    out, lse, _, _ = lax.fori_loop(0, axis_size, step, init)
    return out, lse


def _ring_bwd_loop(q, k, v, out, lse, do, scale, causal, axis_name,
                   axis_size):
    """Backward ring (all [B, S, H, D]): dq stays local; (k, v, dk, dv)
    rotate together so each KV chunk accumulates its gradient from every
    rank and arrives home after axis_size hops.  Per-chunk gradients come
    from the Pallas bwd kernels with the GLOBAL lse, so the chunk
    contributions sum to the exact gradient."""
    from ..ops.pallas.flash_attention import flash_attention_bwd
    my_idx = lax.axis_index(axis_name)
    perm = _ring_perm(axis_size)
    out_cast = out.astype(q.dtype)

    def chunk(kc, vc, diag_causal):
        return flash_attention_bwd(q, kc, vc, out_cast, lse, do, scale,
                                   diag_causal)

    def step(s_i, carry):
        dq, kc, vc, dk, dv = carry
        if causal:
            kv_idx = (my_idx - s_i) % axis_size

            def active():
                dq_c, dk_c, dv_c = lax.cond(kv_idx == my_idx,
                                            lambda: chunk(kc, vc, True),
                                            lambda: chunk(kc, vc, False))
                return (dq + dq_c.astype(jnp.float32),
                        dk + dk_c.astype(jnp.float32),
                        dv + dv_c.astype(jnp.float32))

            dq, dk, dv = lax.cond(kv_idx <= my_idx, active,
                                  lambda: (dq, dk, dv))
        else:
            dq_c, dk_c, dv_c = chunk(kc, vc, False)
            dq = dq + dq_c.astype(jnp.float32)
            dk = dk + dk_c.astype(jnp.float32)
            dv = dv + dv_c.astype(jnp.float32)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return dq, kc, vc, dk, dv

    init = (jnp.zeros(q.shape, jnp.float32), k, v,
            jnp.zeros(k.shape, jnp.float32),
            jnp.zeros(v.shape, jnp.float32))
    dq, _, _, dk, dv = lax.fori_loop(0, axis_size, step, init)
    return dq, dk, dv


def _resolved_scale(scale, d):
    return scale if scale is not None else 1.0 / math.sqrt(d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Ring attention over a sharded sequence dimension.

    Call inside ``shard_map`` with q/k/v [B, seq_local, H, D] sharded on the
    seq dim over ``axis_name`` (size derived via ``lax.axis_size``).  Exact
    (not approximate): equivalent to full softmax attention over the global
    sequence.  ``causal`` masks with GLOBAL positions.
    """
    return _ring_fwd_rule(q, k, v, axis_name, causal, scale)[0]


def _ring_fwd_rule(q, k, v, axis_name, causal, scale):
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    out, lse = _ring_fwd_loop(q, k, v, s, causal, axis_name, axis_size)
    return out.astype(q.dtype), (q, k, v, out, lse)


def _ring_bwd_rule(axis_name, causal, scale, res, g):
    q, k, v, out, lse = res
    s = _resolved_scale(scale, q.shape[-1])
    axis_size = lax.axis_size(axis_name)
    dq, dk, dv = _ring_bwd_loop(q, k, v, out, lse, g, s, causal,
                                axis_name, axis_size)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


ring_flash_attention.defvjp(_ring_fwd_rule, _ring_bwd_rule)


# ---------------------------------------------------------------------------
# Ulysses (all-to-all sequence parallelism)
# ---------------------------------------------------------------------------
def ulysses_attention(q, k, v, axis_name: str, causal: bool = True,
                      scale: Optional[float] = None):
    """All-to-all context parallelism (Ulysses).

    Inside shard_map with seq sharded over ``axis_name``: swaps the sharded
    dim from seq to heads (all_to_all), runs full flash attention on the
    complete sequence locally, swaps back.  Requires
    num_heads % axis_size == 0.  Fully differentiable (all_to_all transposes
    to all_to_all).
    """
    axis_size = lax.axis_size(axis_name)
    if q.shape[2] % axis_size != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads ({q.shape[2]}) divisible by "
            f"axis size ({axis_size})")
    if k.shape[2] % axis_size != 0:
        # GQA group too coarse for the head all-to-all: locally replicate
        # kv heads up to the q head count (the all_to_all needs the split
        # dim divisible; the flash kernel then sees plain MHA)
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    from ..ops.pallas.flash_attention import flash_attention
    # [B, S_loc, H, D] -> [B, S_full, H_loc, D]
    qg, kg, vg = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True) for x in (q, k, v))
    out = flash_attention(qg, kg, vg, scale, causal)
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
