"""Collective-matmul overlap (ring-decomposed TP/SP linears).

Reference anchors: the all-gather-overlap path of
``ColumnSequenceParallelLinear`` (python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py:255 — splits the all-gather into chunked
broadcasts overlapped with the gemm) and the comm/compute overlap that
``fused_linear_param_grad_add`` (phi/kernels/fusion/gpu/
fused_linear_param_grad_add_kernel.cu) exists to serve.

TPU-native design: instead of issuing one big ``all_gather`` (or
``psum``/``reduce_scatter``) *around* a matmul, decompose the pair into a
ring of ``lax.ppermute`` steps interleaved with per-chunk matmuls.  On
TPU, collective-permute is an async ICI operation (start/done pairs in
HLO), so XLA's latency-hiding scheduler overlaps every hop with the
matmul of the chunk already on-chip — the classic "collective matmul"
(Wang et al., "Overlap communication with dependent computation via
decomposition", ASPLOS'23; the same recipe the scaling-book derives for
Megatron linears).  Peak benefit: weight-stationary TP linears whose
gather/scatter time is comparable to their gemm time.

Everything here is manual-SPMD: call INSIDE ``shard_map`` with
``axis_name`` manual, same convention as parallel/manual.py.  All
functions are differentiable (ppermute/dynamic-slice autodiff; the
transpose of a ring is the reverse ring), so they drop into existing
training steps — ``test_overlap.py`` asserts fwd+bwd equivalence against
the un-decomposed collectives on an 8-device virtual mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .topology import MP_AXIS

__all__ = ["all_gather_matmul", "matmul_reduce_scatter",
           "matmul_all_reduce", "sp_matmul_helpers"]


def _ring_perm(n, reverse=False):
    if reverse:
        return [(i, (i - 1) % n) for i in range(n)]
    return [(i, (i + 1) % n) for i in range(n)]


def all_gather_matmul(x_shard, w, axis_name: str = MP_AXIS, axis: int = 1):
    """``all_gather(x_shard, axis) @ w`` as a ppermute ring.

    ``x_shard``: local sequence shard (…, s_local, K) sharded on ``axis``
    over ``axis_name``; ``w``: (K, N_local) — any local weight (column
    shard for SP column-linear).  Returns the full-sequence product
    (…, s_local * n, N_local), bit-identical (up to fp reassociation) to
    gathering first.

    Ring schedule: at step t the chip multiplies the chunk that
    originated on rank (i + t) mod n while its ppermute of the buffer to
    rank i-1 is in flight; XLA overlaps the two because the matmul does
    not depend on the permute result.
    """
    n = lax.axis_size(axis_name)
    i = lax.axis_index(axis_name)
    s_local = x_shard.shape[axis]

    out_shape = list(x_shard.shape[:-1]) + [w.shape[-1]]
    out_shape[axis] = s_local * n
    y = jnp.zeros(out_shape, dtype=jnp.result_type(x_shard.dtype, w.dtype))

    def write(y, buf, t):
        src = (i + t) % n                     # chunk origin of current buf
        chunk = buf @ w
        return lax.dynamic_update_slice_in_dim(y, chunk.astype(y.dtype),
                                               src * s_local, axis)

    def body(t, carry):
        y, buf = carry
        y = write(y, buf, t)
        # send buf around the ring so next step holds rank (i+t+1)'s chunk;
        # the permute shares no deps with the matmul, so the scheduler
        # starts it first and hides the hop behind the gemm
        buf = lax.ppermute(buf, axis_name, _ring_perm(n, reverse=True))
        return y, buf

    # n-1 hops total: the final chunk's matmul runs outside the loop so no
    # dead permute executes on the last iteration
    y, buf = lax.fori_loop(0, n - 1, body, (y, x_shard))
    return write(y, buf, n - 1)


def matmul_reduce_scatter(x, w, axis_name: str = MP_AXIS, axis: int = 1):
    """``reduce_scatter(x @ w, axis)`` as a ppermute ring.

    ``x``: full-sequence local input (…, S, K_local); ``w``: (K_local, N)
    row shard.  Each rank's partial product is reduce-scattered along
    ``axis`` so rank i returns chunk i of the sum, shape (…, S/n, N).

    The accumulator destined for rank j starts at rank j+1 and travels
    the +1 ring for n-1 hops, each receiving rank adding its OWN partial
    of chunk j — and critically, computing that partial's matmul while
    the previous hop is in flight.
    """
    n = lax.axis_size(axis_name)
    i = lax.axis_index(axis_name)
    S = x.shape[axis]
    if S % n:
        raise ValueError(f"matmul_reduce_scatter: dim {axis} ({S}) not "
                         f"divisible by {axis_name} size {n}")
    s_local = S // n

    def part(c):
        """matmul of sequence chunk c only (keeps each step's gemm 1/n)."""
        xc = lax.dynamic_slice_in_dim(x, c * s_local, s_local, axis)
        return xc @ w

    acc = part((i - 1) % n)

    def body(t, acc):
        acc = lax.ppermute(acc, axis_name, _ring_perm(n))
        return acc + part((i - 1 - t) % n)

    return lax.fori_loop(1, n, body, acc)


def sp_matmul_helpers(mp_axis, sequence_parallel: bool, tp_overlap: bool,
                      col_in, row_out):
    """Build the (col_mm, row_mm) pair a Megatron-style block uses for its
    column/row matmuls, ring-decomposed when ``tp_overlap`` applies.

    ``col_in(y)``/``row_out(z)`` are the model's un-decomposed fallbacks
    (mp_copy / all_gather_op before columns; fwd_psum / reduce_scatter_op
    after rows).  ``col_mm(y, *ws)`` always returns a tuple, one product
    per weight; sibling column weights (q/k/v, gate/up) share ONE ring by
    concatenation.  Shared by models/gpt.py and models/llama.py so ring
    dispatch lives in exactly one place.
    """
    ring = mp_axis is not None and sequence_parallel and tp_overlap

    def col_mm(y, *ws):
        if ring:
            w = jnp.concatenate(ws, axis=1) if len(ws) > 1 else ws[0]
            out = all_gather_matmul(y, w, mp_axis)
            if len(ws) == 1:
                return (out,)
            splits = []
            off = 0
            for w_ in ws[:-1]:
                off += w_.shape[1]
                splits.append(off)
            return tuple(jnp.split(out, splits, axis=-1))
        yg = col_in(y)
        return tuple(yg @ w_ for w_ in ws)

    def row_mm(z, w):
        if ring:
            return matmul_reduce_scatter(z, w, mp_axis)
        return row_out(z @ w)

    return col_mm, row_mm


def matmul_all_reduce(x, w, axis_name: str = MP_AXIS, axis: int = 1):
    """``psum(x @ w)`` via ring reduce-scatter + all-gather.

    Only the reduce-scatter half rides the overlapped ring; the trailing
    ``all_gather`` is issued after the chunked gemms finish, so its
    latency is NOT hidden behind compute.  Prefer keeping the activation
    sequence-sharded (plain ``matmul_reduce_scatter``) when the consumer
    allows it — that is the SP design point."""
    y_shard = matmul_reduce_scatter(x, w, axis_name, axis)
    return lax.all_gather(y_shard, axis_name, axis=axis, tiled=True)
