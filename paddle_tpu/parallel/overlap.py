"""Collective-matmul overlap (ring-decomposed TP/SP linears).

Reference anchors: the all-gather-overlap path of
``ColumnSequenceParallelLinear`` (python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py:255 — splits the all-gather into chunked
broadcasts overlapped with the gemm) and the comm/compute overlap that
``fused_linear_param_grad_add`` (phi/kernels/fusion/gpu/
fused_linear_param_grad_add_kernel.cu) exists to serve.

TPU-native design: instead of issuing one big ``all_gather`` (or
``psum``/``reduce_scatter``) *around* a matmul, decompose the pair into a
ring of ``lax.ppermute`` steps interleaved with per-chunk matmuls.  On
TPU, collective-permute is an async ICI operation (start/done pairs in
HLO), so XLA's latency-hiding scheduler overlaps every hop with the
matmul of the chunk already on-chip — the classic "collective matmul"
(Wang et al., "Overlap communication with dependent computation via
decomposition", ASPLOS'23; the same recipe the scaling-book derives for
Megatron linears).  Peak benefit: weight-stationary TP linears whose
gather/scatter time is comparable to their gemm time.

Everything here is manual-SPMD: call INSIDE ``shard_map`` with
``axis_name`` manual, same convention as parallel/manual.py.  All
functions are differentiable (ppermute/dynamic-slice autodiff; the
transpose of a ring is the reverse ring), so they drop into existing
training steps — ``test_overlap.py`` asserts fwd+bwd equivalence against
the un-decomposed collectives on an 8-device virtual mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .topology import MP_AXIS

__all__ = ["all_gather_matmul", "matmul_reduce_scatter",
           "matmul_all_reduce"]


def _ring_perm(n, reverse=False):
    if reverse:
        return [(i, (i - 1) % n) for i in range(n)]
    return [(i, (i + 1) % n) for i in range(n)]


def all_gather_matmul(x_shard, w, axis_name: str = MP_AXIS, axis: int = 1):
    """``all_gather(x_shard, axis) @ w`` as a ppermute ring.

    ``x_shard``: local sequence shard (…, s_local, K) sharded on ``axis``
    over ``axis_name``; ``w``: (K, N_local) — any local weight (column
    shard for SP column-linear).  Returns the full-sequence product
    (…, s_local * n, N_local), bit-identical (up to fp reassociation) to
    gathering first.

    Ring schedule: at step t the chip multiplies the chunk that
    originated on rank (i + t) mod n while its ppermute of the buffer to
    rank i-1 is in flight; XLA overlaps the two because the matmul does
    not depend on the permute result.
    """
    n = lax.axis_size(axis_name)
    i = lax.axis_index(axis_name)
    s_local = x_shard.shape[axis]

    out_shape = list(x_shard.shape[:-1]) + [w.shape[-1]]
    out_shape[axis] = s_local * n
    y = jnp.zeros(out_shape, dtype=jnp.result_type(x_shard.dtype, w.dtype))

    def body(t, carry):
        y, buf = carry
        src = (i + t) % n                     # chunk origin of current buf
        chunk = buf @ w
        y = lax.dynamic_update_slice_in_dim(y, chunk.astype(y.dtype),
                                            src * s_local, axis)
        # send buf around the ring so next step holds rank (i+t+1)'s chunk
        buf = lax.ppermute(buf, axis_name, _ring_perm(n, reverse=True))
        return y, buf

    y, _ = lax.fori_loop(0, n, body, (y, x_shard))
    return y


def matmul_reduce_scatter(x, w, axis_name: str = MP_AXIS, axis: int = 1):
    """``reduce_scatter(x @ w, axis)`` as a ppermute ring.

    ``x``: full-sequence local input (…, S, K_local); ``w``: (K_local, N)
    row shard.  Each rank's partial product is reduce-scattered along
    ``axis`` so rank i returns chunk i of the sum, shape (…, S/n, N).

    The accumulator destined for rank j starts at rank j+1 and travels
    the +1 ring for n-1 hops, each receiving rank adding its OWN partial
    of chunk j — and critically, computing that partial's matmul while
    the previous hop is in flight.
    """
    n = lax.axis_size(axis_name)
    i = lax.axis_index(axis_name)
    S = x.shape[axis]
    if S % n:
        raise ValueError(f"matmul_reduce_scatter: dim {axis} ({S}) not "
                         f"divisible by {axis_name} size {n}")
    s_local = S // n

    def part(c):
        """matmul of sequence chunk c only (keeps each step's gemm 1/n)."""
        xc = lax.dynamic_slice_in_dim(x, c * s_local, s_local, axis)
        return xc @ w

    acc = part((i - 1) % n)

    def body(t, acc):
        acc = lax.ppermute(acc, axis_name, _ring_perm(n))
        return acc + part((i - 1 - t) % n)

    return lax.fori_loop(1, n, body, acc)


def matmul_all_reduce(x, w, axis_name: str = MP_AXIS, axis: int = 1):
    """``psum(x @ w)`` via ring reduce-scatter + all-gather.

    Only the reduce-scatter half rides the overlapped ring; the trailing
    ``all_gather`` is issued after the chunked gemms finish, so its
    latency is NOT hidden behind compute.  Prefer keeping the activation
    sequence-sharded (plain ``matmul_reduce_scatter``) when the consumer
    allows it — that is the SP design point."""
    y_shard = matmul_reduce_scatter(x, w, axis_name, axis)
    return lax.all_gather(y_shard, axis_name, axis=axis, tiled=True)
