"""``paddle_tpu.distributed`` — alias of :mod:`paddle_tpu.parallel` matching
the reference's ``paddle.distributed`` import path."""

from .parallel import *  # noqa: F401,F403
from .parallel import collective, fleet  # noqa: F401
from .parallel.env import init_parallel_env, get_rank, get_world_size  # noqa: F401
