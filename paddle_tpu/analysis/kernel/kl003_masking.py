"""KL003 — tile-edge masking discipline.

A grid axis built with ``pl.cdiv`` (or the ``-(-a // b)`` idiom) means
the LAST tile on that axis can run past the real extent: the block
machinery still delivers a full block (zero/garbage padded, or clamped
re-reads), so a kernel that folds such a tile into a reduction without
masking silently corrupts the result — off-TPU the interpret lane may
even hide it because padding happens to be zeros.

The rule demands that a kernel behind a ceil-divided grid contains at
least one masking construct in its transitive body: ``pl.when``,
``jnp.where``, a ``broadcasted_iota``/``iota`` position stream, or an
index clamp (``minimum``/``maximum``/``clip``).  This matches how
every masked kernel in the repo is written (linear_ce masks
``cols < V``; decode_block clamps the block-table index and masks
``t < length``).
"""

from __future__ import annotations

import ast

from .. import core
from .extract import extract_sites, kernel_closure

_MASK_TAILS = {"when", "where", "broadcasted_iota", "iota", "minimum",
               "maximum", "clip", "select", "select_n"}


@core.register
class TileEdgeMaskRule(core.Rule):
    id = "KL003"
    name = "unmasked-tile-edge"
    severity = "warning"
    doc = ("a pallas_call grid uses ceil-division (pl.cdiv / "
           "-(-a // b)) so its last tile overhangs the data, but the "
           "kernel body has no masking construct (pl.when / where / "
           "iota / clamp)")
    hint = ("mask the overhang: compare an iota position stream "
            "against the true extent (see linear_ce `cols < V`), or "
            "guard the fold with pl.when")

    def check(self, module):
        for site in extract_sites(module):
            if not site.grid_has_cdiv:
                continue
            body = kernel_closure(site)
            if not body:
                continue            # kernel unresolved: nothing provable
            masked = any(
                isinstance(node, ast.Call)
                and core.tail_name(node.func) in _MASK_TAILS
                for fn in body for node in ast.walk(fn))
            if not masked:
                yield self.finding(
                    module, site.call,
                    f"grid of kernel `{site.kernel_name}` uses "
                    "ceil-division but the kernel body never masks the "
                    "tile overhang")
