"""KL001 — provable VMEM overflow at a pallas_call site.

The finding is a PROOF, not a guess: every contributing term is a
lower bound (unproven dims count 1, unproven dtypes count 1 byte,
unparsed buffers count 0), so if the provable working set alone
exceeds :func:`cost.budget_bytes` the kernel can never fit on any
configured generation's core — Mosaic would reject it on hardware
after a compile this rule catches at review time.

Runtime-dependent geometries (most real kernels) are NOT flagged: for
those, the same cost model is enforced dynamically by the fusion
fallback in ``ops/decode_block.py`` and the autotune validity filters,
which this package is the single source of truth for.
"""

from __future__ import annotations

from .. import core
from . import cost
from .extract import extract_sites

_SEVERITY_NOTE = "provable lower bound"


def provable_bytes(site) -> int:
    """Sound lower bound of a site's per-grid-step VMEM residency."""
    total = 0
    for spec, dtype in (
            [(s, None) for s in site.in_specs]
            + list(zip(site.out_specs,
                       site.out_dtypes + [None] * len(site.out_specs)))):
        if not spec.known or spec.memory_space != "vmem":
            continue
        shape = spec.resolved_shape
        if shape is None:
            continue
        isz = 1
        if dtype is not None:
            try:
                isz = cost.itemsize(dtype)
            except ValueError:
                isz = 1
        total += cost.Buffer("block", shape, isz).bytes
    for scr in site.scratch:
        if scr.kind != "vmem" or scr.shape is None:
            continue
        shape = tuple(d if isinstance(d, int) else None
                      for d in scr.shape)
        isz = 1
        if scr.dtype is not None:
            try:
                isz = cost.itemsize(scr.dtype)
            except ValueError:
                isz = 1
        total += cost.Buffer("scratch", shape, isz).bytes
    return total


@core.register
class VmemFootprintRule(core.Rule):
    id = "KL001"
    name = "vmem-overflow"
    severity = "error"
    doc = ("a pallas_call's statically-provable per-grid-step working "
           "set (blocks + scratch, lower-bounded) exceeds the "
           "analysis/kernel/cost.py VMEM budget — the kernel can never "
           "fit a core")
    hint = ("shrink the block/scratch shapes or split the kernel; the "
            "budget table lives in analysis/kernel/cost.py "
            "(budget_bytes) — the same number the runtime fusion "
            "fallback enforces")

    def check(self, module):
        budget = cost.budget_bytes()
        for site in extract_sites(module):
            lb = provable_bytes(site)
            if lb > budget:
                yield self.finding(
                    module, site.call,
                    f"pallas_call working set is provably >= "
                    f"{lb / 2**20:.1f} MB "
                    f"({_SEVERITY_NOTE}) > VMEM budget "
                    f"{budget / 2**20:.1f} MB "
                    f"({cost.DEFAULT_GENERATION}, "
                    f"{int(cost.SAFETY_FRACTION * 100)}% of "
                    f"{cost.VMEM_BYTES_PER_CORE[cost.DEFAULT_GENERATION] / 2**20:.0f} MB)")
