"""kernellint — static Pallas-kernel safety analysis (ISSUE 10).

The package has two faces sharing one cost model:

* :mod:`.cost` — the VMEM cost model.  Closed-form per-kernel byte
  estimates plus the per-generation budget table.  This is ALSO the
  runtime source of truth: ``ops/decode_block.py``'s fusion-fallback
  gate and ``ops/pallas``'s autotune config-validity filter import it,
  so the number the static analyzer checks against is the number the
  serving dispatch actually enforces — they cannot drift.
* :mod:`.extract` + the ``kl00X_*`` rule modules — an AST model of
  every ``pl.pallas_call`` site (grid, BlockSpecs, index maps,
  scratch_shapes, dtypes) feeding the KL001–KL006 rules, registered in
  the same engine as tracelint (``analysis/core.py``): one CLI, one
  suppression syntax, one ratchet machinery, a separate KERNELLINT.md
  ledger.

``cost`` deliberately imports no jax: the analyzer (and CI ratchet)
must run on a bare interpreter, and the runtime callers only hand it
plain ints/strs.
"""

from . import cost  # noqa: F401

__all__ = ["cost"]
