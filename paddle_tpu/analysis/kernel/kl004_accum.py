"""KL004 — accumulation-dtype hazards inside kernels.

bf16 has 8 mantissa bits: a reduction carried in bf16 across grid
steps loses the small addends long before the sum is done, and on the
MXU a dot without ``preferred_element_type`` accumulates in the INPUT
dtype.  The repo convention (every shipped kernel) is: dots say
``preferred_element_type=jnp.float32`` and running state lives in fp32
VMEM scratch, cast once on the final store.

Two exact checks on the kernel's transitive body:

* a ``dot_general``/``dot`` call (or a bare ``@``) without
  ``preferred_element_type`` — the input-dtype-accumulation hazard;
* a VMEM scratch buffer declared in a 16-bit dtype that the kernel
  accumulates into (``ref[...] += ...`` or a self-referencing
  ``ref[...] = f(ref[...])`` update), resolved by mapping the kernel's
  positional signature onto (inputs, outputs, scratch) — only when the
  signature and spec lists are complete enough to make the mapping a
  fact.
"""

from __future__ import annotations

import ast

from .. import core
from .extract import extract_sites, kernel_closure

_DOT_TAILS = {"dot_general", "dot"}
_HALF_DTYPES = {"bfloat16", "float16"}


def _scratch_param_names(site):
    """{param name -> ScratchInfo} when the positional mapping is
    provable, else {}."""
    fn = site.kernel_fn
    if fn is None or not isinstance(fn, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
        return {}
    if not (site.in_specs_complete and site.out_specs_complete
            and site.scratch_complete):
        return {}
    a = fn.args
    if a.vararg or a.kwarg or a.kwonlyargs:
        return {}
    params = [p.arg for p in (a.posonlyargs + a.args)]
    n_in, n_out, n_scr = (len(site.in_specs), len(site.out_specs),
                          len(site.scratch))
    if len(params) != n_in + n_out + n_scr or n_scr == 0:
        return {}
    return dict(zip(params[n_in + n_out:], site.scratch))


@core.register
class AccumDtypeRule(core.Rule):
    id = "KL004"
    name = "accum-dtype-hazard"
    severity = "warning"
    doc = ("a kernel dot lacks preferred_element_type (accumulates in "
           "the input dtype — bf16 on serving paths), or a reduction "
           "is carried in a 16-bit VMEM scratch buffer instead of "
           "fp32")
    hint = ("pass preferred_element_type=jnp.float32 to every kernel "
            "dot; keep running softmax/matmul state in fp32 scratch "
            "and cast once on the final store")

    def _body_dot_findings(self, module, site):
        for fn in kernel_closure(site):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and core.tail_name(node.func) in _DOT_TAILS:
                    if not any(k.arg == "preferred_element_type"
                               for k in node.keywords):
                        yield self.finding(
                            module, node,
                            f"`{core.tail_name(node.func)}` in kernel "
                            f"`{site.kernel_name}` has no "
                            "preferred_element_type — accumulates in "
                            "the input dtype")
                elif isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.MatMult):
                    yield self.finding(
                        module, node,
                        f"bare `@` matmul in kernel "
                        f"`{site.kernel_name}` accumulates in the "
                        "input dtype; use lax.dot_general with "
                        "preferred_element_type")

    def _half_scratch_findings(self, module, site):
        half = {name: scr
                for name, scr in _scratch_param_names(site).items()
                if scr.dtype in _HALF_DTYPES}
        if not half:
            return
        fn = site.kernel_fn
        for node in ast.walk(fn):
            target = value = None
            if isinstance(node, ast.AugAssign):
                target, value = node.target, None
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                target, value = node.targets[0], node.value
            if not (isinstance(target, ast.Subscript)
                    and isinstance(target.value, ast.Name)
                    and target.value.id in half):
                continue
            name = target.value.id
            if value is not None:
                # plain store is fine; only self-referencing updates
                # (ref = f(ref)) carry the reduction in bf16
                reads_self = any(
                    isinstance(n, ast.Name) and n.id == name
                    for n in ast.walk(value))
                if not reads_self:
                    continue
            yield self.finding(
                module, node,
                f"reduction carried in 16-bit VMEM scratch `{name}` "
                f"({half[name].dtype}) in kernel "
                f"`{site.kernel_name}` — accumulate in an fp32 "
                "scratch and cast on the final store")

    def check(self, module):
        seen = set()            # helpers shared by several sites
        for site in extract_sites(module):
            for f in self._body_dot_findings(module, site):
                key = (f.line, f.col)
                if key not in seen:
                    seen.add(key)
                    yield f
            yield from self._half_scratch_findings(module, site)
