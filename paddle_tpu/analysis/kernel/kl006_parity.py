"""KL006 — interpret-parity test coverage for public kernel entry
points.

The container has no TPU, so the interpret lane is the ONLY place a
Pallas kernel's numerics are ever executed before hardware (ROADMAP
item 2 remainder).  A public kernel entry point that no tier-1 test
references is therefore completely unvalidated code — exactly the
state ``quant_linear.weight_only_matmul_int4`` shipped in
(referenced only by the TPU-hardware and Mosaic-cross-lowering lanes,
both skipped in this container) until the ISSUE 10 parity tests.

The rule: every ``__all__`` name of an ``ops/pallas`` kernel module
that is bound to a function must appear (as a word) somewhere under
``tests/`` — excluding the hardware/lowering lanes, which prove
nothing on the interpret tier.
"""

from __future__ import annotations

import ast
import os
import re

from .. import core

_SKIP_MODULES = ("autotune.py", "common.py", "__init__.py")
# lanes that skip off-TPU: a reference there is not interpret coverage
_EXCLUDED_TEST_FILES = ("test_pallas_hw.py", "test_pallas_tpu_lowering.py")


def _module_all(module: core.Module):
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and node.targets[0].id == "__all__" \
                and isinstance(node.value, (ast.Tuple, ast.List)):
            return node, [e.value for e in node.value.elts
                          if isinstance(e, ast.Constant)
                          and isinstance(e.value, str)]
    return None, []


@core.register
class InterpretParityRule(core.Rule):
    id = "KL006"
    name = "interpret-parity-gap"
    severity = "warning"
    doc = ("a public ops/pallas kernel entry point (__all__ function) "
           "is referenced by no tests/ module outside the "
           "hardware/lowering lanes — its numerics never execute in "
           "this container")
    hint = ("add an interpret-tier parity test vs a dense reference "
            "(fp32/bf16 tolerance tiers, see tests/test_fused_head.py "
            "TestPallasTier), or demote the name from __all__")

    def __init__(self):
        self._corpus = None

    def prepare(self, modules):
        tests_dir = os.path.join(core.repo_root(), "tests")
        chunks = []
        if os.path.isdir(tests_dir):
            for root, dirs, names in os.walk(tests_dir):
                # *_fixtures trees are analyzed, never run — a name
                # there is not coverage (and the KL006 fixtures would
                # otherwise self-reference)
                dirs[:] = [d for d in dirs if d != "__pycache__"
                           and not d.endswith("_fixtures")]
                for n in sorted(names):
                    if n.endswith(".py") and n not in _EXCLUDED_TEST_FILES:
                        try:
                            with open(os.path.join(root, n),
                                      encoding="utf-8") as f:
                                chunks.append(f.read())
                        except OSError:
                            pass
        self._corpus = "\n".join(chunks)

    def check(self, module):
        rel = module.rel
        if "ops/pallas/" not in rel or rel.endswith(_SKIP_MODULES):
            return
        all_node, names = _module_all(module)
        if not names or self._corpus is None:
            return
        for name in names:
            fn = module.functions.get(name)
            if fn is None:          # constants/re-exports: not entry points
                continue
            if not re.search(rf"\b{re.escape(name)}\b", self._corpus):
                yield self.finding(
                    module, fn,
                    f"public kernel entry point `{name}` has no "
                    "interpret-tier tests/ reference — unvalidated in "
                    "this container")
