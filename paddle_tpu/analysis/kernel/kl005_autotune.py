"""KL005 — autotune coverage drift.

The autotune registry (``ops/pallas/autotune``) is the ONLY channel
through which tunable kernel configs reach traced code: ``pick`` times
candidates eagerly at warmup, ``lookup`` reads the cached winner at
trace time.  Two drift modes have bitten similar stacks:

* a module grows a ``*_CANDIDATES`` tuple but never registers it —
  the knob silently stays at its default forever and the sweep code
  rots unexercised;
* the ``pick`` and ``lookup`` key strings drift apart (tuner writes
  under one name, trace-time reads another) — every traced call
  silently gets the default while the tuned winner sits unused in the
  cache.

The cost-model half of autotune hygiene ("a candidate that can never
fit") is enforced at RUNTIME, where the true shapes exist: candidate
lists are filtered through ``analysis/kernel/cost.py`` before timing
(``decode_block._fitting_candidates``, ``linear_ce._tuned_blocks``)
and ``pick(valid=...)`` refuses provably-overflowing configs instead
of burning a compile to discover them.
"""

from __future__ import annotations

import ast
import re

from .. import core

_CANDIDATES_RE = re.compile(r"^_?[A-Z0-9_]*CANDIDATES$")
_REGISTRY_CALLS = {"pick", "lookup"}


def _key_literal(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant) \
            and isinstance(call.args[0].value, str):
        return call.args[0].value
    return None


@core.register
class AutotuneCoverageRule(core.Rule):
    id = "KL005"
    name = "autotune-coverage-drift"
    severity = "warning"
    doc = ("a *_CANDIDATES tuple exists with no ops/pallas/autotune "
           "pick/lookup registration in the module, or the module's "
           "pick and lookup key strings disagree")
    hint = ("register the knob: pick(\"<key>\", ...) at warmup, "
            "lookup(\"<key>\", ...) at trace time, one key string per "
            "kernel; dead candidate tuples should be deleted")

    def check(self, module):
        cand_nodes = []
        pick_keys, lookup_keys = set(), set()
        has_registry_call = False
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and _CANDIDATES_RE.match(node.targets[0].id) \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                cand_nodes.append(node)
            elif isinstance(node, ast.Call) \
                    and core.tail_name(node.func) in _REGISTRY_CALLS:
                has_registry_call = True
                key = _key_literal(node)
                if key is not None:
                    (pick_keys if core.tail_name(node.func) == "pick"
                     else lookup_keys).add(key)
        if not has_registry_call:
            for node in cand_nodes:
                yield self.finding(
                    module, node,
                    f"candidates tuple `{node.targets[0].id}` is not "
                    "registered with ops/pallas/autotune (no "
                    "pick/lookup call in this module) — the knob can "
                    "never leave its default")
        if pick_keys and lookup_keys and pick_keys != lookup_keys:
            missing = sorted(pick_keys ^ lookup_keys)
            anchor = next(
                (n for n in ast.walk(module.tree)
                 if isinstance(n, ast.Call)
                 and core.tail_name(n.func) in _REGISTRY_CALLS
                 and _key_literal(n) in missing), module.tree)
            yield self.finding(
                module, anchor,
                f"autotune key drift: pick registers {sorted(pick_keys)} "
                f"but lookup reads {sorted(lookup_keys)} — the traced "
                "path would silently use defaults")
