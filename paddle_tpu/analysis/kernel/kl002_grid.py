"""KL002 — grid/BlockSpec structural consistency.

Three checks, all exact (no bounds involved):

* an index-map lambda whose arity differs from the grid rank — Pallas
  calls index maps with one argument per grid axis, so this fails at
  trace time on TPU but can silently "work" in hand-rolled interpret
  shims;
* an index map returning a coordinate tuple whose length differs from
  the block rank — the classic copy-paste bug when a block gains a
  dimension;
* ``pl.program_id(axis)`` with a constant axis outside the grid rank
  reachable from the kernel body.

Divisibility of array extents by block shapes is deliberately NOT a
static check here: every host wrapper in this repo pads to a block
multiple or derives the grid from the padded extent, and the
edge-masking discipline for ceil-divided grids is KL003's job.
"""

from __future__ import annotations

import ast

from .. import core
from .extract import extract_sites, kernel_closure


@core.register
class GridBlockRule(core.Rule):
    id = "KL002"
    name = "grid-blockspec-mismatch"
    severity = "error"
    doc = ("a BlockSpec index map's arity or returned rank disagrees "
           "with the pallas_call grid/block rank, or the kernel reads "
           "pl.program_id(axis) past the grid rank")
    hint = ("index maps take one arg per grid axis and return one "
            "coordinate per block dim; program_id axes are "
            "0..grid_rank-1")

    def _spec_findings(self, module, site, spec, role):
        if not spec.known:
            return
        if spec.index_map_arity is not None \
                and site.grid_rank is not None \
                and spec.index_map_arity != site.grid_rank:
            yield self.finding(
                module, spec.node,
                f"{role} index map takes {spec.index_map_arity} "
                f"arg(s) but the grid has rank {site.grid_rank}")
        if spec.index_map_rank is not None \
                and spec.shape_len is not None \
                and spec.index_map_rank != spec.shape_len:
            yield self.finding(
                module, spec.node,
                f"{role} index map returns {spec.index_map_rank} "
                f"coordinate(s) for a rank-{spec.shape_len} block")

    def check(self, module):
        for site in extract_sites(module):
            for spec in site.in_specs:
                yield from self._spec_findings(module, site, spec,
                                               "in_spec")
            for spec in site.out_specs:
                yield from self._spec_findings(module, site, spec,
                                               "out_spec")
            if site.grid_rank is None:
                continue
            for fn in kernel_closure(site):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) \
                            and core.tail_name(node.func) == "program_id" \
                            and node.args \
                            and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, int) \
                            and node.args[0].value >= site.grid_rank:
                        yield self.finding(
                            module, node,
                            f"pl.program_id({node.args[0].value}) in "
                            f"kernel `{site.kernel_name}` but the grid "
                            f"at line {site.lineno} has rank "
                            f"{site.grid_rank}")
