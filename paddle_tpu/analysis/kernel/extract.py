"""Static model of ``pl.pallas_call`` sites (the KL rules' substrate).

For every call whose callee tail is ``pallas_call`` the extractor
records, per site: the kernel function (resolved through
``functools.partial``), the grid rank and element expressions, every
in/out ``BlockSpec`` (block shape, memory space, index-map arity and
returned rank), the ``scratch_shapes`` entries (kind/shape/dtype), and
the ``out_shape`` dtypes.

Shape expressions are resolved with a *sound constant evaluator*: only
module-level constants and single-assignment locals of the enclosing
function fold (plus ``min``/``max``/``len``/arithmetic/``pl.cdiv`` over
folded values).  Anything runtime-dependent stays ``None`` — the rules
treat ``None`` dims as "cannot prove", never as a guess, so a KL001
overflow finding is a proof, not a heuristic.  (Function parameter
*defaults* are deliberately NOT folded: a caller can override them.)
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from .. import core

__all__ = ["BlockSpecInfo", "ScratchInfo", "PallasSite", "extract_sites",
           "kernel_closure", "ConstEnv"]

_MAX_FOLD_DEPTH = 32


class ConstEnv:
    """Lazy constant-folding environment: module-level assignments plus
    the enclosing function's single-assignment locals.  Names assigned
    more than once (or augmented) are ambiguous and never fold."""

    def __init__(self, module: core.Module,
                 func: Optional[ast.AST] = None):
        self._exprs: Dict[str, Optional[ast.AST]] = {}
        self._memo: Dict[str, Optional[object]] = {}
        self._collect(module.tree, top_only=True)
        if func is not None:
            self._collect(func, top_only=False)

    def _collect(self, root: ast.AST, top_only: bool) -> None:
        body = root.body if top_only else list(ast.walk(root))
        for node in body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                # second sighting -> ambiguous
                self._exprs[name] = (None if name in self._exprs
                                     else node.value)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Tuple) \
                    and isinstance(node.value, ast.Tuple) \
                    and len(node.targets[0].elts) == len(node.value.elts):
                # `a, b = x, y` — positional unpack of a literal tuple
                for tgt, val in zip(node.targets[0].elts,
                                    node.value.elts):
                    if isinstance(tgt, ast.Name):
                        self._exprs[tgt.id] = (None if tgt.id
                                               in self._exprs else val)
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) \
                    and isinstance(getattr(node, "target", None), ast.Name):
                self._exprs[node.target.id] = None
            elif isinstance(node, ast.For) \
                    and isinstance(node.target, ast.Name):
                self._exprs[node.target.id] = None

    def expr_of(self, name: str) -> Optional[ast.AST]:
        """The defining expression of a single-assignment name (None
        when unknown or ambiguous) — lets structural checks look
        through one level of naming (``nt = -(-mb // pages)``)."""
        return self._exprs.get(name)

    def lookup(self, name: str, depth: int = 0):
        if name in self._memo:
            return self._memo[name]
        self._memo[name] = None            # cycle guard
        expr = self._exprs.get(name)
        if expr is not None:
            self._memo[name] = self.fold(expr, depth + 1)
        return self._memo[name]

    def fold(self, node: ast.AST, depth: int = 0):
        """Fold an expression to an int / tuple of folded values, or
        ``None`` when it cannot be proven constant."""
        if depth > _MAX_FOLD_DEPTH:
            return None
        if isinstance(node, ast.Constant):
            v = node.value
            return v if isinstance(v, (int, float)) or v is None else None
        if isinstance(node, ast.Name):
            return self.lookup(node.id, depth)
        if isinstance(node, (ast.Tuple, ast.List)):
            return tuple(self.fold(e, depth + 1) for e in node.elts)
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self.fold(node.operand, depth + 1)
            return -v if isinstance(v, (int, float)) else None
        if isinstance(node, ast.BinOp):
            a = self.fold(node.left, depth + 1)
            b = self.fold(node.right, depth + 1)
            if not isinstance(a, (int, float)) \
                    or not isinstance(b, (int, float)):
                return None
            try:
                if isinstance(node.op, ast.Add):
                    return a + b
                if isinstance(node.op, ast.Sub):
                    return a - b
                if isinstance(node.op, ast.Mult):
                    return a * b
                if isinstance(node.op, ast.FloorDiv):
                    return a // b
                if isinstance(node.op, ast.Mod):
                    return a % b
                if isinstance(node.op, ast.Pow):
                    return a ** b if abs(b) < 64 else None
            except (ZeroDivisionError, OverflowError):
                return None
            return None
        if isinstance(node, ast.Call):
            tail = core.tail_name(node.func)
            args = [self.fold(a, depth + 1) for a in node.args]
            nums = [a for a in args if isinstance(a, (int, float))]
            if tail in ("min", "max") and args and len(nums) == len(args):
                return (min if tail == "min" else max)(nums)
            if tail == "len" and len(args) == 1 \
                    and isinstance(args[0], tuple):
                return len(args[0])
            if tail == "cdiv" and len(nums) == 2 and nums[1]:
                return -(-int(nums[0]) // int(nums[1]))
            return None
        if isinstance(node, ast.Subscript):
            base = self.fold(node.value, depth + 1)
            idx = self.fold(node.slice, depth + 1)
            if isinstance(base, tuple) and isinstance(idx, int) \
                    and -len(base) <= idx < len(base):
                return base[idx]
            return None
        return None


@dataclasses.dataclass
class BlockSpecInfo:
    node: ast.AST                          # anchor for findings
    known: bool                            # parsed a BlockSpec call
    shape: Optional[Tuple] = None          # folded dims (None entries =
    #                                        unproven or squeezed-dim)
    shape_len: Optional[int] = None        # syntactic rank of the tuple
    memory_space: str = "vmem"             # vmem | smem | any | unknown
    index_map_arity: Optional[int] = None
    index_map_rank: Optional[int] = None   # len of the returned tuple

    @property
    def resolved_shape(self) -> Optional[Tuple[Optional[int], ...]]:
        if not self.known or self.shape is None:
            return None
        return tuple(d if isinstance(d, int) or d is None else None
                     for d in self.shape)


@dataclasses.dataclass
class ScratchInfo:
    node: ast.AST
    kind: str                              # vmem | smem | sem | unknown
    shape: Optional[Tuple] = None
    dtype: Optional[str] = None


@dataclasses.dataclass
class PallasSite:
    module: core.Module
    call: ast.Call
    kernel_name: Optional[str]
    kernel_fn: Optional[ast.AST]
    grid_rank: Optional[int]
    grid_elems: List[ast.AST]
    grid_has_cdiv: bool
    in_specs: List[BlockSpecInfo]
    in_specs_complete: bool                # no Starred / dynamic entries
    out_specs: List[BlockSpecInfo]
    out_specs_complete: bool
    out_dtypes: List[Optional[str]]
    scratch: List[ScratchInfo]
    scratch_complete: bool
    env: ConstEnv

    @property
    def lineno(self) -> int:
        return self.call.lineno


_DTYPE_TAILS = {
    "float32", "float64", "float16", "bfloat16", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
    "float8_e4m3fn", "float8_e5m2", "float8_e4m3",
}


def _dtype_str(node: ast.AST) -> Optional[str]:
    """'float32' for jnp.float32-style references, None for runtime
    dtypes (``x.dtype``)."""
    tail = core.tail_name(node)
    if tail in _DTYPE_TAILS:
        return "bool" if tail == "bool_" else tail
    return None


def _parse_blockspec(node: ast.AST, env: ConstEnv) -> BlockSpecInfo:
    if not (isinstance(node, ast.Call)
            and core.tail_name(node.func) == "BlockSpec"):
        return BlockSpecInfo(node=node, known=False)
    info = BlockSpecInfo(node=node, known=True)
    shape_node = node.args[0] if node.args else None
    index_map = node.args[1] if len(node.args) > 1 else None
    for kw in node.keywords:
        if kw.arg == "block_shape":
            shape_node = kw.value
        elif kw.arg == "index_map":
            index_map = kw.value
        elif kw.arg == "memory_space":
            tail = core.tail_name(kw.value).lower()
            info.memory_space = tail if tail in ("smem", "any", "vmem") \
                else "unknown"
    if shape_node is not None:
        if isinstance(shape_node, (ast.Tuple, ast.List)):
            info.shape_len = len(shape_node.elts)
            info.shape = tuple(env.fold(e) for e in shape_node.elts)
        else:
            folded = env.fold(shape_node)
            if isinstance(folded, tuple):
                info.shape_len = len(folded)
                info.shape = folded
    if isinstance(index_map, ast.Lambda):
        a = index_map.args
        info.index_map_arity = len(a.args) + len(a.posonlyargs)
        if isinstance(index_map.body, (ast.Tuple, ast.List)):
            info.index_map_rank = len(index_map.body.elts)
    return info


def _parse_spec_list(node: Optional[ast.AST], env: ConstEnv
                     ) -> Tuple[List[BlockSpecInfo], bool]:
    """(specs, complete): ``complete`` is False when the list carries a
    Starred / comprehension element, so positional arity is unknown."""
    if node is None:
        return [], False
    if isinstance(node, (ast.Tuple, ast.List)):
        specs, complete = [], True
        for e in node.elts:
            if isinstance(e, ast.Starred):
                complete = False
                continue
            specs.append(_parse_blockspec(e, env))
        return specs, complete
    if isinstance(node, ast.Call):           # single BlockSpec
        return [_parse_blockspec(node, env)], True
    return [], False


def _parse_scratch(node: Optional[ast.AST], env: ConstEnv
                   ) -> Tuple[List[ScratchInfo], bool]:
    if node is None:
        return [], True
    # `[pltpu.VMEM(...)] * 4` folds structurally
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
        for lst, n in ((node.left, node.right), (node.right, node.left)):
            if isinstance(lst, (ast.Tuple, ast.List)):
                reps = env.fold(n)
                if isinstance(reps, int) and 0 <= reps <= 64:
                    inner, complete = _parse_scratch(lst, env)
                    return inner * reps, complete
        return [], False
    if not isinstance(node, (ast.Tuple, ast.List)):
        return [], False
    out, complete = [], True
    for e in node.elts:
        if isinstance(e, ast.Starred):
            complete = False
            continue
        if isinstance(e, ast.Call):
            tail = core.tail_name(e.func)
            dotted = core.dotted_name(e.func)
            if tail in ("VMEM", "SMEM"):
                shape = env.fold(e.args[0]) if e.args else None
                dtype = _dtype_str(e.args[1]) if len(e.args) > 1 else None
                out.append(ScratchInfo(
                    node=e, kind=tail.lower(),
                    shape=shape if isinstance(shape, tuple) else None,
                    dtype=dtype))
                continue
            if tail == "DMA" or "SemaphoreType" in dotted:
                out.append(ScratchInfo(node=e, kind="sem"))
                continue
        out.append(ScratchInfo(node=e, kind="unknown"))
        complete = False
    return out, complete


def _kernel_ref(node: Optional[ast.AST]) -> Optional[str]:
    """Kernel function name from the first pallas_call argument,
    through ``functools.partial``."""
    if node is None:
        return None
    if isinstance(node, ast.Call) and core.tail_name(node.func) == "partial":
        return _kernel_ref(node.args[0]) if node.args else None
    name = core.tail_name(node)
    return name or None


_CDIV_TAILS = ("cdiv",)


def _is_cdiv(node: ast.AST, env: ConstEnv, depth: int = 0) -> bool:
    """`pl.cdiv(a, b)` or the `-(-a // b)` idiom — looked up through
    single-assignment names — with an unprovable quotient (a
    provably-dividing grid is not an edge hazard)."""
    if depth > 8 or node is None:
        return False
    if isinstance(node, ast.Name):
        return _is_cdiv(env.expr_of(node.id), env, depth + 1)
    if isinstance(node, ast.Call) \
            and core.tail_name(node.func) in _CDIV_TAILS:
        return env.fold(node) is None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub) \
            and isinstance(node.operand, ast.BinOp) \
            and isinstance(node.operand.op, ast.FloorDiv) \
            and isinstance(node.operand.left, ast.UnaryOp) \
            and isinstance(node.operand.left.op, ast.USub):
        return env.fold(node) is None
    return False


def _out_dtypes(node: Optional[ast.AST], env: ConstEnv
                ) -> List[Optional[str]]:
    if node is None:
        return []
    elts = node.elts if isinstance(node, (ast.Tuple, ast.List)) else [node]
    out: List[Optional[str]] = []
    for e in elts:
        if isinstance(e, ast.Call) \
                and core.tail_name(e.func) == "ShapeDtypeStruct" \
                and len(e.args) > 1:
            out.append(_dtype_str(e.args[1]))
        else:
            out.append(None)
    return out


def extract_sites(module: core.Module) -> List[PallasSite]:
    """All pallas_call sites in a module (cached on the Module)."""
    cached = getattr(module, "_pallas_sites", None)
    if cached is not None:
        return cached

    # enclosing function map
    enclosing: Dict[ast.AST, ast.AST] = {}
    for fn in ast.walk(module.tree):
        if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for sub in ast.walk(fn):
                enclosing.setdefault(sub, fn)

    sites: List[PallasSite] = []
    for node in ast.walk(module.tree):
        if not (isinstance(node, ast.Call)
                and core.tail_name(node.func) == "pallas_call"):
            continue
        env = ConstEnv(module, enclosing.get(node))

        def deref(v, _env=env):
            """kwargs are often locals (`in_specs=in_specs`): follow
            one level of single-assignment naming."""
            seen = 0
            while isinstance(v, ast.Name) and seen < 8:
                nxt = _env.expr_of(v.id)
                if nxt is None:
                    return v
                v, seen = nxt, seen + 1
            return v

        kw = {k.arg: deref(k.value) for k in node.keywords if k.arg}
        grid = kw.get("grid")
        grid_elems = list(grid.elts) if isinstance(
            grid, (ast.Tuple, ast.List)) else ([grid] if grid else [])
        grid_rank = len(grid_elems) if grid_elems else None
        in_specs, in_complete = _parse_spec_list(kw.get("in_specs"), env)
        out_specs, out_complete = _parse_spec_list(kw.get("out_specs"), env)
        scratch, scratch_complete = _parse_scratch(
            kw.get("scratch_shapes"), env)
        kname = _kernel_ref(node.args[0] if node.args else None)
        sites.append(PallasSite(
            module=module, call=node, kernel_name=kname,
            kernel_fn=module.functions.get(kname) if kname else None,
            grid_rank=grid_rank, grid_elems=grid_elems,
            grid_has_cdiv=any(_is_cdiv(g, env) for g in grid_elems),
            in_specs=in_specs, in_specs_complete=in_complete,
            out_specs=out_specs, out_specs_complete=out_complete,
            out_dtypes=_out_dtypes(kw.get("out_shape"), env),
            scratch=scratch, scratch_complete=scratch_complete,
            env=env))
    module._pallas_sites = sites
    return sites


def kernel_closure(site: PallasSite) -> List[ast.AST]:
    """The kernel function plus every module-local function it
    transitively calls by bare name — the body the KL003/KL004 body
    checks scan."""
    if site.kernel_fn is None:
        return []
    mod = site.module
    seen = {site.kernel_name}
    out = [site.kernel_fn]
    frontier = [site.kernel_fn]
    while frontier:
        fn = frontier.pop()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Name) \
                    and node.func.id in mod.functions \
                    and node.func.id not in seen:
                seen.add(node.func.id)
                callee = mod.functions[node.func.id]
                out.append(callee)
                frontier.append(callee)
    return out
