"""The shared VMEM cost model (KL001) — static analysis AND runtime.

Everything that needs to know whether a Pallas working set fits on-chip
reads THIS module:

* the KL001 rule checks statically-extracted block/scratch shapes
  against :func:`budget_bytes`;
* ``ops/pallas/decode_block.py``'s fusion-fallback gate
  (``unsupported_reason`` → ``DecodeBlockUnsupportedError``) computes
  its working set with :func:`decode_block_vmem`;
* ``ops/pallas``'s autotune candidate filters
  (``decode_block._fitting_candidates``, ``linear_ce._tuned_blocks``)
  drop configs :func:`fits` rejects before ever timing them.

Before ISSUE 10 the budget lived as a hand-maintained
``VMEM_BUDGET_BYTES = 12MB`` constant inside the decode-block kernel
plus an ad-hoc try/except skip in the autotuner; the static analyzer
could not see either.  Now there is one table and one estimator, so the
number the lint proves things about is the number the serving dispatch
enforces.

The byte model is the sum of per-grid-step VMEM residents: one block
per (in_spec, out_spec) with a block shape (``None`` dims count 1;
``SMEM``/``ANY`` specs don't occupy VMEM) plus every ``pltpu.VMEM``
scratch entry.  It deliberately does NOT model Mosaic's (8, 128) tile
padding or double-buffering of streamed blocks — both round UP, so the
documented contract is: the estimate is within ``MODEL_TOLERANCE`` of
the kernel's declared allocation (pinned by tests/test_kernel_cost.py
against interpret-mode-captured block+scratch bytes), and the safety
margin for padding/double-buffering lives in ``SAFETY_FRACTION``.

No jax imports: the analyzer and the CI ratchet run this on a bare
interpreter; runtime callers pass plain ints and dtype strings.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "VMEM_BYTES_PER_CORE", "SAFETY_FRACTION", "DEFAULT_GENERATION",
    "MAX_HEAD_DIM", "MODEL_TOLERANCE", "DMA_STAGING_SLOTS",
    "budget_bytes", "fits",
    "generation_from_device_kind", "itemsize", "Buffer", "vmem_bytes",
    "decode_block_vmem", "decode_block_weight_bytes",
    "decode_block_unsupported_reason",
    "prefill_block_vmem", "prefill_block_unsupported_reason",
    "linear_ce_vmem", "linear_ce_fits",
]

# Physical per-core VMEM by TPU generation (the Pallas guide's ~16 MB
# figure for v4/v5; v6e doubles it).  "interpret" is the CPU tier-1
# lane: budgeted like v4 so the dispatch decisions tier-1 pins are the
# ones real hardware makes.
VMEM_BYTES_PER_CORE: Dict[str, int] = {
    "v4": 16 * 2 ** 20,
    "v5e": 16 * 2 ** 20,
    "v5p": 16 * 2 ** 20,
    "v6e": 32 * 2 ** 20,
    "interpret": 16 * 2 ** 20,
}

# Fraction of physical VMEM a single kernel's declared working set may
# claim.  The remainder absorbs what the closed form does not model:
# Mosaic (8, 128) tile padding, pipeline double-buffering of streamed
# blocks, and compiler-internal temporaries.  0.75 * 16 MB reproduces
# the pre-ISSUE-10 hand constant (12 MB) exactly.
SAFETY_FRACTION = 0.75

DEFAULT_GENERATION = "v4"

# Attention-scratch layout cap carried over from the decode-block
# kernel (one (head, D) row must fit a VMEM register tile fan-out).
MAX_HEAD_DIM = 256

# Documented tolerance for static-estimate vs kernel-declared bytes
# (tests/test_kernel_cost.py pins decode_block and linear_ce to it).
MODEL_TOLERANCE = 0.02

_ITEMSIZE = {
    "float64": 8, "int64": 8, "uint64": 8, "complex64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "bfloat16": 2, "float16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool": 1,
    "float8_e4m3fn": 1, "float8_e5m2": 1, "float8_e4m3": 1,
}


def itemsize(dtype) -> int:
    """Bytes per element for a dtype given as string or anything whose
    ``str()`` names one ("bfloat16", ``jnp.float32``, ``np.dtype``)."""
    s = str(dtype)
    s = s.rsplit(".", 1)[-1].strip("'\"<>")   # "<class 'jax...bfloat16'>"
    if s in _ITEMSIZE:
        return _ITEMSIZE[s]
    for name, n in _ITEMSIZE.items():
        if name in s:
            return n
    raise ValueError(f"unknown dtype {dtype!r} for itemsize")


def generation_from_device_kind(kind: str) -> str:
    """Map a jax ``device_kind`` string to a budget-table key; unknown
    kinds get the conservative default generation."""
    k = kind.lower()
    for gen in ("v6e", "v5p", "v5e", "v4"):
        if gen in k:
            return gen
    return DEFAULT_GENERATION


def budget_bytes(generation: Optional[str] = None) -> int:
    """Usable single-kernel VMEM budget for a generation (the ONE
    number every fusion/validity decision compares against)."""
    gen = generation or DEFAULT_GENERATION
    if gen not in VMEM_BYTES_PER_CORE:
        raise KeyError(f"unknown TPU generation {gen!r}; have "
                       f"{sorted(VMEM_BYTES_PER_CORE)}")
    return int(VMEM_BYTES_PER_CORE[gen] * SAFETY_FRACTION)


def fits(total_bytes: int, generation: Optional[str] = None) -> bool:
    return total_bytes <= budget_bytes(generation)


@dataclasses.dataclass(frozen=True)
class Buffer:
    """One VMEM-resident buffer: a per-grid-step block or a scratch
    allocation.  ``None`` dims (Pallas squeezed block dims) count 1."""
    name: str
    shape: Tuple[Optional[int], ...]
    itemsize: int

    @property
    def bytes(self) -> int:
        n = 1
        for d in self.shape:
            n *= 1 if d is None else int(d)
        return n * self.itemsize


def vmem_bytes(buffers: Iterable[Buffer]) -> int:
    """Total declared VMEM of a kernel invocation: per-grid-step input/
    output blocks plus scratch accumulators/staging."""
    return sum(b.bytes for b in buffers)


# ---------------------------------------------------------------------------
# decode_block / prefill_block: the fused block megakernels (ops/pallas)
# ---------------------------------------------------------------------------
# Both block kernels stage KV pages through a revolving two-slot buffer
# (start the NEXT page-chunk's DMA while the current one accumulates),
# so the declared staging allocation is 2x the per-chunk footprint.
DMA_STAGING_SLOTS = 2


def _page_staging_bytes(pages: int, block_size: int, kv_heads: int,
                        head_dim: int, pool_itemsize: int,
                        kv_quant: bool) -> int:
    """Declared bytes of the double-buffered page staging tier: k + v
    data pages per slot, plus per-(token, head) fp32 scale rows when the
    pool is quantized (ops/paged_kv.QuantizedKVPool layout)."""
    per_chunk = 2 * pages * block_size * kv_heads * head_dim * pool_itemsize
    if kv_quant:
        per_chunk += 2 * pages * block_size * kv_heads * 4
    return DMA_STAGING_SLOTS * per_chunk


def decode_block_vmem(*, hidden: int, num_heads: int, kv_heads: int,
                      head_dim: int, block_size: int, pages: int,
                      weight_bytes: int, pool_itemsize: int,
                      x_itemsize: int = 4,
                      kv_quant: bool = False) -> Dict[str, int]:
    """Byte breakdown of one decode_block kernel invocation.

    Mirrors ``ops/pallas/decode_block._call`` exactly: the layer's full
    weight set streams into VMEM as whole-array blocks
    (``weight_bytes``), ``pages`` KV pages stage per attention chunk
    (k + v, two revolving DMA slots so the next chunk's copy overlaps
    the current chunk's accumulation), the online-softmax state is fp32
    scratch, and the residual stream/RoPE rows/outputs are one-row
    blocks.  Keys: ``weights``, ``staging``, ``scratch``, ``io``,
    ``total``.

    With ``kv_quant`` the pool is int8 data plus per-(token, head) fp32
    scales: the staging tier gains a scale row per page (k + v) and the
    kernel emits fp32 ``k_new``/``v_new`` (the host quantizes on
    append), so ``pool_itemsize`` must be 1 and the new-KV io rows are
    fp32.
    """
    Hq, Hkv, D, BS = num_heads, kv_heads, head_dim, block_size
    staging = _page_staging_bytes(pages, BS, Hkv, D, pool_itemsize,
                                  kv_quant)
    # fp32 scratch: q (Hq, D) + acc (Hq, D) + new k/v (2 * Hkv * D)
    # + running max/sum (2 * Hq)
    scratch = 4 * (2 * Hq * D + 2 * Hkv * D + 2 * Hq)
    new_kv_itemsize = 4 if kv_quant else pool_itemsize
    io = vmem_bytes([
        Buffer("x", (1, hidden), x_itemsize),
        Buffer("cos", (1, D), x_itemsize),
        Buffer("sin", (1, D), x_itemsize),
        Buffer("x_out", (1, hidden), x_itemsize),
        Buffer("k_new", (1, Hkv, D), new_kv_itemsize),
        Buffer("v_new", (1, Hkv, D), new_kv_itemsize),
    ])
    total = weight_bytes + staging + scratch + io
    return {"weights": weight_bytes, "staging": staging,
            "scratch": scratch, "io": io, "total": total}


def _quantized_matmul_bytes(k: int, n: int, weight_dtype: Optional[str],
                            group_size: int, itemsize_: int) -> int:
    """Stored bytes of one (K, N) matmul weight under weight-only
    quantization — the ``nn.quant.weight_quantize`` layout: int8 keeps
    K*N one-byte codes, int4 packs two codes per byte along K (halves
    packing, ceil(K/2) rows), and every matmul carries fp32 scales —
    one per output channel (``group_size == -1``) or one per
    (K-group, channel)."""
    if weight_dtype is None:
        return k * n * itemsize_
    groups = 1 if group_size in (-1, None, 0) else -(-k // int(group_size))
    scale = groups * n * 4
    if weight_dtype == "int8":
        return k * n + scale
    if weight_dtype == "int4":
        return -(-k // 2) * n + scale
    raise ValueError(f"unknown weight_dtype {weight_dtype!r} "
                     "(want None, 'int8' or 'int4')")


def decode_block_weight_bytes(*, hidden: int, num_heads: int,
                              kv_heads: int, head_dim: int,
                              ffn_hidden: int, arch: str = "llama",
                              fused_qkv: bool = False, bias: bool = False,
                              weight_dtype: Optional[str] = None,
                              group_size: int = -1,
                              itemsize_: int = 4) -> int:
    """Closed-form bytes of one decode-block layer's weight set, with
    optional weight-only quantization — the static side of the fusion
    envelope proof (``decode_block_unsupported_reason`` admits widths
    under int8/int4 that fall back at full width).

    Matmul weights quantize (int8: 1 B/code; int4: packed halves,
    ceil(K/2) rows; + fp32 scales per channel or per (group, channel));
    norm weights and biases stay at ``itemsize_`` — exactly what
    ``quantization.serve.quantize_params_for_serving`` produces.
    """
    H, Hq, Hkv, D, F = hidden, num_heads, kv_heads, head_dim, ffn_hidden

    def mm(k, n):
        return _quantized_matmul_bytes(k, n, weight_dtype, group_size,
                                       itemsize_)

    if fused_qkv:
        qkv = mm(H, (Hq + 2 * Hkv) * D)
    else:
        qkv = mm(H, Hq * D) + 2 * mm(H, Hkv * D)
    total = qkv + mm(Hq * D, H)
    if arch == "llama":
        total += 2 * mm(H, F) + mm(F, H)          # gate, up, down
        total += 2 * H * itemsize_                # ln1_w, ln2_w
    elif arch == "gpt":
        total += mm(H, F) + mm(F, H)              # fc, proj
        total += 2 * H * itemsize_                # ln1_w, ln2_w
    else:
        raise ValueError(f"unknown arch {arch!r}")
    if bias:
        # qkv + o + fc/proj (+ up/gate-less llama has no bias path, but
        # the spec permits it symmetrically) and the layernorm biases
        nb = (Hq + 2 * Hkv) * D + H + F + H + 2 * H
        total += nb * itemsize_
    return total


def decode_block_unsupported_reason(
        *, hidden: int, num_heads: int, kv_heads: int, head_dim: int,
        block_size: int, rope: bool, weight_bytes: int,
        pool_itemsize: int, x_itemsize: int = 4,
        kv_quant: bool = False,
        budget: Optional[int] = None,
        generation: Optional[str] = None) -> Optional[str]:
    """None when one decode_block layer fits the kernel's limits, else
    a human-readable reason — the runtime fusion-fallback signal
    (``DecodeBlockUnsupportedError`` when the kernel is forced) and the
    KL001 ground truth, from one formula."""
    D = head_dim
    if D > MAX_HEAD_DIM:
        return f"head_dim {D} exceeds the kernel cap {MAX_HEAD_DIM}"
    if rope and D % 2:
        return f"rotate-half RoPE needs an even head_dim, got {D}"
    limit = budget if budget is not None else budget_bytes(generation)
    est = decode_block_vmem(
        hidden=hidden, num_heads=num_heads, kv_heads=kv_heads,
        head_dim=D, block_size=block_size, pages=1,
        weight_bytes=weight_bytes, pool_itemsize=pool_itemsize,
        x_itemsize=x_itemsize, kv_quant=kv_quant)
    if est["total"] > limit:
        return (f"layer needs ~{est['total'] / 2**20:.1f} MB VMEM "
                f"({est['weights'] / 2**20:.1f} MB weights) > budget "
                f"{limit / 2**20:.1f} MB — multi-core fusion "
                "territory, per-op tier serves it")
    return None


def prefill_block_vmem(*, hidden: int, num_heads: int, kv_heads: int,
                       head_dim: int, block_size: int, pages: int,
                       chunk: int, weight_bytes: int, pool_itemsize: int,
                       x_itemsize: int = 4,
                       kv_quant: bool = False) -> Dict[str, int]:
    """Byte breakdown of one prefill_block kernel invocation — the
    chunked-prefill twin of :func:`decode_block_vmem`.

    Mirrors ``ops/pallas/prefill_block._call``: the same whole-array
    weight blocks and double-buffered page staging as the decode
    kernel, but the resident tile is ``chunk`` prompt tokens instead of
    one — q/new-k/new-v/acc scratch and the io blocks all scale by
    ``chunk``, and the in-chunk causal attention runs over the same
    scratch the epilogue folds.  Keys: ``weights``, ``staging``,
    ``scratch``, ``io``, ``total``.
    """
    Hq, Hkv, D, BS = num_heads, kv_heads, head_dim, block_size
    staging = _page_staging_bytes(pages, BS, Hkv, D, pool_itemsize,
                                  kv_quant)
    # fp32 scratch, all carrying the chunk-tile dim: q (Hq, chunk, D)
    # + acc (Hq, chunk, D) + new k/v (2 * Hkv * chunk * D) + running
    # max/sum (2 * Hq * chunk) — the decode formula times the tile
    scratch = 4 * chunk * (2 * Hq * D + 2 * Hkv * D + 2 * Hq)
    new_kv_itemsize = 4 if kv_quant else pool_itemsize
    io = vmem_bytes([
        Buffer("x", (chunk, hidden), x_itemsize),
        Buffer("cos", (chunk, D), x_itemsize),
        Buffer("sin", (chunk, D), x_itemsize),
        Buffer("x_out", (chunk, hidden), x_itemsize),
        Buffer("k_new", (chunk, Hkv, D), new_kv_itemsize),
        Buffer("v_new", (chunk, Hkv, D), new_kv_itemsize),
    ])
    total = weight_bytes + staging + scratch + io
    return {"weights": weight_bytes, "staging": staging,
            "scratch": scratch, "io": io, "total": total}


def prefill_block_unsupported_reason(
        *, hidden: int, num_heads: int, kv_heads: int, head_dim: int,
        block_size: int, chunk: int, rope: bool, weight_bytes: int,
        pool_itemsize: int, x_itemsize: int = 4,
        kv_quant: bool = False,
        budget: Optional[int] = None,
        generation: Optional[str] = None) -> Optional[str]:
    """None when one prefill_block chunk fits the kernel's limits, else
    a human-readable reason — the runtime fusion-fallback signal
    (``PrefillBlockUnsupportedError`` when the kernel is forced), from
    the same formula the autotune validity filter reads."""
    D = head_dim
    if D > MAX_HEAD_DIM:
        return f"head_dim {D} exceeds the kernel cap {MAX_HEAD_DIM}"
    if rope and D % 2:
        return f"rotate-half RoPE needs an even head_dim, got {D}"
    limit = budget if budget is not None else budget_bytes(generation)
    est = prefill_block_vmem(
        hidden=hidden, num_heads=num_heads, kv_heads=kv_heads,
        head_dim=D, block_size=block_size, pages=1, chunk=chunk,
        weight_bytes=weight_bytes, pool_itemsize=pool_itemsize,
        x_itemsize=x_itemsize, kv_quant=kv_quant)
    if est["total"] > limit:
        return (f"chunk of {chunk} needs ~{est['total'] / 2**20:.1f} MB "
                f"VMEM ({est['weights'] / 2**20:.1f} MB weights) > "
                f"budget {limit / 2**20:.1f} MB — multi-core fusion "
                "territory, per-op tier serves it")
    return None


# ---------------------------------------------------------------------------
# linear_ce: the fused CE head forward kernel (ops/pallas/linear_ce)
# ---------------------------------------------------------------------------
def linear_ce_vmem(*, block_rows: int, chunk: int, hidden: int,
                   x_itemsize: int = 4, w_itemsize: int = 4) -> Dict[str, int]:
    """Byte breakdown of one linear_ce forward invocation per grid
    step, mirroring ``ops/pallas/linear_ce._fwd``: an activation row
    block, a vocab-chunk weight block, the label column, two fp32
    outputs and four fp32 online-softmax scratch columns."""
    br, C, H = block_rows, chunk, hidden
    blocks = vmem_bytes([
        Buffer("x", (br, H), x_itemsize),
        Buffer("w", (C, H), w_itemsize),
        Buffer("labels", (br, 1), 4),
        Buffer("nll", (br, 1), 4),
        Buffer("lse", (br, 1), 4),
    ])
    scratch = 4 * br * 4
    return {"blocks": blocks, "scratch": scratch,
            "total": blocks + scratch}


def linear_ce_fits(block_rows: int, chunk: int, hidden: int,
                   x_itemsize: int = 4, w_itemsize: int = 4,
                   generation: Optional[str] = None) -> bool:
    """Autotune validity: can a (block_rows, chunk) candidate's working
    set ever fit?  ``_tuned_blocks`` filters candidates through this
    BEFORE timing them — a config this rejects would only die inside
    Mosaic on hardware, after burning a compile."""
    return fits(linear_ce_vmem(block_rows=block_rows, chunk=chunk,
                               hidden=hidden, x_itemsize=x_itemsize,
                               w_itemsize=w_itemsize)["total"],
                generation)
