"""TL007 — nondeterministic structure in pytree-building code.

jax flattens dicts in sorted-key order, but a ``set`` iterated to build
a param list (or a mutable default accumulating across calls) produces
a structure that can differ between processes — which shows up as a
cross-host pytree-structure mismatch or a donation plan keyed on the
wrong leaf order, not as a local error.  Flags:

* mutable default arguments (``def f(x=[], y={}, z=set())`` and the
  ``list()/dict()/set()`` call forms) — anywhere;
* ``for``/comprehension iteration directly over a ``set`` literal or
  ``set(...)`` call — unordered iteration.
"""

from __future__ import annotations

import ast

from .. import core


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, ast.Set):
        return True
    return isinstance(node, ast.Call) \
        and isinstance(node.func, ast.Name) and node.func.id == "set"


@core.register
class PytreeOrderRule(core.Rule):
    id = "TL007"
    name = "pytree-order-hazard"
    severity = "warning"
    doc = ("mutable default arguments, and iteration directly over a "
           "set — order differs across processes, so pytree structures "
           "built from it diverge across hosts")
    hint = ("default to None and create inside the function; iterate "
            "`sorted(...)` instead of the raw set")

    def check(self, module):
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defaults = list(node.args.defaults) + [
                    d for d in node.args.kw_defaults if d is not None]
                for d in defaults:
                    if isinstance(d, (ast.List, ast.Dict, ast.Set)) or (
                            isinstance(d, ast.Call)
                            and isinstance(d.func, ast.Name)
                            and d.func.id in ("list", "dict", "set")):
                        yield self.finding(
                            module, d,
                            f"mutable default argument in `{node.name}` "
                            f"— shared across every call",
                            hint="default to None and create inside "
                                 "the function")
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if _is_set_expr(node.iter):
                    yield self.finding(
                        module, node.iter,
                        "iteration over a set — order is "
                        "process-dependent",
                        hint="iterate sorted(...) for a deterministic "
                             "order")
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp)):
                for gen in node.generators:
                    if _is_set_expr(gen.iter):
                        yield self.finding(
                            module, gen.iter,
                            "comprehension over a set — order is "
                            "process-dependent",
                            hint="iterate sorted(...) for a "
                                 "deterministic order")
