"""tracelint rule set — importing this package registers every rule.

Add a rule by dropping a module here that defines a ``core.Rule``
subclass decorated with ``@core.register`` and importing it below
(``docs/static_analysis.md`` walks through the steps).
"""

from . import tl001_host_sync      # noqa: F401
from . import tl002_purity         # noqa: F401
from . import tl003_recompile      # noqa: F401
from . import tl004_donation       # noqa: F401
from . import tl005_collectives    # noqa: F401
from . import tl006_excepts        # noqa: F401
from . import tl007_pytree         # noqa: F401
from . import tl008_notimpl        # noqa: F401
from . import tl009_partition_specs  # noqa: F401

# kernellint (KL) rules live beside their cost model in ../kernel but
# register in the same engine: one CLI, one suppression syntax, one
# ratchet machinery — a separate KERNELLINT.md ledger.
from ..kernel import kl001_vmem        # noqa: F401
from ..kernel import kl002_grid        # noqa: F401
from ..kernel import kl003_masking     # noqa: F401
from ..kernel import kl004_accum       # noqa: F401
from ..kernel import kl005_autotune    # noqa: F401
from ..kernel import kl006_parity      # noqa: F401

# locklint (LK) rules live beside their thread-role model in ../threads;
# same engine, same suppression syntax, a separate LOCKLINT.md ledger.
from ..threads import lk001_shared_state  # noqa: F401
from ..threads import lk002_blocking      # noqa: F401
from ..threads import lk003_lock_order    # noqa: F401
from ..threads import lk004_cv_wait       # noqa: F401
from ..threads import lk005_finalizers    # noqa: F401
from ..threads import lk006_thread_leak   # noqa: F401
