"""TL008 — NotImplementedError stubs (the NOTIMPL ratchet as a rule).

The classification is the one ``tools/notimpl_inventory.py`` has
ratcheted since VERDICT r3 — abstract contracts and documented guards
pass; a function whose whole body is the raise is parity debt and
becomes a finding.  ``analysis.notimpl`` reuses the same classifier to
write NOTIMPL.md, so one walker and one suppression syntax produce
both reports.
"""

from __future__ import annotations

from .. import core
from ..notimpl import classify_module


@core.register
class NotImplStubRule(core.Rule):
    id = "TL008"
    name = "notimpl-stub"
    severity = "info"
    doc = ("a function whose entire body is `raise NotImplementedError` "
           "— a parity name with no behavior behind it")
    hint = ("implement it, or turn it into a documented guard/redirect "
            "(see NOTIMPL.md)")

    def check(self, module):
        for site in classify_module(module):
            if site["kind"] != "stub":
                continue
            yield core.Finding(
                rule=self.id, severity=self.severity, path=module.rel,
                line=site["line"], col=0,
                message=f"`{site['function']}` is a whole-body "
                        f"NotImplementedError stub"
                        + (f" — {site['msg']}" if site["msg"] else ""),
                hint=self.hint)
