"""TL003 — recompile / unbounded-cache hazards.

Generalizes the ADVICE r5 ``_jit_cache`` finding (unbounded dict keyed
on static live-in VALUES → a varying Python scalar recompiles every
call and grows the cache forever; fixed in PR 1 with utils.lru).
Flags:

* ``jit(f)(...)`` — a fresh jit wrapper built and immediately invoked
  inside a function body: a new cache entry (and trace) per call.
* module-level ``*cache*`` dicts that store jit/lower/compile results
  by subscript with no eviction anywhere in the module.
* ``functools.lru_cache(maxsize=None)`` — unbounded by declaration.
"""

from __future__ import annotations

import ast

from .. import core

_JIT_NAMES = {"jit", "jit_compile"}
_CACHED_BUILD_MARKERS = ("jit", "lower", "compile")


@core.register
class RecompileRule(core.Rule):
    id = "TL003"
    name = "recompile-hazard"
    severity = "warning"
    doc = ("patterns that defeat jit caching: per-call jit(f)(...) "
           "invocation, unbounded value-keyed caches of compiled "
           "callables, lru_cache(maxsize=None)")
    hint = ("hoist the jit wrapper out of the hot path, or bound the "
            "cache (utils.lru.LRUCache) and key it on shapes/dtypes, "
            "not scalar values")

    def _module_level_cache_dicts(self, module):
        names = set()
        for node in module.tree.body:
            tgt = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
            elif isinstance(node, ast.AnnAssign):
                tgt, val = node.target, node.value
            else:
                continue
            if isinstance(tgt, ast.Name) and "cache" in tgt.id.lower() \
                    and isinstance(val, ast.Dict) and not val.keys:
                names.add(tgt.id)
        return names

    def check(self, module):
        caches = self._module_level_cache_dicts(module)
        evicted = set()
        if caches:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in ("pop", "popitem") \
                        and isinstance(node.func.value, ast.Name) \
                        and node.func.value.id in caches:
                    evicted.add(node.func.value.id)

        in_function = set()
        for fn in module.functions.values():
            for node in ast.walk(fn):
                in_function.add(id(node))

        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            # jit(f)(...) immediately invoked inside a function body
            if isinstance(node.func, ast.Call) \
                    and core.tail_name(node.func.func) in _JIT_NAMES \
                    and id(node) in in_function:
                yield self.finding(
                    module, node,
                    "`jit(...)(...)` builds a fresh jit wrapper per "
                    "call — every invocation re-traces",
                    hint="build the jitted callable once (module level "
                         "or cached) and reuse it")
                continue
            # lru_cache(maxsize=None)
            if core.tail_name(node.func) == "lru_cache":
                for kw in node.keywords:
                    if kw.arg == "maxsize" \
                            and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is None:
                        yield self.finding(
                            module, node,
                            "`lru_cache(maxsize=None)` — unbounded "
                            "cache; long-running training leaks host "
                            "memory",
                            hint="set a finite maxsize")
            # cache[key] = <jit/lower/compile result>
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Subscript) \
                    and isinstance(node.targets[0].value, ast.Name) \
                    and node.targets[0].value.id in caches - evicted \
                    and isinstance(node.value, ast.Call):
                callee = core.tail_name(node.value.func).lower()
                if any(m in callee for m in _CACHED_BUILD_MARKERS):
                    yield self.finding(
                        module, node,
                        f"unbounded module-level cache "
                        f"`{node.targets[0].value.id}` stores compiled "
                        f"callables with no eviction — value-varying "
                        f"keys grow it every call (ADVICE r5 _jit_cache)",
                        hint="bound it with utils.lru.LRUCache or key "
                             "strictly on shapes/dtypes")
