"""TL001 — host synchronization inside traced code.

``.item()`` / ``.tolist()`` / ``.numpy()`` / ``jax.device_get`` /
``block_until_ready`` on a tracer aborts tracing (ConcretizationError)
or, worse, silently bakes a stale value into the compiled program when
it happens on a closed-over concrete array.  ``bool()/int()/float()``
and ``np.asarray`` on a traced value are flagged only when the receiver
is a formal parameter of the traced function — the conservative subset
we can resolve without type inference.
"""

from __future__ import annotations

import ast

from .. import core

_SYNC_METHODS = {"item", "tolist", "numpy"}
_SYNC_CALLS = {"jax.device_get", "jax.block_until_ready"}
_CASTS = {"bool", "int", "float"}
_NP_TO_HOST = {"numpy.asarray", "numpy.array", "numpy.copy"}


@core.register
class HostSyncRule(core.Rule):
    id = "TL001"
    name = "host-sync-in-trace"
    severity = "error"
    doc = ("host synchronization (.item()/.tolist()/.numpy()/"
           "jax.device_get/bool()/int()/float()/np.asarray on a traced "
           "value) inside a function reachable from "
           "jit/to_static/scan/shard_map")
    hint = ("keep the value on device (jnp ops / lax.cond), or move the "
            "read outside the traced function")

    def check(self, module):
        for fn in module.traced_functions():
            params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f = node.func
                if isinstance(f, ast.Attribute) and not node.args \
                        and f.attr in _SYNC_METHODS:
                    yield self.finding(
                        module, node,
                        f"`.{f.attr}()` in traced `{fn.name}` forces a "
                        f"device→host sync under tracing")
                    continue
                resolved = module.resolve(f)
                if resolved in _SYNC_CALLS:
                    yield self.finding(
                        module, node,
                        f"`{resolved}` in traced `{fn.name}` blocks on "
                        f"device values under tracing")
                    continue
                arg0 = node.args[0] if node.args else None
                on_param = isinstance(arg0, ast.Name) and arg0.id in params
                if isinstance(f, ast.Name) and f.id in _CASTS and on_param:
                    yield self.finding(
                        module, node,
                        f"`{f.id}({arg0.id})` on a parameter of traced "
                        f"`{fn.name}` concretizes the tracer")
                elif resolved in _NP_TO_HOST and on_param:
                    yield self.finding(
                        module, node,
                        f"`{resolved}({arg0.id})` on a parameter of "
                        f"traced `{fn.name}` pulls the value to host")
