"""TL002 — impure traced functions.

A traced function runs ONCE, at trace time; ``print`` fires once and
never again, ``time.time()``/stdlib ``random``/``np.random`` freeze a
single value into the compiled program, and ``global``/``nonlocal``
writes mutate Python state the compiled executable will never see (the
exact hazards ``jit/graph_break.py`` pays exec-based eager interludes
for at runtime — see ADVICE r5 and the PR 2 Global/Nonlocal fallback).
``jax.random`` is functional and explicitly allowed.
"""

from __future__ import annotations

import ast

from .. import core

_CLOCKS = {"time.time", "time.perf_counter", "time.monotonic",
           "time.process_time", "time.time_ns", "time.perf_counter_ns"}


@core.register
class PurityRule(core.Rule):
    id = "TL002"
    name = "impure-trace"
    severity = "error"
    doc = ("side effects inside traced code: print, wall-clock reads, "
           "stdlib/np RNG, global/nonlocal writes — executed once at "
           "trace time, then baked in or silently dropped")
    hint = ("use jax.debug.print / jax.random with an explicit key / "
            "thread state through arguments instead")

    def check(self, module):
        for fn in module.traced_functions():
            for node in ast.walk(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    kw = "global" if isinstance(node, ast.Global) else \
                        "nonlocal"
                    yield self.finding(
                        module, node,
                        f"`{kw} {', '.join(node.names)}` in traced "
                        f"`{fn.name}` — rebinding is invisible to the "
                        f"compiled program",
                        hint="return the new value instead of rebinding")
                    continue
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Name) \
                        and node.func.id == "print":
                    yield self.finding(
                        module, node,
                        f"`print` in traced `{fn.name}` fires once at "
                        f"trace time, never per step",
                        hint="use jax.debug.print (or io_callback)")
                    continue
                resolved = module.resolve(node.func)
                if resolved in _CLOCKS:
                    yield self.finding(
                        module, node,
                        f"`{resolved}()` in traced `{fn.name}` freezes "
                        f"one timestamp into the compiled program",
                        hint="time outside the traced function")
                elif resolved.startswith("random.") \
                        and module.imports.get("random", "") == "random":
                    yield self.finding(
                        module, node,
                        f"stdlib `{resolved}` in traced `{fn.name}` "
                        f"draws once at trace time",
                        hint="use jax.random with a threaded key")
                elif resolved.startswith("numpy.random."):
                    yield self.finding(
                        module, node,
                        f"`{resolved}` in traced `{fn.name}` draws once "
                        f"at trace time",
                        hint="use jax.random with a threaded key")
