"""TL009 — shard_map/pjit PartitionSpec axis-name drift.

TL005 guards collective CALL SITES (``psum(x, "mp")``); this rule
extends the same vocabulary check to the sharding DECLARATIONS that
route arrays onto mesh axes: ``in_specs=``/``out_specs=`` of
``shard_map`` and ``in_shardings=``/``out_shardings=`` of ``pjit``.
A ``P("modelp")`` against a mesh whose axes are ``("dp", "mp")``
fails at trace time at best; under ``check_vma=False`` manual meshes
it can silently replicate a tensor that was meant to be sharded —
costing memory and, for donated buffers, correctness.

The axis vocabulary is shared with TL005 (``*_AXIS`` module constants
plus every mesh ``axis_names=(...)``/``make_mesh((..), (names))``
entry in the scanned tree): a string literal inside a
PartitionSpec/``P(...)`` constructor in those keyword positions that
matches no known axis is drift or a typo.
"""

from __future__ import annotations

import ast

from .. import core
from .tl005_collectives import CollectiveAxisRule

_WRAPPERS = {"shard_map", "pjit", "jit"}   # jit: in_/out_shardings
_SPEC_KWARGS = {"in_specs", "out_specs", "in_shardings", "out_shardings"}
_SPEC_CTORS = {"PartitionSpec", "P"}


@core.register
class PartitionSpecAxisRule(core.Rule):
    id = "TL009"
    name = "partition-spec-axis-drift"
    severity = "warning"
    doc = ("a shard_map/pjit in_specs/out_specs PartitionSpec names an "
           "axis matching no *_AXIS constant or mesh axis_names entry "
           "in the scanned tree")
    hint = ("use the topology constants (parallel/topology.py MP_AXIS "
            "et al.) in PartitionSpecs — or add the new axis to the "
            "mesh that names it")

    def __init__(self):
        self.vocab = set()

    def prepare(self, modules):
        # one vocabulary with TL005: axis constants + mesh axis names
        collector = CollectiveAxisRule()
        collector.prepare(modules)
        self.vocab = set(collector.vocab)
        # make_mesh((2,), ("mp",)) passes names positionally
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Call) \
                        and core.tail_name(node.func) == "make_mesh" \
                        and len(node.args) >= 2 \
                        and isinstance(node.args[1],
                                       (ast.Tuple, ast.List)):
                    for e in node.args[1].elts:
                        if isinstance(e, ast.Constant) \
                                and isinstance(e.value, str):
                            self.vocab.add(e.value)

    def _spec_axis_literals(self, node: ast.AST):
        """(expr, value) string literals inside PartitionSpec/P
        constructors anywhere under ``node``."""
        for sub in ast.walk(node):
            if not (isinstance(sub, ast.Call)
                    and core.tail_name(sub.func) in _SPEC_CTORS):
                continue
            for arg in list(sub.args) + [k.value for k in sub.keywords]:
                elts = arg.elts if isinstance(
                    arg, (ast.Tuple, ast.List)) else [arg]
                for e in elts:
                    if isinstance(e, ast.Constant) \
                            and isinstance(e.value, str):
                        yield e, e.value

    def check(self, module):
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and core.tail_name(node.func) in _WRAPPERS):
                continue
            for kw in node.keywords:
                if kw.arg not in _SPEC_KWARGS:
                    continue
                for expr, value in self._spec_axis_literals(kw.value):
                    if value not in self.vocab:
                        yield self.finding(
                            module, expr,
                            f"{core.tail_name(node.func)} "
                            f"{kw.arg} names axis {value!r} which "
                            "matches no *_AXIS constant or mesh "
                            "axis_names in the scanned tree")
