"""TL006 — silent broad exception swallows.

``except Exception: pass`` hides real failures (a full disk in a
checkpoint writer, a poisoned shared-memory segment in the loader) as
non-events.  The triage contract for core subsystems (checkpoint/, io/,
optimizer/, parallel/): narrow the clause to the exception the code
actually expects, or log-and-continue with an explicit comment; only a
finalizer racing interpreter shutdown (``__del__``) earns an inline
``# tracelint: disable=TL006`` with its justification.
"""

from __future__ import annotations

import ast

from .. import core

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    if core.tail_name(t) in _BROAD:
        return True
    if isinstance(t, ast.Tuple):
        return any(core.tail_name(e) in _BROAD for e in t.elts)
    return False


def _is_silent(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue                      # docstring / ellipsis
        return False
    return True


@core.register
class SilentExceptRule(core.Rule):
    id = "TL006"
    name = "silent-broad-except"
    severity = "warning"
    doc = ("`except Exception:`/`except:`/`except BaseException:` whose "
           "body is only `pass` — the failure disappears without a trace")
    hint = ("narrow to the intended exception type, or log-and-continue "
            "with an explicit comment; suppress (with justification) "
            "only genuine shutdown-race finalizers")

    def check(self, module):
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and _is_silent(node.body):
                what = "bare except" if node.type is None else \
                    f"except {core.dotted_name(node.type) or 'Exception'}"
                yield self.finding(
                    module, node,
                    f"`{what}: pass` silently swallows every failure")
